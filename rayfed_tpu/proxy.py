"""Module-level ``send`` / ``recv`` primitives.

Parity with reference ``fed/barriers.py:418-438``: ``send`` routes through
the party's send proxy and registers the in-flight result with the cleanup
watchdog; ``recv`` returns a future that parks until the owner's push
arrives.
"""

from __future__ import annotations

from typing import Any

from rayfed_tpu.executor import LocalRef
from rayfed_tpu.runtime import Runtime, get_runtime


def send_on_runtime(
    runtime: Runtime,
    dest_party: str,
    data: Any,
    upstream_seq_id: Any,
    downstream_seq_id: Any,
    stream: Any = None,
    round_tag: Any = None,
    epoch_tag: Any = None,
    quant_meta: Any = None,
) -> LocalRef:
    """``stream``: stable stream name enabling the transport's per-peer
    delta cache (ship only changed chunks — see TransportClient).
    ``round_tag``: federated round index stamped into the frame metadata
    (``wire.ROUND_TAG_KEY``) so in-flight pipelined rounds stay
    attributable — see :meth:`TransportManager.send`.  ``epoch_tag``:
    roster epoch stamped into the metadata (``wire.EPOCH_TAG_KEY``;
    cross-epoch frames are rejected loudly by the receiver).
    ``quant_meta``: shared-quantization-grid descriptor stamped into the
    metadata (``wire.QUANT_GRID_KEY``) for compressed-domain payloads."""
    if runtime.send_proxy is None:
        raise RuntimeError("transport not started; call fed.init() first")
    result_ref = runtime.send_proxy.send(
        dest_party=dest_party,
        data=data,
        upstream_seq_id=upstream_seq_id,
        downstream_seq_id=downstream_seq_id,
        stream=stream,
        round_tag=round_tag,
        epoch_tag=epoch_tag,
        quant_meta=quant_meta,
    )
    if runtime.cleanup_manager is not None:
        runtime.cleanup_manager.push_to_sending(result_ref)
    return result_ref


def send_many_on_runtime(
    runtime: Runtime,
    dest_parties,
    data: Any,
    upstream_seq_id: Any,
    downstream_seq_id: Any,
    stream: Any = None,
    round_tag: Any = None,
    epoch_tag: Any = None,
    quant_meta: Any = None,
    blob_offer: bool = False,
) -> dict:
    """Broadcast fan-out: ONE payload encode shared by every destination.

    The transport encodes (and checksums, and device→host fetches) the
    value once and pushes it to all parties concurrently — the owner's
    broadcast-on-get cost becomes max(per-peer wire time), not
    N × (encode + wire).  Each per-party result ref registers with the
    cleanup watchdog exactly like a single send.

    ``blob_offer=True``: large immutable payloads may ship as
    fingerprint handles resolved pull-on-demand by the receivers — see
    :meth:`TransportManager.send_many`.
    """
    if runtime.send_proxy is None:
        raise RuntimeError("transport not started; call fed.init() first")
    refs = runtime.send_proxy.send_many(
        dest_parties=dest_parties,
        data=data,
        upstream_seq_id=upstream_seq_id,
        downstream_seq_id=downstream_seq_id,
        stream=stream,
        round_tag=round_tag,
        epoch_tag=epoch_tag,
        quant_meta=quant_meta,
        blob_offer=blob_offer,
    )
    if runtime.cleanup_manager is not None:
        for ref in refs.values():
            runtime.cleanup_manager.push_to_sending(ref)
    return refs


def recv_on_runtime(
    runtime: Runtime,
    src_party: str,
    upstream_seq_id: Any,
    curr_seq_id: Any,
) -> LocalRef:
    if runtime.recv_proxy is None:
        raise RuntimeError("transport not started; call fed.init() first")
    return runtime.recv_proxy.recv(
        src_party=src_party,
        upstream_seq_id=upstream_seq_id,
        downstream_seq_id=curr_seq_id,
    )


def send(dest_party: str, data: Any, upstream_seq_id: Any, downstream_seq_id: Any):
    return send_on_runtime(
        get_runtime(), dest_party, data, upstream_seq_id, downstream_seq_id
    )


def recv(party: str, src_party: str, upstream_seq_id: Any, curr_seq_id: Any):
    assert party, "Party can not be None."
    return recv_on_runtime(get_runtime(), src_party, upstream_seq_id, curr_seq_id)
