"""Federated flight recorder — cross-party round tracing.

Diagnosing *why* a round was slow (or which party bounded the wall)
used to mean reading N party logs and mentally joining them by round
number.  This module is the join: a bounded, thread-safe ring of
structured **span records** fed by the named seams that already exist —
transport send phases, server delivery, mailbox waits, aggregation
fold/finalize, quorum cutoffs/failovers, ring/hierarchy phase
boundaries, overlap's hidden-comms window, object-plane pulls,
checkpoint save/restore — plus the chaos harness, so an injected
partition appears on the SAME timeline as the failover it caused.

Record shape (:data:`SPAN_FIELDS`)::

    (party, round, epoch, phase, peer, stream, nbytes,
     t_start, dur_s, outcome, detail)

``t_start`` is wall-clock epoch seconds (``time.time()``) so records
from different parties can be merged onto one timeline; ``dur_s`` is a
monotonic-clock duration.  ``phase`` is a dotted name whose first
segment is the subsystem (``wire.send``, ``agg.finalize``,
``quorum.failover``, ``chaos.partition`` ...); ``outcome`` is ``"ok"``
unless the instrumented operation failed/was cut off; ``detail`` is a
small JSON-safe dict (stage breakdowns, member sets, fault ops).

Cost discipline (the chaos-hook contract): with no recorder installed
every emission helper is ONE module-global read.  Armed, an emission is
a deque append under a lock held for exactly that append — never a
sleep, never I/O — so a span write from the transport's receive event
loop cannot stall frames (the ``chaos.fire_nonblocking`` discipline).

Arming:

- ``RAYFED_TRACE=1`` in the environment (picked up by ``fed.init`` via
  :func:`maybe_install_from_env`, like ``RAYFED_CHAOS``), or
- ``JobConfig.trace = True``, or
- :func:`install` directly from tests/benches.

Cross-party collection: :func:`rayfed_tpu.api.trace_collect` pulls each
peer's ring window over the existing transport (an observer-consumed
request frame + a nonce-keyed DATA reply — the BLOB_GET shape), aligns
clocks with the NTP-style offset estimated from the request/reply round
trip (error bound ≤ RTT/2, see :func:`estimate_clock_offset`), and
merges everything into one timeline.  Renderers: :func:`to_trace_events`
(Chrome/Perfetto ``trace_event`` JSON) and ``tool/trace_report.py``
(text critical-path round reports).  See
``docs/source/observability.rst``.
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# Version of the trace-collection protocol semantics: the request /
# reply-metadata schemas, the record field order, and the clock-offset
# estimation contract.  Like OBJECT_PLANE_VERSION this is a
# payload-level knob: bumping it re-pins ``tool/wire_format.lock``
# WITHOUT a WIRE_FORMAT_VERSION bump — the frame layout is untouched.
TELEMETRY_VERSION = 1

# Field order of one span record — the single cross-party contract for
# both the in-memory ring and the wire encoding (records travel as
# field LISTS in this order, not dicts, to keep reply payloads small).
SPAN_FIELDS = (
    "party", "round", "epoch", "phase", "peer", "stream", "nbytes",
    "t_start", "dur_s", "outcome", "detail",
)

SpanRecord = collections.namedtuple("SpanRecord", SPAN_FIELDS)

DEFAULT_TRACE_CAPACITY = 16384

ENV_VAR = "RAYFED_TRACE"


class FlightRecorder:
    """Bounded thread-safe ring of :class:`SpanRecord` (one per process,
    like the chaos schedule; every record carries its acting ``party``
    so in-process multi-party simulations attribute correctly)."""

    def __init__(
        self, party: Optional[str] = None,
        capacity: int = DEFAULT_TRACE_CAPACITY,
    ) -> None:
        self.party = party
        self.capacity = int(capacity)
        self._dq: collections.deque = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._total = 0  # monotonic append count (ring may evict)
        self.t_armed = time.time()

    def emit(
        self,
        phase: str,
        *,
        t_start: Optional[float] = None,
        dur_s: float = 0.0,
        round: Optional[int] = None,
        epoch: Optional[int] = None,
        peer: Optional[str] = None,
        stream: Optional[str] = None,
        nbytes: int = 0,
        outcome: str = "ok",
        detail: Optional[Dict[str, Any]] = None,
        party: Optional[str] = None,
    ) -> None:
        """Append one record.  Lock held for the append only — callable
        from any thread including the transport event loop.  Never
        raises: a diagnostic must not be able to fail a round, so a
        malformed field degrades to a ``bad-record`` marker instead."""
        try:
            rec = SpanRecord(
                party=party if party is not None else self.party,
                round=None if round is None else int(round),
                epoch=None if epoch is None else int(epoch),
                phase=str(phase),
                peer=peer,
                stream=stream,
                nbytes=int(nbytes),
                t_start=(
                    float(t_start) if t_start is not None else time.time()
                ),
                dur_s=float(dur_s),
                outcome=str(outcome),
                detail=detail,
            )
        except Exception as exc:
            rec = SpanRecord(
                party=self.party, round=None, epoch=None, phase=str(phase),
                peer=None, stream=None, nbytes=0, t_start=time.time(),
                dur_s=0.0, outcome="bad-record",
                detail={"error": repr(exc)},
            )
        with self._lock:
            self._dq.append(rec)
            self._total += 1

    def records(
        self, rounds: Any = None, party: Optional[str] = None,
    ) -> List[SpanRecord]:
        """Snapshot of the ring (oldest first).  ``rounds`` filters by
        round tag: an int keeps that round, a ``(lo, hi)`` pair keeps
        the inclusive range — records carrying NO round tag (mailbox
        waits, chaos wire faults, health events) are always kept, since
        a window without its untagged context would hide exactly the
        cross-cutting records the merge exists for."""
        with self._lock:
            recs = list(self._dq)
        if party is not None:
            recs = [r for r in recs if r.party == party]
        if rounds is None:
            return recs
        if isinstance(rounds, int):
            lo = hi = int(rounds)
        else:
            lo, hi = int(rounds[0]), int(rounds[1])
        return [
            r for r in recs if r.round is None or lo <= r.round <= hi
        ]

    def resize(self, capacity: int) -> None:
        """Rebound the ring, KEEPING the newest records that fit —
        ``fed.init(trace_capacity=)`` against an already-armed (e.g.
        env-armed) recorder must honor the explicit request instead of
        silently keeping the old bound."""
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"trace capacity must be >= 1, got {capacity}")
        with self._lock:
            if capacity == self.capacity:
                return
            self._dq = collections.deque(self._dq, maxlen=capacity)
            self.capacity = capacity

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._total

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n, total = len(self._dq), self._total
        return {
            "trace_armed": True,
            "trace_records": n,
            "trace_total_recorded": total,
            "trace_dropped": max(0, total - n),
            "trace_capacity": self.capacity,
        }


# ---------------------------------------------------------------------------
# Process-global arming (the chaos.install pattern)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FlightRecorder] = None


def install(
    party: Optional[str] = None,
    capacity: int = DEFAULT_TRACE_CAPACITY,
) -> FlightRecorder:
    """Arm the flight recorder process-wide; returns it.  Re-installing
    replaces the ring (tests that want a fresh window)."""
    global _ACTIVE
    _ACTIVE = FlightRecorder(party=party, capacity=capacity)
    return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def installed() -> Optional[FlightRecorder]:
    return _ACTIVE


def active() -> Optional[FlightRecorder]:
    """The armed recorder or ``None`` — ONE global read.  Hot call
    sites hold the return value and skip all argument construction when
    disarmed."""
    return _ACTIVE


def armed() -> bool:
    return _ACTIVE is not None


def maybe_install_from_env(party: Optional[str] = None):
    """Arm from ``RAYFED_TRACE=1`` if set (``fed.init`` calls this, so
    subprocess harnesses arm via env like chaos).  Idempotent: an
    already-armed recorder is kept, but a recorder armed WITHOUT a
    party adopts ``party`` — env-armed rings exist before fed.init
    knows who this party is."""
    import os

    if _ACTIVE is not None:
        if party is not None and _ACTIVE.party is None:
            _ACTIVE.party = party
        return _ACTIVE
    raw = os.environ.get(ENV_VAR, "")
    if raw not in ("1", "true", "on", "yes"):
        return None
    cap = int(os.environ.get("RAYFED_TRACE_CAPACITY", DEFAULT_TRACE_CAPACITY))
    return install(party=party, capacity=cap)


def emit(phase: str, **kw: Any) -> None:
    """Module-level emission — a no-op (one global read) when disarmed."""
    rec = _ACTIVE
    if rec is None:
        return
    rec.emit(phase, **kw)


def event(phase: str, **kw: Any) -> None:
    """A zero-duration record stamped now (cutoffs, failovers, chaos)."""
    rec = _ACTIVE
    if rec is None:
        return
    rec.emit(phase, t_start=time.time(), dur_s=0.0, **kw)


def phase_spanner(prefix: str, **static_kw: Any):
    """The topology drivers' phase-boundary span helper: returns
    ``mark(name, t0, **kw) -> now_p`` emitting ``<prefix>.<name>``
    anchored by back-dating ``time.time()`` with the ``perf_counter``
    delta since ``t0`` (ONE anchoring rule for ring/hierarchy/future
    topologies, not N hand-rolled copies).  The armed check happens
    ONCE here — disarmed, the returned mark is a bare perf_counter
    read with zero argument construction."""
    rec = _ACTIVE
    if rec is None:
        return lambda name, t0, **kw: time.perf_counter()

    def mark(name: str, t0: float, **kw: Any) -> float:
        now_p = time.perf_counter()
        rec.emit(
            f"{prefix}.{name}",
            t_start=time.time() - (now_p - t0),
            dur_s=now_p - t0, **static_kw, **kw,
        )
        return now_p

    return mark


@contextlib.contextmanager
def span(phase: str, **kw: Any):
    """Time a block as one span.  Disarmed cost: one global read and a
    generator frame — use only at non-hot sites (per round / per pull /
    per checkpoint, not per frame)."""
    rec = _ACTIVE
    if rec is None:
        yield
        return
    t_wall = time.time()
    t0 = time.perf_counter()
    try:
        yield
    except BaseException:
        rec.emit(
            phase, t_start=t_wall, dur_s=time.perf_counter() - t0,
            outcome="error", **kw,
        )
        raise
    rec.emit(phase, t_start=t_wall, dur_s=time.perf_counter() - t0, **kw)


# ---------------------------------------------------------------------------
# Trace-collection schemas — single producers, fingerprinted by
# tool/check_wire_format.py (cross-party contracts riding ordinary
# frame metadata / payloads; no frame-layout change)
# ---------------------------------------------------------------------------


class TelemetryError(RuntimeError):
    """A trace collection could not complete or a schema was malformed."""


def make_trace_request(
    reply_key: str, rounds: Any = None, t_send: Optional[float] = None,
) -> Dict[str, Any]:
    """The ``wire.TRACE_GET_KEY`` frame-metadata value: asks a peer for
    its ring window, naming the reply rendezvous key the requester is
    already parked on (the BLOB_GET shape).  ``rounds``: None (whole
    ring), an int, or an inclusive ``[lo, hi]`` pair."""
    rnd: Optional[List[int]] = None
    if rounds is not None:
        if isinstance(rounds, int):
            rnd = [int(rounds), int(rounds)]
        else:
            rnd = [int(rounds[0]), int(rounds[1])]
    return {
        "v": int(TELEMETRY_VERSION),
        "rk": str(reply_key),
        "rnd": rnd,
        "ts": float(t_send if t_send is not None else time.time()),
    }


def check_trace_request(req: Any) -> Dict[str, Any]:
    if not isinstance(req, dict) or not isinstance(req.get("rk"), str):
        raise TelemetryError(f"malformed trace request: {req!r}")
    rnd = req.get("rnd")
    if rnd is not None and (
        not isinstance(rnd, (list, tuple)) or len(rnd) != 2
    ):
        raise TelemetryError(f"malformed trace request rounds: {req!r}")
    return {
        "v": int(req.get("v", 1)),
        "rk": req["rk"],
        "rnd": None if rnd is None else [int(rnd[0]), int(rnd[1])],
        "ts": float(req.get("ts", 0.0)),
    }


def make_trace_reply_meta(
    party: str, count: int, t_wall: Optional[float] = None,
    armed: bool = True, err: Optional[str] = None,
) -> Dict[str, Any]:
    """The ``wire.TRACE_PUT_KEY`` frame-metadata value: stamps a reply
    with the serving party, its record count, its wall clock at serve
    time (``tw`` — the clock-offset estimate's peer sample), and
    whether its recorder was armed at all (a disarmed peer replies an
    EMPTY window, loudly distinguishable from a quiet armed one).
    ``err`` names a serve-side failure (malformed request, encode
    error): the server replies it instead of staying silent, so the
    collector fails FAST with the real reason instead of waiting out
    its per-peer timeout (the object plane's holder-miss notice
    shape)."""
    return {
        "v": int(TELEMETRY_VERSION),
        "party": str(party),
        "n": int(count),
        "tw": float(t_wall if t_wall is not None else time.time()),
        "armed": bool(armed),
        "err": None if err is None else str(err),
    }


def check_trace_reply_meta(rep: Any) -> Dict[str, Any]:
    if not isinstance(rep, dict) or not isinstance(rep.get("party"), str):
        raise TelemetryError(f"malformed trace reply metadata: {rep!r}")
    err = rep.get("err")
    return {
        "v": int(rep.get("v", 1)),
        "party": rep["party"],
        "n": int(rep.get("n", 0)),
        "tw": float(rep.get("tw", 0.0)),
        "armed": bool(rep.get("armed", False)),
        "err": None if err is None else str(err),
    }


def _json_safe(value: Any) -> Any:
    """Coerce a detail payload to JSON-safe primitives (the wire
    encoding refuses nothing — a diagnostic must never fail a round)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set, frozenset)):
        return [_json_safe(v) for v in value]
    return repr(value)


def record_to_list(rec: SpanRecord) -> list:
    """One record as a field LIST in :data:`SPAN_FIELDS` order — the
    wire/report interchange form."""
    return [
        rec.party, rec.round, rec.epoch, rec.phase, rec.peer, rec.stream,
        rec.nbytes, rec.t_start, rec.dur_s, rec.outcome,
        _json_safe(rec.detail),
    ]


def record_from_list(row: Sequence[Any]) -> SpanRecord:
    if len(row) != len(SPAN_FIELDS):
        raise TelemetryError(
            f"trace record carries {len(row)} fields, expected "
            f"{len(SPAN_FIELDS)} ({SPAN_FIELDS})"
        )
    return SpanRecord(*row)


def encode_records(records: Iterable[SpanRecord]) -> bytes:
    """The trace reply's payload bytes: compact JSON of field lists."""
    doc = {
        "v": int(TELEMETRY_VERSION),
        "fields": list(SPAN_FIELDS),
        "records": [record_to_list(r) for r in records],
    }
    return json.dumps(doc, separators=(",", ":")).encode()


def decode_records(data: Any) -> List[SpanRecord]:
    doc = json.loads(bytes(data))
    if int(doc.get("v", 1)) > TELEMETRY_VERSION:
        raise TelemetryError(
            f"trace payload uses telemetry protocol v{doc.get('v')}; "
            f"this party understands up to v{TELEMETRY_VERSION}"
        )
    if doc.get("fields") != list(SPAN_FIELDS):
        raise TelemetryError(
            f"trace payload field order {doc.get('fields')} != "
            f"{list(SPAN_FIELDS)}"
        )
    return [record_from_list(row) for row in doc.get("records", [])]


# ---------------------------------------------------------------------------
# Clock alignment + merge
# ---------------------------------------------------------------------------


def estimate_clock_offset(
    t_send: float, t_recv: float, t_peer: float,
) -> Dict[str, float]:
    """NTP-style one-exchange offset estimate from the trace-collection
    round trip itself (a control-frame exchange, the same machinery the
    health monitor's pings ride).

    ``offset_s`` is the peer's clock minus ours, assuming the peer
    stamped ``t_peer`` halfway through the round trip; mapping a peer
    timestamp onto our timeline is ``t_local = t_peer_stamp −
    offset_s``.  The documented error bound is ``rtt/2`` (the reply
    could have spent the whole round trip on either leg) — with
    loopback/datacenter RTTs of 0.1–2 ms, far finer than the
    millisecond-scale spans the report reasons about.
    """
    rtt = max(0.0, float(t_recv) - float(t_send))
    offset = float(t_peer) - (float(t_send) + float(t_recv)) / 2.0
    return {"offset_s": offset, "rtt_s": rtt, "bound_s": rtt / 2.0}


def merge_records(
    party_records: Dict[str, List[SpanRecord]],
    clock_offsets: Optional[Dict[str, Dict[str, float]]] = None,
) -> List[Dict[str, Any]]:
    """One timeline: every record as a dict with ``t_start`` mapped
    onto the COLLECTOR's clock (peer timestamps shifted by the
    estimated offset) and ``party`` filled from the map key when the
    record itself carries none, sorted by adjusted start time."""
    offsets = clock_offsets or {}
    merged: List[Dict[str, Any]] = []
    for party, recs in party_records.items():
        off = float(offsets.get(party, {}).get("offset_s", 0.0))
        for rec in recs:
            d = dict(zip(SPAN_FIELDS, record_to_list(rec)))
            if d["party"] is None:
                d["party"] = party
            d["t_start"] = float(d["t_start"]) - off
            merged.append(d)
    merged.sort(key=lambda d: d["t_start"])
    return merged


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace_event export
# ---------------------------------------------------------------------------


def to_trace_events(
    merged: Sequence[Dict[str, Any]],
    clock_offsets: Optional[Dict[str, Dict[str, float]]] = None,
) -> Dict[str, Any]:
    """Chrome/Perfetto ``trace_event`` JSON for a merged timeline
    (:func:`merge_records` output, or any sequence of record dicts).

    One *process* per party (named via ``process_name`` metadata
    events), one *thread* per phase family (the dotted prefix:
    ``wire``, ``agg``, ``quorum`` ...).  Spans with a duration are
    complete ("X") events; zero-duration records are instants ("i").
    Timestamps are microseconds relative to the earliest record, so
    the timeline opens at t=0 in the Perfetto UI.
    """
    events: List[Dict[str, Any]] = []
    parties = sorted({str(d.get("party")) for d in merged})
    pid_of = {p: i + 1 for i, p in enumerate(parties)}
    tids: Dict[Tuple[str, str], int] = {}
    t0 = min((float(d["t_start"]) for d in merged), default=0.0)
    for p in parties:
        events.append({
            "name": "process_name", "ph": "M", "pid": pid_of[p], "tid": 0,
            "args": {"name": p},
        })
        off = (clock_offsets or {}).get(p)
        if off:
            events.append({
                "name": "clock_sync_bound", "ph": "M", "pid": pid_of[p],
                "tid": 0, "args": {k: round(v, 6) for k, v in off.items()},
            })
    for d in merged:
        p = str(d.get("party"))
        cat = str(d.get("phase", "")).split(".", 1)[0] or "misc"
        key = (p, cat)
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == p]) + 1
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid_of[p],
                "tid": tids[key], "args": {"name": cat},
            })
        args = {
            k: d.get(k)
            for k in ("round", "epoch", "peer", "stream", "outcome")
            if d.get(k) is not None
        }
        if d.get("nbytes"):
            args["nbytes"] = d["nbytes"]
        if d.get("detail") is not None:
            args["detail"] = _json_safe(d["detail"])
        ev: Dict[str, Any] = {
            "name": str(d.get("phase")),
            "cat": cat,
            "pid": pid_of[p],
            "tid": tids[key],
            "ts": round((float(d["t_start"]) - t0) * 1e6, 3),
            "args": args,
        }
        dur = float(d.get("dur_s") or 0.0)
        if dur > 0.0:
            ev["ph"] = "X"
            ev["dur"] = round(dur * 1e6, 3)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
