"""In-party execution substrate — replaces Ray tasks/actors/object store.

The reference delegates local execution to Ray (``fed/api.py:294-297``,
``fed/_private/fed_actor.py:66-70``): every ``fed.remote`` call becomes a
``ray.remote`` task in a worker *process*, and values flow through the
plasma object store.  On TPU that model is wrong: a party owns exactly one
set of local devices, the expensive work is XLA-compiled computation whose
dispatch is already asynchronous, and moving arrays through an object store
would force device→host copies.

So the substrate here is deliberately in-process:

- :class:`LocalRef` — the in-party future (replaces ``ray.ObjectRef``).
- :class:`TaskExecutor` — a thread pool that resolves *top-level* LocalRef
  arguments to values and invokes the (usually jit-compiled) callable.
  JAX owns device parallelism; threads only overlap host work, transfers
  and dispatch.  Nested LocalRefs inside containers are passed through
  un-resolved, matching Ray's argument semantics that the reference relies
  on (see ``tests/test_pass_fed_objects_in_containers_in_normal_tasks.py``
  in the reference: the consumer calls ``fed.get`` inside the task body).
- :class:`ActorInstance` — a stateful object bound to a single-thread
  executor, so method calls execute serially in submission order (Ray
  actor semantics without a process boundary).
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
from typing import Any, Callable, Optional, Sequence

logger = logging.getLogger(__name__)


class LocalRef:
    """A future for a value produced inside this party.

    Wraps :class:`concurrent.futures.Future`.  ``resolve()`` blocks until
    the value is available (the analogue of ``ray.get`` on an ObjectRef).
    """

    __slots__ = ("_future",)

    def __init__(self, future: Optional[concurrent.futures.Future] = None) -> None:
        self._future = future if future is not None else concurrent.futures.Future()

    @classmethod
    def from_value(cls, value: Any) -> "LocalRef":
        ref = cls()
        ref._future.set_result(value)
        return ref

    def resolve(self, timeout: Optional[float] = None) -> Any:
        return self._future.result(timeout=timeout)

    def done(self) -> bool:
        return self._future.done()

    def exception(self, timeout: Optional[float] = None):
        return self._future.exception(timeout=timeout)

    def set_result(self, value: Any) -> None:
        self._future.set_result(value)

    def set_exception(self, exc: BaseException) -> None:
        self._future.set_exception(exc)

    def add_done_callback(self, fn: Callable[["LocalRef"], None]) -> None:
        self._future.add_done_callback(lambda _f: fn(self))

    def then(
        self,
        fn: Callable[[Any], Any],
        executor: Optional[concurrent.futures.Executor] = None,
    ) -> "LocalRef":
        """Chain ``fn`` onto this ref without parking a thread.

        Returns a new LocalRef resolving to ``fn(value)``; an exception
        (from this ref or from ``fn``) propagates to the returned ref.

        THREADING CONTRACT: without ``executor``, ``fn`` runs inline on
        whichever thread RESOLVES this ref — a task-pool worker, the
        transport event loop, or the caller itself when the ref is
        already done.  Callbacks must therefore be quick and non-blocking
        (a slow callback on the event loop stalls every connection), and
        must not assume any particular thread identity.  Pass
        ``executor`` to move the work — e.g. the transport decodes
        received payloads on its codec pool rather than the event loop.
        """
        out = LocalRef()

        def _run(value: Any) -> None:
            try:
                out.set_result(fn(value))
            # fedlint: disable=FED004 — transferred, not swallowed: KI/SE resolve the chained LocalRef and re-raise at resolve()
            except BaseException as e:
                out.set_exception(e)

        def _cb(ref: "LocalRef") -> None:
            try:
                exc = ref.exception()
            # fedlint: disable=FED004 — transferred, not swallowed: the cancellation/KI resolves the chained ref and re-raises at resolve()
            except BaseException as e:
                # exception() on a CANCELLED future raises instead of
                # returning (e.g. shutdown cancelling a parked recv) —
                # the chained ref must still resolve or callers hang.
                out.set_exception(e)
                return
            if exc is not None:
                out.set_exception(exc)
                return
            if executor is not None:
                try:
                    executor.submit(_run, ref.resolve())
                # fedlint: disable=FED004 — transferred, not swallowed: a shutdown-pool submit failure resolves the chained ref
                except BaseException as e:  # pool shut down mid-flight
                    out.set_exception(e)
            else:
                _run(ref.resolve())

        self.add_done_callback(_cb)
        return out

    def __repr__(self) -> str:  # pragma: no cover
        return f"LocalRef(done={self._future.done()})"


def resolve_local_refs(refs: Sequence[LocalRef], timeout: Optional[float] = None):
    return [r.resolve(timeout=timeout) for r in refs]


def is_local_ref(obj: Any) -> bool:
    return isinstance(obj, LocalRef)


def is_local_refs(objects: Any) -> bool:
    """True if ``objects`` is a LocalRef or a non-empty list of LocalRefs.

    Parity with reference ``fed/utils.py:64-74`` (``is_ray_object_refs``)
    used for the ``fed.get`` passthrough path.
    """
    if isinstance(objects, LocalRef):
        return True
    if isinstance(objects, list) and objects:
        return all(isinstance(o, LocalRef) for o in objects)
    return False


def _materialize_arg(arg: Any) -> Any:
    """Resolve a *top-level* argument if it is a LocalRef.

    Containers are not traversed: a LocalRef nested inside a list stays a
    LocalRef, which the task body resolves via ``fed.get`` (matches Ray's
    top-level-only ObjectRef resolution that the reference depends on).
    """
    if isinstance(arg, LocalRef):
        return arg.resolve()
    return arg


class TaskExecutor:
    """Thread-pool dispatch of party-local work.

    ``bind_runtime_fn`` is called in each worker thread before executing a
    task body so that ``fed.*`` calls made *inside* tasks see the right
    per-party runtime (required for multi-party-in-one-process simulation
    and for ``fed.get`` inside task bodies).
    """

    def __init__(
        self,
        max_workers: int = 16,
        thread_name_prefix: str = "rayfed-worker",
        bind_runtime_fn: Optional[Callable[[], None]] = None,
    ) -> None:
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix=thread_name_prefix
        )
        self._bind_runtime_fn = bind_runtime_fn
        self._shutdown = False

    def submit(
        self,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        num_returns: int = 1,
        name: Optional[str] = None,
    ):
        """Submit ``fn(*args, **kwargs)``; returns LocalRef or list of them.

        ``name`` (defaults to the callable's ``__name__``) is stamped
        onto the worker thread for the task's duration and into the
        exception log line, so a traceback or a thread dump of a hung
        party names the fed task instead of an anonymous
        ``rayfed-worker-3``.
        """
        if self._shutdown:
            raise RuntimeError("TaskExecutor has been shut down")
        task_name = name or getattr(fn, "__name__", None) or repr(fn)

        def _run():
            if self._bind_runtime_fn is not None:
                self._bind_runtime_fn()
            thread = threading.current_thread()
            base_name = thread.name
            thread.name = f"{base_name}[{task_name}]"
            try:
                resolved_args = tuple(_materialize_arg(a) for a in args)
                resolved_kwargs = {
                    k: _materialize_arg(v) for k, v in kwargs.items()
                }
                return fn(*resolved_args, **resolved_kwargs)
            except BaseException as e:
                # The exception also travels to the LocalRef; this log
                # line is the one place that pairs it with the task name.
                logger.debug("fed task %r failed: %r", task_name, e)
                raise
            finally:
                thread.name = base_name

        future = self._pool.submit(_run)
        if num_returns == 1:
            return LocalRef(future)
        return _split_future(future, num_returns)

    def submit_resolved(self, fn: Callable, *args, **kwargs) -> LocalRef:
        """Submit without argument materialization (internal use)."""

        def _run():
            if self._bind_runtime_fn is not None:
                self._bind_runtime_fn()
            return fn(*args, **kwargs)

        return LocalRef(self._pool.submit(_run))

    def shutdown(self, wait: bool = True) -> None:
        self._shutdown = True
        self._pool.shutdown(wait=wait)


class CommsLane(TaskExecutor):
    """A dedicated single-thread lane for cross-party comms orchestration.

    The pipelined round engine (:mod:`rayfed_tpu.fl.overlap`) hands each
    round's push + aggregation to this lane and immediately returns to
    local compute.  The lane is deliberately NOT the task executor and
    NOT the transport codec pool:

    - Task-pool threads run training bodies; a blocking multi-second
      ``streaming_aggregate`` wait parked there would steal a worker
      from (and at pool saturation, deadlock behind) the very training
      work the overlap is supposed to hide it under.
    - Codec-pool threads encode/decode payload bytes; the aggregation
      wait must be free to *consume* codec work, so waiting on the codec
      pool could self-deadlock.

    One thread, not a pool: round *k+1*'s aggregate depends on round
    *k*'s anyway (the DGA correction consumes it), so comms jobs are
    inherently serial — a single lane makes that ordering structural
    instead of relying on callers to chain futures.

    ``bind_runtime_fn`` is invoked on the lane thread before each job so
    ``fed.*``/``get_runtime()`` calls made inside resolve to the owning
    party's runtime (the same contract as :class:`TaskExecutor`).

    Implementation-wise this IS a one-worker :class:`TaskExecutor` — the
    isolation argument above is about not sharing the *instances*, not
    about needing different machinery — so it subclasses rather than
    duplicating the pool/bind/shutdown plumbing.
    """

    def __init__(
        self,
        name: str = "rayfed-comms",
        bind_runtime_fn: Optional[Callable[[], None]] = None,
    ) -> None:
        super().__init__(
            max_workers=1, thread_name_prefix=name,
            bind_runtime_fn=bind_runtime_fn,
        )

    def submit(self, fn: Callable, *args, **kwargs) -> LocalRef:
        """Queue ``fn(*args, **kwargs)`` on the lane; returns a LocalRef.

        (Simpler signature than :meth:`TaskExecutor.submit` — lane jobs
        pass their arguments pre-resolved and need no name stamping.)
        """
        if self._shutdown:
            raise RuntimeError("CommsLane has been shut down")
        return self.submit_resolved(fn, *args, **kwargs)


def _split_future(
    future: concurrent.futures.Future, num_returns: int
) -> list[LocalRef]:
    """Fan a single future producing a sequence into ``num_returns`` refs."""
    children = [LocalRef() for _ in range(num_returns)]

    def _distribute(parent: concurrent.futures.Future) -> None:
        exc = parent.exception()
        if exc is not None:
            for child in children:
                child.set_exception(exc)
            return
        values = parent.result()
        try:
            values = list(values)
        except TypeError:
            for child in children:
                child.set_exception(
                    TypeError(
                        f"task declared num_returns={num_returns} but returned "
                        f"non-iterable {type(values).__name__}"
                    )
                )
            return
        if len(values) != num_returns:
            for child in children:
                child.set_exception(
                    ValueError(
                        f"task declared num_returns={num_returns} but returned "
                        f"{len(values)} values"
                    )
                )
            return
        for child, value in zip(children, values):
            child.set_result(value)

    future.add_done_callback(_distribute)
    return children


class ActorInstance:
    """A party-local stateful actor: one object + one serial executor.

    Method calls run one-at-a-time in submission order on a dedicated
    thread, reproducing Ray's default actor concurrency semantics.  State
    (e.g. sharded model params as ``jax.Array``s) stays on-device between
    calls — no object-store round trips.
    """

    def __init__(
        self,
        cls: type,
        cls_args: tuple,
        cls_kwargs: dict,
        bind_runtime_fn: Optional[Callable[[], None]] = None,
        name: str = "actor",
    ) -> None:
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"rayfed-actor-{name}"
        )
        self._bind_runtime_fn = bind_runtime_fn
        self._instance: Any = None
        self._killed = False
        self._lock = threading.Lock()

        def _construct():
            if self._bind_runtime_fn is not None:
                self._bind_runtime_fn()
            resolved_args = tuple(_materialize_arg(a) for a in cls_args)
            resolved_kwargs = {k: _materialize_arg(v) for k, v in cls_kwargs.items()}
            self._instance = cls(*resolved_args, **resolved_kwargs)
            return None

        self._ready_ref = LocalRef(self._pool.submit(_construct))

    @property
    def ready_ref(self) -> LocalRef:
        return self._ready_ref

    def call_method(
        self, method_name: str, args: tuple, kwargs: dict, num_returns: int = 1
    ):
        with self._lock:
            if self._killed:
                raise RuntimeError("actor has been killed")

            def _run():
                if self._bind_runtime_fn is not None:
                    self._bind_runtime_fn()
                # Surface constructor failure on first method call.
                self._ready_ref.resolve()
                resolved_args = tuple(_materialize_arg(a) for a in args)
                resolved_kwargs = {
                    k: _materialize_arg(v) for k, v in kwargs.items()
                }
                method = getattr(self._instance, method_name)
                return method(*resolved_args, **resolved_kwargs)

            future = self._pool.submit(_run)
        if num_returns == 1:
            return LocalRef(future)
        return _split_future(future, num_returns)

    def kill(self) -> None:
        with self._lock:
            self._killed = True
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._instance = None
