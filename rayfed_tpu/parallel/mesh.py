"""Device-mesh construction for a party's local TPU slice.

Axis-name conventions used across the framework (models, sharding
strategies, ring attention, pipeline):

- ``dp``   — data parallel (batch split; gradients all-reduced)
- ``fsdp`` — fully-sharded data parallel (params sharded over this axis)
- ``tp``   — tensor/model parallel (matmul contracting or feature dims)
- ``sp``   — sequence/context parallel (ring attention / Ulysses)
- ``ep``   — expert parallel (MoE experts spread over this axis)
- ``pp``   — pipeline parallel (layer stages)

``create_mesh({'dp': 2, 'tp': 4})`` builds a Mesh over the locally visible
devices.  A trailing axis may be -1 to absorb the remaining devices.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"
AXIS_PP = "pp"

STANDARD_AXES = (AXIS_DP, AXIS_FSDP, AXIS_TP, AXIS_SP, AXIS_EP, AXIS_PP)


def create_mesh(
    shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a named Mesh over this party's devices.

    ``shape`` maps axis name → size, in the order given (insertion order is
    the device-grid order — put the most-communicating axis last so it
    lands on the innermost/fastest ICI dimension).  One axis may be -1.
    With ``shape=None`` the mesh is 1-D data-parallel over all devices.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if not shape:
        shape = {AXIS_DP: n}
    names = list(shape.keys())
    sizes = list(shape.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if known <= 0 or n % known:
            raise ValueError(
                f"cannot infer -1 axis: {n} devices not divisible by {known}"
            )
        sizes[sizes.index(-1)] = n // known
    total = math.prod(sizes)
    if total != n:
        raise ValueError(
            f"mesh shape {dict(zip(names, sizes))} requires {total} devices, "
            f"but {n} are visible"
        )
    grid = np.asarray(devices).reshape(sizes)
    return Mesh(grid, axis_names=tuple(names))


def single_device_mesh(device=None) -> Mesh:
    """A 1×… mesh for one device — lets sharded code paths run unchanged."""
    if device is None:
        device = jax.devices()[0]
    return Mesh(np.asarray([device]), axis_names=(AXIS_DP,))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1) if hasattr(mesh.shape, "get") else dict(
        zip(mesh.axis_names, mesh.devices.shape)
    ).get(axis, 1)
