"""Party-local parallelism: meshes, sharding strategies, collectives.

The reference has **no** intra-party parallelism (SURVEY §2.10) — whatever
the user's TF/Torch code did inside a Ray task.  Here it is first-class:
each party owns a `jax.sharding.Mesh` over its local TPU slice, fed tasks
carry a :class:`~rayfed_tpu.parallel.sharding.ShardingStrategy` describing
how their compute maps onto the mesh axes (DP / FSDP / TP / SP / EP / PP),
and cross-party aggregation composes with intra-party XLA collectives.
"""

from rayfed_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    create_mesh,
)
from rayfed_tpu.parallel.pipeline import make_pipeline, pipeline_collective, stack_params
from rayfed_tpu.parallel.sharding import ShardingStrategy

__all__ = [
    "create_mesh",
    "ShardingStrategy",
    "make_pipeline",
    "pipeline_collective",
    "stack_params",
    "AXIS_DP",
    "AXIS_FSDP",
    "AXIS_TP",
    "AXIS_SP",
    "AXIS_EP",
    "AXIS_PP",
]
