"""Sharding strategies: how a fed task's compute maps onto the party mesh.

A :class:`ShardingStrategy` bundles the mesh with partition rules for
params and batch, and compiles train/eval steps with ``jax.jit`` +
``NamedSharding`` constraints.  DP/FSDP/TP/SP/EP/PP are expressed as which
mesh axes the batch, parameters, sequence, and experts are split over —
XLA inserts the collectives (psum/all-gather/reduce-scatter) from the
sharding annotations; nothing is hand-scheduled.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Optional, Sequence, Tuple

import jax

from rayfed_tpu.utils.jax_compat import set_mesh
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rayfed_tpu import tree_util
from rayfed_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def shard_params_by_rules(
    mesh: Mesh,
    params: Any,
    rules: Sequence[Tuple[str, P]],
    default: Optional[P] = None,
) -> Any:
    """Build a NamedSharding pytree for ``params`` from (regex, spec) rules.

    First matching rule wins (t5x-style partitioning rules, applied to the
    '/'-joined tree path).  Unmatched leaves use ``default`` (replicated if
    None).  Specs naming axes absent from the mesh degrade to None on that
    dim, so one rule set serves every mesh shape.
    """
    default = default if default is not None else P()
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    axis_names = set(mesh.axis_names)

    def _prune(spec: P) -> P:
        pruned = []
        for entry in spec:
            if entry is None:
                pruned.append(None)
            elif isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a in axis_names)
                pruned.append(kept if kept else None)
            else:
                pruned.append(entry if entry in axis_names else None)
        return P(*pruned)

    def _assign(path, leaf):
        path_s = _path_str(path)
        for pat, spec in compiled:
            if pat.search(path_s):
                return NamedSharding(mesh, _prune(spec))
        return NamedSharding(mesh, _prune(default))

    return jax.tree_util.tree_map_with_path(_assign, params)


@dataclasses.dataclass
class ShardingStrategy:
    """Declarative parallelism plan for a party's compute.

    - ``batch_axes``: mesh axes the leading batch dim is split over (DP).
    - ``param_rules``: (regex, PartitionSpec) rules for model params —
      FSDP ≈ shard large kernels over 'fsdp'; TP ≈ shard feature dims over
      'tp'; EP ≈ shard the expert dim over 'ep'.
    - ``seq_axis``: mesh axis for sequence/context parallelism (ring
      attention / Ulysses) — consumed by the attention ops.
    - ``pp_axis``: mesh axis for pipeline stages — consumed by
      :mod:`rayfed_tpu.parallel.pipeline`.
    """

    mesh: Mesh
    batch_axes: Tuple[str, ...] = (AXIS_DP,)
    param_rules: Tuple[Tuple[str, P], ...] = ()
    param_default: Optional[P] = None
    seq_axis: Optional[str] = None
    pp_axis: Optional[str] = None

    def batch_sharding(self, ndim: int = 2) -> NamedSharding:
        axes = tuple(a for a in self.batch_axes if a in self.mesh.axis_names)
        spec = (axes if axes else None,) + (None,) * (ndim - 1)
        return NamedSharding(self.mesh, P(*spec))

    def param_shardings(self, params: Any) -> Any:
        return shard_params_by_rules(
            self.mesh, params, self.param_rules, self.param_default
        )

    def shard_params(self, params: Any) -> Any:
        return jax.device_put(params, self.param_shardings(params))

    def shard_batch(self, batch: Any) -> Any:
        def _put(x):
            return jax.device_put(x, self.batch_sharding(ndim=max(1, x.ndim)))

        return tree_util.tree_map(_put, batch)

    def replicate(self, tree: Any) -> Any:
        return jax.device_put(tree, replicated(self.mesh))

    def jit_step(
        self,
        step_fn: Callable,
        donate_argnums: Tuple[int, ...] = (),
        **jit_kwargs,
    ) -> Callable:
        """jit ``step_fn`` under this strategy's mesh context.

        Shardings flow from the arguments (params/batch already placed by
        :meth:`shard_params`/:meth:`shard_batch`); XLA derives the rest.
        """
        jitted = jax.jit(step_fn, donate_argnums=donate_argnums, **jit_kwargs)

        def _call(*args, **kwargs):
            with set_mesh(self.mesh):
                return jitted(*args, **kwargs)

        _call.lower = jitted.lower  # expose for AOT/compile checks
        return _call


def data_parallel(mesh: Mesh) -> ShardingStrategy:
    return ShardingStrategy(mesh=mesh, batch_axes=(AXIS_DP,))


def fsdp(mesh: Mesh, min_shard_dim: int = 2) -> ShardingStrategy:
    """Batch over dp+fsdp; every ≥2-D kernel sharded over 'fsdp' on dim 0."""
    del min_shard_dim
    return ShardingStrategy(
        mesh=mesh,
        batch_axes=(AXIS_DP, AXIS_FSDP),
        param_rules=((r"(kernel|embedding|scale.*|w[0-9]*)$", P(AXIS_FSDP)),),
    )
