"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style).

Equal-width pipelining the TPU way: stages are shards of a *stacked*
layer pytree over ``pp``; activations hop stage→stage with
``lax.ppermute`` inside a ``lax.scan`` over ticks — no host round trips,
no per-stage processes.  XLA overlaps the collective-permute with the
next tick's compute, so the only inherent cost is the (S−1)-tick bubble,
amortized by the number of microbatches.

Absent from the reference (SURVEY §2.10: no PP anywhere); here it is a
party-local sharding strategy: combine ``pp`` with ``dp``/``tp`` axes in
one mesh and the stage body is itself free to use tp/sp collectives.

Constraints (the classic equal-width contract):

- stage input and output shapes/dtypes are identical;
- every leaf of the stacked params has leading dim == number of stages ×
  layers-per-stage (the stage receives its slice with that leading dim).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_collective(
    stage_params: Any,
    x_microbatches: jax.Array,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    axis_name: str = "pp",
) -> jax.Array:
    """Collective form — call inside ``shard_map``.

    ``stage_params``: this stage's slice of the stacked params (leading
    dim = layers per stage).  ``x_microbatches``: [M, mb, ...] replicated
    across stages (only stage 0 reads it).  Returns [M, mb, ...]
    outputs, replicated across stages.
    """
    num_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    num_mb = x_microbatches.shape[0]
    total_ticks = num_mb + num_stages - 1
    perm = [(k, (k + 1) % num_stages) for k in range(num_stages)]

    state = jnp.zeros_like(x_microbatches[0])
    outputs = jnp.zeros_like(x_microbatches)

    def tick(carry, i):
        state, outputs = carry
        # Stage s processes microbatch (i - s) on tick i, if in range.
        mb_idx = jnp.clip(i, 0, num_mb - 1)
        x_in = jnp.where(stage == 0, x_microbatches[mb_idx], state)
        y = stage_fn(stage_params, x_in)
        # Last stage banks its finished microbatch j = i - (S-1).
        j = i - (num_stages - 1)
        banked = outputs.at[jnp.clip(j, 0, num_mb - 1)].set(y)
        outputs = jnp.where((stage == num_stages - 1) & (j >= 0), banked, outputs)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(total_ticks)
    )
    # Replicate the last stage's banked outputs to every stage.
    return lax.psum(
        jnp.where(stage == num_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )


def make_pipeline(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    axis_name: str = "pp",
    num_microbatches: int,
):
    """Build a pipelined apply: (stacked_params, x) → y.

    ``stacked_params``: pytree whose leaves have leading dim =
    total layers (divisible by the ``pp`` axis size); sharded over
    ``axis_name`` on dim 0.  ``x``: [B, ...] with B divisible by
    ``num_microbatches``; returns [B, ...].
    """
    n_stages = mesh.shape[axis_name]

    collective = functools.partial(
        pipeline_collective, stage_fn=stage_fn, axis_name=axis_name
    )
    sharded = jax.shard_map(
        collective,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )

    def apply(stacked_params, x):
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            if leaf.shape[0] % n_stages:
                raise ValueError(
                    f"stacked param leading dim {leaf.shape[0]} not divisible "
                    f"by {n_stages} pipeline stages"
                )
        b = x.shape[0]
        if b % num_microbatches:
            raise ValueError(
                f"batch {b} not divisible by {num_microbatches} microbatches"
            )
        mbs = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])
        out = sharded(stacked_params, mbs)
        return out.reshape(b, *out.shape[2:])

    return apply


def stack_params(params_list) -> Any:
    """Stack per-layer param pytrees into one stacked tree (dim 0 = layer)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)
