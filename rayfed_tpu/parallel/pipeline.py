"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style).

Equal-width pipelining the TPU way: stages are shards of a *stacked*
layer pytree over ``pp``; activations hop stage→stage with
``lax.ppermute`` inside a ``lax.scan`` over ticks — no host round trips,
no per-stage processes.  XLA overlaps the collective-permute with the
next tick's compute, so the only inherent cost is the (S−1)-tick bubble,
amortized by the number of microbatches.

Absent from the reference (SURVEY §2.10: no PP anywhere); here it is a
party-local sharding strategy: combine ``pp`` with ``dp``/``tp`` axes in
one mesh and the stage body is itself free to use tp/sp collectives.

Constraints (the classic equal-width contract):

- stage input and output shapes/dtypes are identical;
- every leaf of the stacked params has leading dim == number of stages ×
  layers-per-stage (the stage receives its slice with that leading dim).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax

from rayfed_tpu.utils.jax_compat import shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_collective(
    stage_params: Any,
    x_microbatches: jax.Array,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    axis_name: str = "pp",
) -> jax.Array:
    """Collective form — call inside ``shard_map``.

    ``stage_params``: this stage's slice of the stacked params (leading
    dim = layers per stage).  ``x_microbatches``: [M, mb, ...] replicated
    across stages (only stage 0 reads it).  Returns [M, mb, ...]
    outputs, replicated across stages.
    """
    num_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    num_mb = x_microbatches.shape[0]
    total_ticks = num_mb + num_stages - 1
    perm = [(k, (k + 1) % num_stages) for k in range(num_stages)]

    state = jnp.zeros_like(x_microbatches[0])
    outputs = jnp.zeros_like(x_microbatches)

    def tick(carry, i):
        state, outputs = carry
        # Stage s processes microbatch (i - s) on tick i, if in range.
        mb_idx = jnp.clip(i, 0, num_mb - 1)
        x_in = jnp.where(stage == 0, x_microbatches[mb_idx], state)
        y = stage_fn(stage_params, x_in)
        # Last stage banks its finished microbatch j = i - (S-1).
        j = i - (num_stages - 1)
        banked = outputs.at[jnp.clip(j, 0, num_mb - 1)].set(y)
        outputs = jnp.where((stage == num_stages - 1) & (j >= 0), banked, outputs)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(total_ticks)
    )
    # Replicate the last stage's banked outputs to every stage.
    return lax.psum(
        jnp.where(stage == num_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )


def make_pipeline(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    axis_name: str = "pp",
    num_microbatches: int,
):
    """Build a pipelined apply: (stacked_params, x) → y.

    ``stacked_params``: pytree whose leaves have leading dim =
    total layers (divisible by the ``pp`` axis size); sharded over
    ``axis_name`` on dim 0.  ``x``: [B, ...] with B divisible by
    ``num_microbatches``; returns [B, ...].
    """
    n_stages = mesh.shape[axis_name]

    collective = functools.partial(
        pipeline_collective, stage_fn=stage_fn, axis_name=axis_name
    )
    sharded = shard_map(
        collective,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )

    def apply(stacked_params, x):
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            if leaf.shape[0] % n_stages:
                raise ValueError(
                    f"stacked param leading dim {leaf.shape[0]} not divisible "
                    f"by {n_stages} pipeline stages"
                )
        b = x.shape[0]
        if b % num_microbatches:
            raise ValueError(
                f"batch {b} not divisible by {num_microbatches} microbatches"
            )
        mbs = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])
        out = sharded(stacked_params, mbs)
        return out.reshape(b, *out.shape[2:])

    return apply


def stack_params(params_list) -> Any:
    """Stack per-layer param pytrees into one stacked tree (dim 0 = layer)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


# ---------------------------------------------------------------------------
# 1F1B training schedule
# ---------------------------------------------------------------------------


def pipeline_train_collective(
    stage_params: Any,
    x_microbatches: jax.Array,
    target_microbatches: jax.Array,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    *,
    axis_name: str = "pp",
):
    """One-forward-one-backward training schedule — call inside shard_map.

    Each scan tick runs one forward (microbatch ``t - s``) **and** one
    backward (microbatch ``t - 2(S-1) + s``) per stage, so in steady
    state every stage alternates F/B with no separate reverse pass.
    Backward recomputes the stage forward from its saved *input* via
    ``jax.vjp`` (activation recomputation), so per-stage live memory is
    O(S) saved microbatch inputs — differentiating the GPipe scan
    instead stores residuals for every one of the M + S - 1 ticks,
    O(M) per stage.  Total ticks: M + 2(S-1).

    The last stage seeds the backward from ``loss_fn(y, target)`` of the
    microbatch it just finished (its F and B of the same microbatch land
    on the same tick).  Loss is the mean of ``loss_fn`` over microbatches.

    Returns ``(loss, param_grads)``: grads have the stage's stacked-param
    shape (sharded over ``axis_name`` like the params).
    """
    num_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    num_mb = x_microbatches.shape[0]
    total_ticks = num_mb + 2 * (num_stages - 1)
    # Max in-flight microbatches per stage is 2(S-1-s)+1 <= 2S-1.
    num_slots = 2 * num_stages
    perm_fwd = [(k, (k + 1) % num_stages) for k in range(num_stages)]
    perm_bwd = [(k, (k - 1) % num_stages) for k in range(num_stages)]

    mb_shape = x_microbatches.shape[1:]
    in_buf0 = jnp.zeros((num_slots,) + mb_shape, x_microbatches.dtype)
    fwd_state0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    bwd_state0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    grads0 = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
    inv_m = 1.0 / num_mb

    def tick(carry, t):
        fwd_state, bwd_state, in_buf, grads, loss_acc = carry
        fi = t - stage  # forward microbatch index this tick
        bi = t - 2 * (num_stages - 1) + stage  # backward microbatch index
        do_f = (fi >= 0) & (fi < num_mb)
        do_b = (bi >= 0) & (bi < num_mb)

        # ---- forward ----
        x_in = jnp.where(
            stage == 0, x_microbatches[jnp.clip(fi, 0, num_mb - 1)], fwd_state
        )
        y = stage_fn(stage_params, x_in)
        # Save the stage input so backward can recompute (gated write).
        slot_f = jnp.clip(fi, 0, num_mb - 1) % num_slots
        saved = in_buf.at[slot_f].set(x_in)
        in_buf = jnp.where(do_f, saved, in_buf)

        # Last stage: loss of the microbatch finished this tick, and the
        # backward seed dL/dy for that same microbatch (fi == bi there).
        tgt = target_microbatches[jnp.clip(fi, 0, num_mb - 1)]
        mb_loss, seed = jax.value_and_grad(loss_fn)(y, tgt)
        loss_acc = loss_acc + jnp.where(
            (stage == num_stages - 1) & do_f, mb_loss * inv_m, 0.0
        )

        # ---- backward (recompute from the saved input) ----
        slot_b = jnp.clip(bi, 0, num_mb - 1) % num_slots
        x_saved = in_buf[slot_b]
        _, vjp_fn = jax.vjp(stage_fn, stage_params, x_saved)
        g_in = jnp.where(
            stage == num_stages - 1,
            seed.astype(bwd_state.dtype) * inv_m,
            bwd_state,
        )
        gp, gx = vjp_fn(g_in.astype(y.dtype))
        grads = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(do_b, g, jnp.zeros_like(g)),
            grads,
            gp,
        )

        fwd_state = lax.ppermute(y, axis_name, perm_fwd)
        bwd_state = lax.ppermute(gx, axis_name, perm_bwd)
        return (fwd_state, bwd_state, in_buf, grads, loss_acc), None

    carry0 = (fwd_state0, bwd_state0, in_buf0, grads0, jnp.float32(0.0))
    (_, _, _, grads, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(total_ticks)
    )
    # Loss lives on the last stage only; replicate it.
    loss = lax.psum(
        jnp.where(stage == num_stages - 1, loss_acc, 0.0), axis_name
    )
    return loss, grads


# ---------------------------------------------------------------------------
# Interleaved (virtual-stage) schedule
# ---------------------------------------------------------------------------


def pipeline_train_interleaved_collective(
    stage_params: Any,
    x_microbatches: jax.Array,
    target_microbatches: jax.Array,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    *,
    axis_name: str = "pp",
    num_chunks: int,
):
    """Interleaved-schedule training — call inside shard_map.

    Virtual-stage pipelining (the Megatron-LM interleaved idea, built
    the SPMD way): each device hosts ``v = num_chunks`` model CHUNKS,
    with virtual stage ``s_v = c·S + d`` (chunk ``c`` on device ``d``) —
    so one pipeline traversal visits the device ring ``v`` times.  The
    ramp bubble shrinks from (S−1) full per-device stage times to
    (S−1) CHUNK times (1/v of a stage): the first microbatch reaches the
    last device after S−1 chunk computations, not S−1 stage
    computations.

    Schedule: microbatch ``m = g·S + r`` runs its (chunk ``c``) unit on
    device ``d`` at fine tick ``τ = d + g·S·v + c·S + r``.  Every
    dependency is satisfied with margin exactly 1 tick, so a single
    forward ring ``ppermute`` per tick carries both the stage→stage hop
    and the chunk-wrap hop (device S−1 → device 0), and every device is
    busy every tick in steady state.  The backward pass is the exact
    time-reversal of the forward schedule on the reverse ring; each
    backward unit recomputes its chunk forward from the saved chunk
    INPUT (activation recomputation), so per-device live memory is the
    M·v saved chunk inputs — GPipe-with-recompute's O(M) class, traded
    for the interleaved bubble; use the 1F1B schedule (v=1) when
    activation memory, not bubble, binds.

    Total fine ticks: 2·(M·v + S − 1); ideal step time
    2·M·T_stage + 2·(S−1)·T_stage/v vs 1F1B's 2·M·T + 2·(S−1)·T.

    Returns ``(loss, param_grads)`` like
    :func:`pipeline_train_collective`; the device's param slice is
    [v·layers_per_chunk, ...] with its chunks CONTIGUOUS in chunk order
    (see ``_interleave_blocks`` in :func:`make_pipeline_train`).
    """
    num_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    v = num_chunks
    num_mb = x_microbatches.shape[0]
    span = num_mb * v + num_stages - 1  # fine ticks per direction
    perm_fwd = [(k, (k + 1) % num_stages) for k in range(num_stages)]
    perm_bwd = [(k, (k - 1) % num_stages) for k in range(num_stages)]

    mb_shape = x_microbatches.shape[1:]
    inv_m = 1.0 / num_mb

    def chunk_params(c):
        # Static per-branch chunk slice: leading dim v*Lc -> [Lc, ...].
        def slice_c(p):
            lc = p.shape[0] // v
            return p[c * lc : (c + 1) * lc]

        return jax.tree_util.tree_map(slice_c, stage_params)

    def decode_unit(u):
        """Fine-tick offset u = τ − d → (chunk, microbatch, valid)."""
        g = u // (num_stages * v)
        rem = u % (num_stages * v)
        c = rem // num_stages
        r = rem % num_stages
        m = g * num_stages + r
        valid = (u >= 0) & (m >= 0) & (m < num_mb)
        return c, jnp.clip(m, 0, num_mb - 1), valid

    # ---- forward: compute + save every chunk input --------------------------
    in_store0 = jnp.zeros((v, num_mb) + mb_shape, x_microbatches.dtype)

    def fwd_tick(carry, tau):
        state, in_store, loss_acc = carry
        u = tau - stage
        c, m, valid = decode_unit(u)
        # Fresh microbatches enter only at virtual stage 0 (= device 0
        # chunk 0); every other unit consumes the ring.
        x_in = jnp.where(
            (stage == 0) & (c == 0), x_microbatches[m], state
        )
        y = lax.switch(
            c, [lambda x, cc=cc: stage_fn(chunk_params(cc), x) for cc in range(v)],
            x_in,
        )
        saved = jax.lax.dynamic_update_slice(
            in_store, x_in[None, None], (c, m) + (0,) * len(mb_shape)
        )
        in_store = jnp.where(valid, saved, in_store)
        # Loss banks at the LAST virtual stage (device S−1, chunk v−1).
        mb_loss = loss_fn(y, target_microbatches[m])
        loss_acc = loss_acc + jnp.where(
            (stage == num_stages - 1) & (c == v - 1) & valid,
            mb_loss * inv_m,
            0.0,
        )
        state = lax.ppermute(y, axis_name, perm_fwd)
        return (state, in_store, loss_acc), None

    carry0 = (
        jnp.zeros(mb_shape, x_microbatches.dtype),
        in_store0,
        jnp.float32(0.0),
    )
    (_, in_store, loss_acc), _ = lax.scan(
        fwd_tick, carry0, jnp.arange(span)
    )

    # ---- backward: exact time-reversal of the forward schedule --------------
    grads0 = jax.tree_util.tree_map(jnp.zeros_like, stage_params)

    def bwd_tick(carry, tau_b):
        g_state, grads = carry
        u = (span - 1 - tau_b) - stage  # the unit whose forward slot mirrors
        c, m, valid = decode_unit(u)
        x_saved = jax.lax.dynamic_slice(
            in_store, (c, m) + (0,) * len(mb_shape), (1, 1) + mb_shape
        ).reshape(mb_shape)

        def branch(cc):
            def run(x_saved, g_in, tgt):
                p_c = chunk_params(cc)
                y, vjp_fn = jax.vjp(
                    lambda p, x: stage_fn(p, x), p_c, x_saved
                )
                # Seed at the last virtual stage: dL/dy of this unit's
                # own microbatch; elsewhere the ring cotangent.
                gy = jax.grad(loss_fn)(y, tgt)
                is_seed = (stage == num_stages - 1) & (cc == v - 1)
                g_eff = jnp.where(
                    is_seed, gy.astype(g_in.dtype) * inv_m, g_in
                )
                gp_c, gx = vjp_fn(g_eff.astype(y.dtype))
                # Embed the chunk grads into the device's full slice.
                def embed(full, gc):
                    lc = full.shape[0] // v
                    return jax.lax.dynamic_update_slice(
                        full, gc, (cc * lc,) + (0,) * (full.ndim - 1)
                    )

                gp = jax.tree_util.tree_map(
                    embed,
                    jax.tree_util.tree_map(jnp.zeros_like, stage_params),
                    gp_c,
                )
                return gp, gx

            return run

        gp, gx = lax.switch(
            c,
            [branch(cc) for cc in range(v)],
            x_saved,
            g_state,
            target_microbatches[m],
        )
        grads = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(valid, g, jnp.zeros_like(g)),
            grads,
            gp,
        )
        g_state = lax.ppermute(
            jnp.where(valid, gx, jnp.zeros_like(gx)), axis_name, perm_bwd
        )
        return (g_state, grads), None

    (_, grads), _ = lax.scan(
        bwd_tick,
        (jnp.zeros(mb_shape, x_microbatches.dtype), grads0),
        jnp.arange(span),
    )
    loss = lax.psum(
        jnp.where(stage == num_stages - 1, loss_acc, 0.0), axis_name
    )
    return loss, grads


def make_pipeline_train(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    *,
    axis_name: str = "pp",
    num_microbatches: int,
    virtual_stages: int = 1,
):
    """Build a pipelined training step: (stacked_params, x, targets) → (loss, grads).

    ``loss_fn(y_mb, target_mb) -> scalar``; the returned loss is its mean
    over microbatches and ``grads`` matches ``stacked_params`` (sharded
    over ``axis_name``).  Gradient-equivalent to ``jax.grad`` through the
    :func:`make_pipeline` forward (tested).

    ``virtual_stages=1`` (default): the 1F1B schedule — O(S) per-stage
    activation memory, ramp bubble 2(S−1) stage times.
    ``virtual_stages=v>1``: the interleaved schedule — each device hosts
    ``v`` model chunks and the bubble shrinks to 2(S−1)/v stage times
    (see :func:`pipeline_train_interleaved_collective`); ``stage_fn``
    then receives chunks of ``total_layers/(S·v)`` layers.
    """
    n_stages = mesh.shape[axis_name]
    v = int(virtual_stages)
    if v < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {v}")

    if v == 1:
        collective = functools.partial(
            pipeline_train_collective,
            stage_fn=stage_fn,
            loss_fn=loss_fn,
            axis_name=axis_name,
        )
    else:
        collective = functools.partial(
            pipeline_train_interleaved_collective,
            stage_fn=stage_fn,
            loss_fn=loss_fn,
            axis_name=axis_name,
            num_chunks=v,
        )
    sharded = shard_map(
        collective,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P()),
        out_specs=(P(), P(axis_name)),
        check_vma=False,
    )

    def _interleave_blocks(leaf):
        """Reorder virtual-stage blocks so shard_map's contiguous split
        hands device d its chunks [d, S+d, …] in chunk order."""
        lb = leaf.shape[0] // (n_stages * v)
        blocks = leaf.reshape((n_stages * v, lb) + leaf.shape[1:])
        order = jnp.asarray(
            [c * n_stages + d for d in range(n_stages) for c in range(v)]
        )
        return jnp.take(blocks, order, axis=0).reshape(leaf.shape)

    def _deinterleave_blocks(leaf):
        lb = leaf.shape[0] // (n_stages * v)
        blocks = leaf.reshape((n_stages * v, lb) + leaf.shape[1:])
        order = [c * n_stages + d for d in range(n_stages) for c in range(v)]
        inverse = jnp.asarray(
            [order.index(b) for b in range(n_stages * v)]
        )
        return jnp.take(blocks, inverse, axis=0).reshape(leaf.shape)

    def train(stacked_params, x, targets):
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            if leaf.shape[0] % (n_stages * v):
                raise ValueError(
                    f"stacked param leading dim {leaf.shape[0]} not divisible "
                    f"by {n_stages} stages x {v} virtual stages"
                )
        b = x.shape[0]
        if b % num_microbatches:
            raise ValueError(
                f"batch {b} not divisible by {num_microbatches} microbatches"
            )
        if v > 1 and num_microbatches % n_stages:
            # The interleaved slot formula m = g*S + r schedules
            # microbatches in groups of S; a trailing partial group's
            # units would land past the scan span and silently drop
            # their loss/grad contributions (same constraint as
            # Megatron-LM's interleaved schedule).
            raise ValueError(
                f"interleaved schedule needs num_microbatches "
                f"({num_microbatches}) divisible by the {n_stages} "
                f"pipeline stages (virtual_stages={v})"
            )
        mb = b // num_microbatches
        mbs = x.reshape(num_microbatches, mb, *x.shape[1:])
        tgts = targets.reshape(num_microbatches, mb, *targets.shape[1:])
        if v == 1:
            return sharded(stacked_params, mbs, tgts)
        permuted = jax.tree_util.tree_map(_interleave_blocks, stacked_params)
        loss, grads = sharded(permuted, mbs, tgts)
        return loss, jax.tree_util.tree_map(_deinterleave_blocks, grads)

    return train
