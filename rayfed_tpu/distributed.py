"""Multi-host parties: one party spanning several JAX processes.

The reference's party is one Ray cluster (any number of machines behind
one GCS); this framework's party is a JAX process group — the TPU-native
equivalent of "a party spans hosts" is ``jax.distributed.initialize``
over the party's pod slice (SURVEY §2.10 inter-party row).  Compute then
runs SPMD over a global mesh spanning every host in the party, with XLA
collectives riding ICI/DCN.

Cross-party traffic stays on the push transport, but only **process 0 of
each party (the leader)** runs it — one listener, one egress per party.
Values a non-leader process needs (recv'd pushes, broadcast-on-get
results) reach it through the **party process bridge**: the
jax.distributed coordination service's key-value store, keyed by the
same deterministic ``(upstream, downstream)`` rendezvous ids as the wire.
The KV bridge is key-addressed and unordered, so recv futures may
resolve in any order on any thread — no collective-ordering hazard (the
ordered-collective alternative, ``multihost_utils.broadcast_one_to_all``,
would require every process to resolve recvs in lockstep program order).

Payload sizing: bridge values ride the coordination service (designed
for metadata, not bulk tensors) — fine for control values, model deltas
and CPU-test scale.  Bulk sharded arrays should instead be produced ON
the party mesh (each process feeds its local shards) rather than pushed
through a single leader; see ``parallel/sharding.py``.
"""

from __future__ import annotations

import base64
import concurrent.futures
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from rayfed_tpu.executor import LocalRef

logger = logging.getLogger(__name__)

_BRIDGE_PREFIX = "rayfed_bridge"


class PartyProcessGroup:
    """This party's JAX process group (leader = process 0).

    Wraps ``jax.distributed.initialize`` plus the coordination-service
    KV client used as the intra-party value bridge.
    """

    def __init__(
        self,
        coordinator_address: str,
        num_processes: int,
        process_id: int,
    ) -> None:
        import jax

        self.num_processes = int(num_processes)
        self.process_id = int(process_id)
        self.coordinator_address = coordinator_address
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=self.num_processes,
            process_id=self.process_id,
        )
        # The coordination-service KV client has no public accessor yet
        # (tracked upstream); reach into jax._src behind a guard so a JAX
        # upgrade that moves it fails loudly with an actionable message
        # instead of an AttributeError deep in a send.
        try:
            from jax._src import distributed as _jdist

            self._client = _jdist.global_state.client
        except (ImportError, AttributeError) as e:  # pragma: no cover
            raise RuntimeError(
                "rayfed_tpu's multi-host KV bridge uses the private "
                "jax._src.distributed.global_state.client API (verified on "
                "jax 0.4.30-0.9.x); this JAX build "
                f"({jax.__version__}) no longer exposes it — pin a tested "
                "JAX or port PartyProcessGroup to the replacement API"
            ) from e
        if self._client is None:  # pragma: no cover
            raise RuntimeError("jax.distributed did not expose a KV client")
        self._published: List[Tuple[str, str, float]] = []
        self._published_lock = threading.Lock()

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    # -- KV bridge ------------------------------------------------------------

    def _key(self, upstream_seq_id: Any, downstream_seq_id: Any) -> str:
        return f"{_BRIDGE_PREFIX}/{upstream_seq_id}#{downstream_seq_id}"

    def _ack_key(self, upstream_seq_id, downstream_seq_id, pid: int) -> str:
        return (
            f"{_BRIDGE_PREFIX}_ack/{upstream_seq_id}#{downstream_seq_id}/{pid}"
        )

    def publish(self, upstream_seq_id, downstream_seq_id, data: bytes) -> None:
        """Leader-side: make a received value visible to all party processes."""
        self._client.key_value_set(
            self._key(upstream_seq_id, downstream_seq_id),
            base64.b64encode(data).decode("ascii"),
        )
        with self._published_lock:
            self._published.append(
                (str(upstream_seq_id), str(downstream_seq_id), time.monotonic())
            )

    def fetch(
        self, upstream_seq_id, downstream_seq_id, timeout_s: float
    ) -> bytes:
        """Non-leader-side: block until the leader publishes the value."""
        encoded = self._client.blocking_key_value_get(
            self._key(upstream_seq_id, downstream_seq_id),
            int(timeout_s * 1000),
        )
        # Ack so the leader's GC can delete the entry once every
        # non-leader has read it (the coordination-service KV is for
        # metadata — values must not accumulate for the job's lifetime).
        try:
            self._client.key_value_set(
                self._ack_key(upstream_seq_id, downstream_seq_id, self.process_id),
                "1",
            )
        except Exception:  # pragma: no cover
            logger.debug("bridge ack failed", exc_info=True)
        return base64.b64decode(encoded)

    def _probe(self, key: str) -> bool:
        try:
            self._client.blocking_key_value_get(key, 1)
            return True
        except Exception:
            return False

    def gc_published(self, ttl_s: float = 3600.0) -> int:
        """Leader-side: delete bridge entries every non-leader has acked
        (or that exceeded the TTL).  Returns the number deleted."""
        with self._published_lock:
            tracked = list(self._published)
        deleted = 0
        now = time.monotonic()
        keep = []
        for up, down, t0 in tracked:
            acked = all(
                self._probe(self._ack_key(up, down, pid))
                for pid in range(1, self.num_processes)
            )
            if acked or now - t0 > ttl_s:
                for k in [self._key(up, down)] + [
                    self._ack_key(up, down, pid)
                    for pid in range(1, self.num_processes)
                ]:
                    try:
                        self._client.key_value_delete(k)
                    except Exception:  # pragma: no cover
                        pass
                deleted += 1
            else:
                keep.append((up, down, t0))
        with self._published_lock:
            # Re-merge entries published while GC ran.
            fresh = [e for e in self._published if e not in tracked]
            self._published = keep + fresh
        return deleted

    def barrier(self, name: str, timeout_s: float = 120.0) -> None:
        self._client.wait_at_barrier(name, int(timeout_s * 1000))

    def cleanup(self) -> None:
        """Best-effort removal of bridge keys (leader, at shutdown)."""
        if not self.is_leader:
            return
        try:
            self._client.key_value_delete(_BRIDGE_PREFIX)
        except Exception:  # pragma: no cover - older jax w/o dir delete
            logger.debug("bridge key cleanup not supported", exc_info=True)

    def shutdown(self) -> None:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:  # pragma: no cover
            logger.debug("jax.distributed.shutdown failed", exc_info=True)


def _encode_value(value: Any) -> bytes:
    from rayfed_tpu.transport import wire

    return b"".join(
        bytes(b) if not isinstance(b, bytes) else b
        for b in wire.encode_payload(value)
    )


def _decode_value(data: bytes, allowed: Optional[Dict], device_put: bool) -> Any:
    from rayfed_tpu.transport import wire

    return wire.decode_payload(data, allowed=allowed, device_put=device_put)


class MultiHostTransport:
    """Send/recv proxy for a party spanning multiple JAX processes.

    - Leader: wraps the party's real :class:`TransportManager`; every
      successful recv is additionally published on the process bridge.
    - Non-leader: no wire at all — sends resolve ``True`` immediately
      (the leader performs the real push; the same deterministic program
      runs there), recvs fetch from the bridge.
    """

    def __init__(
        self,
        inner,  # TransportManager | None
        group: PartyProcessGroup,
        *,
        allowed: Optional[Dict] = None,
        device_put_received: bool = True,
        timeout_s: float = 60.0,
    ) -> None:
        self._inner = inner
        self._group = group
        self._allowed = allowed
        self._device_put = device_put_received
        self._timeout_s = timeout_s
        self._fetch_pool = (
            None
            if group.is_leader
            else concurrent.futures.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="rayfed-bridge-fetch"
            )
        )
        self._gc_stop = threading.Event()
        self._gc_thread: Optional[threading.Thread] = None
        if group.is_leader and group.num_processes > 1:
            def _gc_loop():
                while not self._gc_stop.wait(15.0):
                    try:
                        self._group.gc_published()
                    except Exception:  # pragma: no cover
                        logger.debug("bridge GC error", exc_info=True)

            self._gc_thread = threading.Thread(
                target=_gc_loop, name="rayfed-bridge-gc", daemon=True
            )
            self._gc_thread.start()

    # -- proxy interface ------------------------------------------------------

    def send(self, dest_party, data, upstream_seq_id, downstream_seq_id):
        if self._inner is not None:
            return self._inner.send(
                dest_party=dest_party,
                data=data,
                upstream_seq_id=upstream_seq_id,
                downstream_seq_id=downstream_seq_id,
            )
        # Non-leader: the leader's identical program does the real push.
        return LocalRef.from_value(True)

    def recv(self, src_party, upstream_seq_id, downstream_seq_id):
        if self._inner is not None:
            ref = self._inner.recv(
                src_party=src_party,
                upstream_seq_id=upstream_seq_id,
                downstream_seq_id=downstream_seq_id,
            )
            if self._group.num_processes > 1:
                def _publish(r: LocalRef) -> None:
                    if r.exception() is not None:
                        return
                    try:
                        self._group.publish(
                            upstream_seq_id,
                            downstream_seq_id,
                            _encode_value(r.resolve()),
                        )
                    except Exception:
                        logger.exception(
                            "bridge publish failed for (%s, %s)",
                            upstream_seq_id, downstream_seq_id,
                        )

                ref.add_done_callback(_publish)
            return ref

        out = LocalRef()

        def _fetch():
            try:
                data = self._group.fetch(
                    upstream_seq_id, downstream_seq_id, self._timeout_s
                )
                out.set_result(
                    _decode_value(data, self._allowed, self._device_put)
                )
            except Exception as e:
                out.set_exception(
                    TimeoutError(
                        f"bridge fetch of ({upstream_seq_id}, "
                        f"{downstream_seq_id}) failed: {e}"
                    )
                )

        self._fetch_pool.submit(_fetch)
        return out

    def ping(self, dest_party: str, timeout_s: float = 1.0) -> bool:
        if self._inner is not None:
            return self._inner.ping(dest_party, timeout_s)
        return True  # non-leaders have no wire to check

    def get_stats(self) -> Dict[str, Any]:
        stats = self._inner.get_stats() if self._inner is not None else {}
        stats["party_process_id"] = self._group.process_id
        stats["party_num_processes"] = self._group.num_processes
        return stats

    def stop(self) -> None:
        self._gc_stop.set()
        if self._gc_thread is not None:
            self._gc_thread.join(timeout=5)
        if self._inner is not None:
            self._inner.stop()
        if self._fetch_pool is not None:
            self._fetch_pool.shutdown(wait=False)
        self._group.cleanup()
        self._group.shutdown()
