"""Multi-host parties: one party spanning several JAX processes.

The reference's party is one Ray cluster (any number of machines behind
one GCS); this framework's party is a JAX process group — the TPU-native
equivalent of "a party spans hosts" is ``jax.distributed.initialize``
over the party's pod slice (SURVEY §2.10 inter-party row).  Compute then
runs SPMD over a global mesh spanning every host in the party, with XLA
collectives riding ICI/DCN.

Cross-party traffic stays on the push transport, but only **process 0 of
each party (the leader)** runs it — one listener, one egress per party.
Values a non-leader process needs (recv'd pushes, broadcast-on-get
results) reach it through the **party process bridge**: every non-leader
runs its own :class:`TransportServer` instance and the leader re-pushes
each received DATA frame's raw payload to it over the same wire stack
(zero-copy frames, CRC, native writev) — bulk tensors never ride the
coordination service.  The jax.distributed KV store carries only
control metadata: the non-leaders' bridge addresses.

The bridge is keyed by the same deterministic ``(upstream, downstream)``
rendezvous ids as the wire, and each process's mailbox is key-addressed
and unordered — recv futures may resolve in any order on any thread with
no collective-ordering hazard (the ordered-collective alternative,
``multihost_utils.broadcast_one_to_all``, would require every process to
resolve recvs in lockstep program order).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import socket as _socket
import threading
from typing import Any, Dict, Optional

from rayfed_tpu.executor import LocalRef

logger = logging.getLogger(__name__)

_BRIDGE_PREFIX = "rayfed_bridge"


def _local_host_ip() -> str:
    """Address other party processes can reach this host at.

    On multi-homed hosts the default-route interface may not be the one
    the leader can reach; ``RAYFED_BRIDGE_HOST`` overrides the heuristic.
    """
    import os

    override = os.environ.get("RAYFED_BRIDGE_HOST")
    if override:
        return override
    try:
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))  # no packets sent; routes only
            return s.getsockname()[0]
        finally:
            s.close()
    except Exception:
        return "127.0.0.1"


class PartyProcessGroup:
    """This party's JAX process group (leader = process 0).

    Wraps ``jax.distributed.initialize`` plus the coordination-service
    KV client used for control metadata (bridge addresses, barriers).
    """

    def __init__(
        self,
        coordinator_address: str,
        num_processes: int,
        process_id: int,
    ) -> None:
        import jax

        self.num_processes = int(num_processes)
        self.process_id = int(process_id)
        self.coordinator_address = coordinator_address
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=self.num_processes,
            process_id=self.process_id,
        )
        # The coordination-service KV client has no public accessor yet
        # (tracked upstream); reach into jax._src behind a guard so a JAX
        # upgrade that moves it fails loudly with an actionable message
        # instead of an AttributeError deep in a send.
        try:
            from jax._src import distributed as _jdist

            self._client = _jdist.global_state.client
        except (ImportError, AttributeError) as e:  # pragma: no cover
            raise RuntimeError(
                "rayfed_tpu's multi-host control bridge uses the private "
                "jax._src.distributed.global_state.client API (verified on "
                "jax 0.4.30-0.9.x); this JAX build "
                f"({jax.__version__}) no longer exposes it — pin a tested "
                "JAX or port PartyProcessGroup to the replacement API"
            ) from e
        if self._client is None:  # pragma: no cover
            raise RuntimeError("jax.distributed did not expose a KV client")

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    # -- control metadata ------------------------------------------------------

    def publish_bridge_address(self, address: str) -> None:
        """Non-leader: advertise this process's bridge listener."""
        self._client.key_value_set(
            f"{_BRIDGE_PREFIX}_addr/{self.process_id}", address
        )

    def fetch_bridge_address(self, pid: int, timeout_s: float) -> str:
        """Leader: resolve a non-leader's bridge listener address."""
        return self._client.blocking_key_value_get(
            f"{_BRIDGE_PREFIX}_addr/{pid}", int(timeout_s * 1000)
        )

    def key_value_set(self, key: str, value: str) -> None:
        """Generic control-metadata publish (leader verdicts etc.)."""
        self._client.key_value_set(key, value)

    def blocking_key_value_get(self, key: str, timeout_s: float) -> str:
        """Generic control-metadata fetch with a deadline."""
        return self._client.blocking_key_value_get(
            key, int(timeout_s * 1000)
        )

    def barrier(self, name: str, timeout_s: float = 120.0) -> None:
        """Party-wide barrier with a DEADLINE and a named failure: the
        raw KV barrier error is a bare status string — wrap it so the
        operator learns which barrier, which process, and how long it
        waited (the missing processes are whichever never arrived)."""
        try:
            self._client.wait_at_barrier(name, int(timeout_s * 1000))
        except Exception as e:
            raise RuntimeError(
                f"party process barrier {name!r} failed on process "
                f"{self.process_id}/{self.num_processes} after waiting "
                f"{timeout_s:.0f}s — at least one party process never "
                f"arrived (or already failed): {e}"
            ) from e

    def cleanup(self) -> None:
        """Best-effort removal of bridge keys (leader, at shutdown) so a
        re-init against the same coordination service can't resolve a
        stale address from the previous incarnation."""
        if not self.is_leader:
            return
        try:
            self._client.key_value_delete(f"{_BRIDGE_PREFIX}_addr")
        except Exception:  # pragma: no cover - older jax w/o dir delete
            logger.debug("bridge key cleanup not supported", exc_info=True)

    def shutdown(self) -> None:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:  # pragma: no cover
            logger.debug("jax.distributed.shutdown failed", exc_info=True)


class MultiHostTransport:
    """Send/recv proxy for a party spanning multiple JAX processes.

    - Leader: wraps the party's real :class:`TransportManager`; every
      received DATA frame's raw payload is additionally re-pushed to
      each non-leader's bridge server over the wire stack.
    - Non-leader: runs a bridge :class:`TransportManager` (listener on
      an OS-assigned port, advertised via the coordination KV).  Sends
      resolve ``True`` immediately (the leader performs the real push;
      the same deterministic program runs there); recvs park on the
      local bridge mailbox and decode with the full device_put /
      mesh-re-shard path — each process places its own shards.
    """

    def __init__(
        self,
        inner,  # TransportManager (NOT yet started) | None
        group: PartyProcessGroup,
        *,
        allowed: Optional[Dict] = None,
        device_put_received: bool = True,
        timeout_s: float = 60.0,
        mesh_provider=None,
        job_config=None,
        tls_config: Optional[Dict] = None,
        leader_address: Optional[str] = None,
    ) -> None:
        self._inner = inner
        self._group = group
        self._allowed = allowed
        self._device_put = device_put_received
        self._timeout_s = timeout_s
        self._job = job_config
        self._tls_config = tls_config
        # The party's advertised cross-party address — which is the
        # LEADER's listener.  Non-leaders run a watchdog against it so
        # leader death mid-round poisons their parked bridge recvs
        # within the death deadline instead of the recv backstop.
        self._leader_address = leader_address
        self._watchdog_task = None
        self._nl_roster = None  # lazy non-leader roster stub
        self._bridge_mgr = None  # non-leader listener
        self._bridge_clients: Dict[int, Any] = {}  # leader: pid -> client
        self._bridge_ready = threading.Event()
        # Loop-side twin of _bridge_ready: republish coroutines await this
        # instead of parking shared executor threads in a blocking wait
        # (a burst of early frames would otherwise occupy the same
        # executor the server raw-read and writev paths use).  Created
        # lazily ON the inner loop (single-threaded there, so no race).
        self._bridge_ready_async: Optional[asyncio.Event] = None
        # Set by api.init: called with a failed-send LocalRef so the
        # cleanup watchdog sees a fatal republish (exit-on-failure
        # semantics apply to the intra-party bridge too).
        self.failure_handler = None
        # Collective-call sequence for runtime cap mutation: every
        # process of the SPMD program calls set_max_message_size the
        # same number of times in the same order, so a local counter
        # names matching barrier/verdict keys on all of them.
        self._msgcap_seq = itertools.count()

        if group.num_processes <= 1:
            self._bridge_ready.set()
            if inner is not None:
                inner.start()
        elif group.is_leader:
            self._start_leader_bridge()
        else:
            self._start_member_bridge(mesh_provider)
            self._start_leader_watchdog()

    # -- bridge wiring ---------------------------------------------------------

    def _bridge_job_config(self):
        """Bridge-side job knobs: inherit the party's limits (a leader
        republish larger than the bridge server's cap would be fatally
        rejected and silently desync the SPMD program)."""
        import dataclasses

        from rayfed_tpu.config import JobConfig

        base = self._job if self._job is not None else JobConfig()
        return dataclasses.replace(
            base,
            device_put_received=self._device_put,
            recv_backstop_s=self._timeout_s,
        )

    def _start_member_bridge(self, mesh_provider) -> None:
        from rayfed_tpu.config import ClusterConfig, PartyConfig
        from rayfed_tpu.transport.manager import TransportManager

        me = f"bridge-p{self._group.process_id}"
        cc = ClusterConfig(
            parties={
                me: PartyConfig.from_dict({"address": "0.0.0.0:0"})
            },
            current_party=me,
            serializing_allowed_list=self._allowed,
            # Same TLS posture as the cross-party wire: the bridge
            # crosses the inter-host network too.
            tls_config=self._tls_config,
        )
        self._bridge_mgr = TransportManager(cc, self._bridge_job_config())
        self._bridge_mgr.mesh_provider = mesh_provider
        self._bridge_mgr.start()
        port = self._bridge_mgr._server.bound_port
        self._group.publish_bridge_address(f"{_local_host_ip()}:{port}")
        self._bridge_ready.set()

    def _start_leader_watchdog(self) -> None:
        """Non-leader: monitor the LEADER's cross-party listener.

        The leader is every non-leader's single source of cross-party
        values; when it dies mid-round the bridge mailbox's parked
        recvs used to wait out the full recv backstop.  The watchdog
        pings the leader's transport (the party's advertised address)
        on the bridge manager's loop and, after ``peer_death_pings``
        consecutive failures, fails every parked bridge waiter —
        leader death now surfaces on the member within the death
        deadline, as a :class:`~rayfed_tpu.exceptions.RemoteError`
        naming the leader.  Like the main health monitor, a leader
        that was never reachable only parks recvs (startup skew), and
        monitoring continues so waiters that park AFTER the death are
        failed on the next cycle too.
        """
        if self._leader_address is None or self._bridge_mgr is None:
            return
        from rayfed_tpu.config import JobConfig, RetryPolicy
        from rayfed_tpu.transport import tls as tls_utils
        from rayfed_tpu.transport.client import TransportClient

        mgr = self._bridge_mgr
        job = self._job if self._job is not None else JobConfig()
        if not job.peer_failfast:
            return
        interval = job.peer_health_interval_s
        threshold = max(1, int(job.peer_death_pings))
        client = TransportClient(
            src_party=mgr._party,
            dest_party="party-leader",
            address=self._leader_address,
            retry_policy=RetryPolicy(max_attempts=1),
            timeout_s=job.cross_silo_timeout_s,
            max_message_size=job.cross_silo_messages_max_size,
            ssl_context=tls_utils.client_ssl_context(self._tls_config),
            loop=mgr._loop,
        )

        async def _watch():
            from rayfed_tpu.exceptions import RemoteError

            fails = 0
            ever_reachable = False
            while True:
                await asyncio.sleep(interval)
                try:
                    ok = await asyncio.wait_for(
                        client.ping(
                            timeout_s=min(1.0, interval), ctl=True
                        ),
                        timeout=interval,
                    )
                except Exception:
                    ok = False
                if ok:
                    ever_reachable = True
                    fails = 0
                    continue
                if not ever_reachable:
                    continue
                fails += 1
                if fails < threshold:
                    continue
                mailbox = mgr._mailbox
                waiting = sorted(mailbox.parties_with_waiters())
                if not waiting:
                    continue
                logger.warning(
                    "party leader at %s unreachable (%d consecutive "
                    "pings); failing %d parked bridge recvs",
                    self._leader_address, fails, len(waiting),
                )
                err = RemoteError(
                    "party-leader",
                    "ConnectionError",
                    f"this party's leader process "
                    f"({self._leader_address}) is unreachable "
                    f"({fails} consecutive pings over "
                    f"~{fails * interval:.0f}s) — the bridge cannot "
                    f"deliver cross-party values; the SPMD program "
                    f"cannot proceed",
                ).to_wire()
                for party in waiting:
                    # poison_new=False: the loop keeps running, so
                    # waiters that park after this cycle are failed on
                    # the next one — and a recovered leader resumes
                    # cleanly with nothing to un-poison.
                    mailbox.fail_party(party, err, poison_new=False)

        def _arm():
            self._watchdog_task = mgr._loop.create_task(_watch())

        mgr._loop.call_soon_threadsafe(_arm)

    def _start_leader_bridge(self) -> None:
        """Install the republish hook, start the wire, and resolve
        non-leader addresses in the background.

        Hook-before-start: a peer's push can land the instant the
        listener accepts, and a frame received with no hook installed
        would never reach the non-leaders (silent SPMD desync at
        startup).  Republishes block until resolution completes.
        """
        from rayfed_tpu.config import RetryPolicy
        from rayfed_tpu.transport import tls as tls_utils
        from rayfed_tpu.transport.client import TransportClient

        inner = self._inner
        inner._server._on_message = self._on_leader_message
        inner.start()

        def _connect():
            # Retry each address forever: a party process that never
            # comes up means the job is stuck regardless, and "skip the
            # missing process" would be a silent desync.  Loud beats
            # degraded.
            for pid in range(1, self._group.num_processes):
                while True:
                    try:
                        addr = self._group.fetch_bridge_address(pid, 60.0)
                        break
                    except Exception as e:
                        logger.warning(
                            "bridge address for p%d not resolved yet (%s); "
                            "retrying", pid, e,
                        )
                self._bridge_clients[pid] = TransportClient(
                    src_party=inner._party,
                    dest_party=f"bridge-p{pid}",
                    address=addr,
                    retry_policy=RetryPolicy(),
                    timeout_s=inner._job.cross_silo_timeout_s,
                    max_message_size=inner._job.cross_silo_messages_max_size,
                    ssl_context=tls_utils.client_ssl_context(self._tls_config),
                )
            self._bridge_ready.set()
            inner._loop.call_soon_threadsafe(self._set_ready_on_loop)

        threading.Thread(
            target=_connect, name="rayfed-bridge-connect", daemon=True
        ).start()

    def _set_ready_on_loop(self) -> None:
        # Runs on the inner loop; creates the event if no republish
        # raced ahead of us.
        if self._bridge_ready_async is None:
            self._bridge_ready_async = asyncio.Event()
        self._bridge_ready_async.set()

    def _on_leader_message(self, message) -> None:
        # Runs on the inner loop thread; must not block.
        # fedlint: disable=FED002 — provably on-loop: installed as the server's _on_message callback, invoked only from its frame dispatch on the loop thread
        asyncio.ensure_future(self._republish(message))

    async def _republish(self, message) -> None:
        loop = asyncio.get_running_loop()
        if not self._bridge_ready.is_set():
            if self._bridge_ready_async is None:
                self._bridge_ready_async = asyncio.Event()
            while True:
                try:
                    await asyncio.wait_for(
                        self._bridge_ready_async.wait(), timeout=60
                    )
                    break
                except asyncio.TimeoutError:
                    logger.error(
                        "bridge clients still unresolved; republish of "
                        "(%s, %s) waiting",
                        message.upstream_seq_id, message.downstream_seq_id,
                    )
        crc = None
        clients = list(self._bridge_clients.items())
        if (
            clients
            and clients[0][1].checksum_enabled
            and message.error is None
        ):
            # One off-loop checksum, reused for every non-leader (the
            # inline per-send path would recompute it N-1 times ON the
            # event loop).
            from rayfed_tpu import native

            crc = await loop.run_in_executor(
                None, native.crc32c, message.payload
            )
        for pid, client in clients:
            try:
                await client.send_data(
                    [message.payload] if message.error is None else [],
                    message.upstream_seq_id,
                    message.downstream_seq_id,
                    crc=crc,
                    error=message.error,
                )
            except Exception as e:
                # A failed republish means the non-leader can never see
                # this value: the SPMD program WILL desync.  Loud path
                # (module docstring contract): escalate to the cleanup
                # watchdog (exit-on-failure semantics) instead of letting
                # the non-leader's recv park until its backstop.
                logger.exception(
                    "bridge republish to p%d failed (up=%s down=%s)",
                    pid, message.upstream_seq_id, message.downstream_seq_id,
                )
                # Poison the key ON the member: when the bridge itself
                # is reachable but this payload can't cross it (e.g. it
                # exceeds the bridge's message cap), the member's recv
                # must RAISE a RemoteError naming the failure instead
                # of hanging until its backstop.  A fully unreachable
                # bridge fails this too — then the member-side leader
                # watchdog is the backstop.
                try:
                    from rayfed_tpu.exceptions import RemoteError

                    await client.send_data(
                        [],
                        message.upstream_seq_id,
                        message.downstream_seq_id,
                        error=RemoteError(
                            "party-leader",
                            "BridgeRepublishError",
                            f"leader failed to republish "
                            f"({message.upstream_seq_id}, "
                            f"{message.downstream_seq_id}) to party "
                            f"process {pid}: {e}",
                        ).to_wire(),
                    )
                except Exception:
                    logger.exception(
                        "bridge republish poison to p%d also failed", pid
                    )
                if self.failure_handler is not None:
                    try:
                        self.failure_handler(LocalRef.from_value(False), e)
                    except Exception:  # pragma: no cover
                        logger.exception("republish failure handler raised")

    # -- proxy interface ------------------------------------------------------

    def send(self, dest_party, data, upstream_seq_id, downstream_seq_id,
             stream=None, round_tag=None, epoch_tag=None,
             quant_meta=None, blob_offer=False):
        # blob_offer is deliberately dropped: a multi-host party never
        # offers fingerprint handles — the RECEIVER may itself be a
        # multi-host group whose non-leader bridge processes cannot
        # pull, so its broadcasts stay eager pushes.
        del blob_offer
        if self._inner is not None:
            return self._inner.send(
                dest_party=dest_party,
                data=data,
                upstream_seq_id=upstream_seq_id,
                downstream_seq_id=downstream_seq_id,
                stream=stream,
                round_tag=round_tag,
                epoch_tag=epoch_tag,
                quant_meta=quant_meta,
            )
        # Non-leader: the leader's identical program does the real push.
        return LocalRef.from_value(True)

    def send_many(self, dest_parties, data, upstream_seq_id,
                  downstream_seq_id, stream=None, round_tag=None,
                  epoch_tag=None, quant_meta=None, blob_offer=False):
        """Fan-out broadcast (one shared encode) — leader only; see
        :meth:`TransportManager.send_many`.  ``blob_offer`` is dropped
        (see :meth:`send`): multi-host parties broadcast eagerly."""
        del blob_offer
        if self._inner is not None:
            return self._inner.send_many(
                dest_parties=dest_parties,
                data=data,
                upstream_seq_id=upstream_seq_id,
                downstream_seq_id=downstream_seq_id,
                stream=stream,
                round_tag=round_tag,
                epoch_tag=epoch_tag,
                quant_meta=quant_meta,
            )
        return {p: LocalRef.from_value(True) for p in dest_parties}

    def recv(self, src_party, upstream_seq_id, downstream_seq_id):
        if self._inner is not None:
            return self._inner.recv(
                src_party=src_party,
                upstream_seq_id=upstream_seq_id,
                downstream_seq_id=downstream_seq_id,
            )
        return self._bridge_mgr.recv(
            src_party=src_party,
            upstream_seq_id=upstream_seq_id,
            downstream_seq_id=downstream_seq_id,
        )

    def recv_stream(self, src_party, upstream_seq_id, downstream_seq_id,
                    sink):
        """Chunk-granular receive — leader only: the cross-party wire
        (and thus the chunk hook) exists on the leader process.  A
        non-leader coordinator process cannot stream-aggregate; use the
        one-shot ``fl.aggregate`` for multi-host parties until the
        bridge republish grows a chunk hook."""
        if self._inner is None:
            raise NotImplementedError(
                "streaming aggregation is not supported on non-leader "
                "processes of a multi-host party — aggregate with "
                "fl.aggregate there instead"
            )
        return self._inner.recv_stream(
            src_party, upstream_seq_id, downstream_seq_id, sink
        )

    def recv_stream_many(self, entries):
        """Batch chunk-sink registration — leader only, like
        :meth:`recv_stream` (same non-leader caveat)."""
        if self._inner is None:
            raise NotImplementedError(
                "streaming aggregation is not supported on non-leader "
                "processes of a multi-host party — aggregate with "
                "fl.aggregate there instead"
            )
        return self._inner.recv_stream_many(entries)

    def cancel_stream(self, upstream_seq_id, downstream_seq_id):
        if self._inner is not None:
            self._inner.cancel_stream(upstream_seq_id, downstream_seq_id)

    def _send_poison(self, dest_party, upstream_seq_id, downstream_seq_id,
                     exc):
        """Poison a promised rendezvous key on the consumer (see
        :meth:`TransportManager._send_poison`).  Leaders delegate to the
        real wire — without this, a multi-host leader's aggregation
        aborts (ring poison cascade, streaming result poison) would
        silently no-op and leave every peer parked until its backstop.
        Non-leaders resolve ``True`` like :meth:`send`: the leader's
        identical program delivers the real poison."""
        if self._inner is not None:
            return self._inner._send_poison(
                dest_party, upstream_seq_id, downstream_seq_id, exc
            )
        return LocalRef.from_value(True)

    def ping(self, dest_party: str, timeout_s: float = 1.0) -> bool:
        if self._inner is not None:
            return self._inner.ping(dest_party, timeout_s)
        return True  # non-leaders have no cross-party wire to check

    @property
    def roster(self):
        """The party's roster-epoch object (elastic membership) — the
        leader's real one; non-leaders get a local stub (quorum rounds
        are leader-driven, like streaming aggregation)."""
        if self._inner is not None:
            return self._inner.roster
        if self._nl_roster is None:
            from rayfed_tpu.transport.manager import RosterState

            self._nl_roster = RosterState([])
        return self._nl_roster

    def drain_membership_requests(self) -> list:
        if self._inner is not None:
            return self._inner.drain_membership_requests()
        return []

    @property
    def secagg_keys(self):
        """Secure-aggregation key agreement (transport/secagg.py) —
        leader-only, like every other cross-party plane: the leader's
        HELLO handshakes carry the party's key.  None on non-leaders;
        the fl.secagg entry points fail loudly on it (masked rounds are
        leader-driven, like streaming aggregation)."""
        if self._inner is not None:
            return self._inner.secagg_keys
        return None

    def ensure_secagg_peer_keys(self, parties, timeout_s: float = 30.0):
        if self._inner is None:
            raise NotImplementedError(
                "secure aggregation is leader-driven: non-leader "
                "processes of a multi-host party have no cross-party "
                "wire to agree keys over"
            )
        return self._inner.ensure_secagg_peer_keys(parties, timeout_s)

    @property
    def objects(self):
        """Content-addressed object plane (transport/objectstore.py) —
        leader-only like every cross-party plane: the leader's manager
        serves and pulls blobs.  None on non-leaders; handle resolution
        on one fails loudly (``objects.maybe_resolve_handle``) instead
        of handing user code a raw handle dict."""
        if self._inner is not None:
            return self._inner.objects
        return None

    @property
    def transfer_log(self):
        """Per-manager transfer records (rayfed_tpu/metrics.py) — the
        leader's wire view.  Non-leaders expose their bridge manager's
        log (its recv re-pushes ARE that process's transfers)."""
        if self._inner is not None:
            return self._inner.transfer_log
        bridge = getattr(self, "_bridge_mgr", None)
        return getattr(bridge, "transfer_log", None)

    def collect_trace(
        self, peer: str, rounds=None, timeout_s=None,
    ) -> tuple:
        """Cross-party trace pull (``fed.trace_collect``) — leader-only
        like every cross-party plane: the leader's manager holds the
        wire clients the TRACE_GET round trip rides.  Non-leaders have
        no cross-party transport and fail loudly (collect on the
        leader; the SERVING side works on every process that runs a
        manager, so multi-host parties can always be collected FROM)."""
        if self._inner is None:
            from rayfed_tpu import telemetry

            raise telemetry.TelemetryError(
                "non-leader process of a multi-host party has no "
                "cross-party wire transport to collect traces over — "
                "run fed.trace_collect on the party leader"
            )
        return self._inner.collect_trace(
            peer, rounds=rounds, timeout_s=timeout_s
        )

    def set_max_message_size(self, max_bytes: int) -> None:
        """Runtime message-size cap mutation, party-wide and atomic.

        A multi-host party must move the cap on EVERY process at once:
        the leader's wire server/clients AND each sibling's bridge
        server — a leader that accepted a newly-allowed large payload
        while one bridge server kept the init-time cap would have its
        republish fatally rejected there, silently desyncing the SPMD
        program.  This is therefore a **collective**: every process of
        the party calls ``fed.set_max_message_length`` at the same
        program point (like any other SPMD collective).

        Protocol: enter-barrier (no process still has a pre-call send
        in flight once all have arrived) → the leader applies to its
        real manager (which itself rejects on in-flight cross-party
        sends) and its bridge republish clients, then publishes an
        ``ok``/``err:...`` verdict on the coordination KV → non-leaders
        fetch the verdict and apply to their bridge manager only on
        ``ok`` → exit-barrier.  On an ``err`` verdict every process
        raises the same ``RuntimeError``, so a rejected mutation leaves
        the whole party on the old cap — never torn across processes.
        """
        max_bytes = int(max_bytes)
        if max_bytes <= 0:
            raise ValueError(
                f"max message length must be positive, got {max_bytes}"
            )
        if self._group.num_processes <= 1:
            if self._inner is not None:
                self._inner.set_max_message_size(max_bytes)
            return

        seq = next(self._msgcap_seq)
        verdict_key = f"{_BRIDGE_PREFIX}_msgcap/{seq}"
        self._group.barrier(f"rfw_msgcap_enter_{seq}")
        if self._group.is_leader:
            verdict = "ok"
            try:
                self._leader_apply_cap(max_bytes)
            except Exception as e:
                verdict = f"err:{e}"
            self._group.key_value_set(verdict_key, verdict)
        else:
            verdict = self._group.blocking_key_value_get(verdict_key, 120.0)
            if verdict == "ok" and self._bridge_mgr is not None:
                # Bridge managers never originate sends, so the inner
                # inflight guard is vacuous here — this is a plain
                # server/job-config cap update on the bridge listener.
                self._bridge_mgr.set_max_message_size(max_bytes)
        self._group.barrier(f"rfw_msgcap_exit_{seq}")
        if verdict != "ok":
            raise RuntimeError(
                f"set_max_message_length rejected for multi-host party "
                f"(no process applied it): {verdict[4:]}"
            )

    def _leader_apply_cap(self, max_bytes: int) -> None:
        """Leader side of the cap collective: real manager + bridge
        republish clients.  The bridge inflight check runs FIRST so a
        busy bridge rejects before the inner manager mutates — inside
        the enter-barrier no process is issuing new sends, so the
        check-then-apply window cannot readmit traffic."""

        async def _check_bridge():
            busy = sorted(
                pid
                for pid, c in self._bridge_clients.items()
                if c.has_inflight_sends()
            )
            if busy:
                raise RuntimeError(
                    f"cannot change max message length while bridge "
                    f"republishes are in flight to party processes "
                    f"{busy}; retry after the round completes"
                )

        async def _apply_bridge():
            for c in self._bridge_clients.values():
                c._max_message_size = max_bytes

        loop = self._inner._loop
        if self._bridge_clients:
            asyncio.run_coroutine_threadsafe(
                _check_bridge(), loop
            ).result(timeout=30)
        self._inner.set_max_message_size(max_bytes)
        if self._bridge_clients:
            asyncio.run_coroutine_threadsafe(
                _apply_bridge(), loop
            ).result(timeout=30)

    def effective_transport_options(self, dest_party: str) -> Dict[str, Any]:
        if self._inner is not None:
            return self._inner.effective_transport_options(dest_party)
        return {
            "party": dest_party,
            "options": {},
            "ignored_keys": [],
            "metadata": {},
            "note": "non-leader process: no cross-party wire",
        }

    def get_stats(self) -> Dict[str, Any]:
        mgr = self._inner if self._inner is not None else self._bridge_mgr
        stats = mgr.get_stats() if mgr is not None else {}
        stats["party_process_id"] = self._group.process_id
        stats["party_num_processes"] = self._group.num_processes
        return stats

    def stop(self) -> None:
        if self._inner is not None:
            self._inner.stop()  # also cancels bridge-client tasks (same loop)
        if self._bridge_mgr is not None:
            self._bridge_mgr.stop()
        self._group.cleanup()
        self._group.shutdown()
