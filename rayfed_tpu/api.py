"""Public API: ``init`` / ``shutdown`` / ``remote`` / ``get`` / ``kill``.

Capability parity with reference ``fed/api.py``, redesigned for a
single-controller-per-party TPU runtime: ``init`` stands up the party's
Runtime (executor + transport proxies + cleanup watchdog + optional local
device mesh) instead of a Ray cluster; config lives on the Runtime rather
than a GCS KV; ``@remote`` tasks dispatch to (optionally jit-compiled) JAX
callables on the party's devices.
"""

from __future__ import annotations

import functools
import inspect
import logging
import time
from typing import Any, Dict, List, Optional, Union

from rayfed_tpu import utils as fed_utils
from rayfed_tpu.actor import FedActorHandle
from rayfed_tpu.call_holder import FedCallHolder
from rayfed_tpu.cleanup import CleanupManager
from rayfed_tpu.config import (
    DEFAULT_MAX_MESSAGE_SIZE,
    ClusterConfig,
    JobConfig,
    PartyConfig,
    RetryPolicy,
)
from rayfed_tpu.executor import LocalRef, is_local_refs
from rayfed_tpu.fed_object import FedObject
from rayfed_tpu.runtime import (
    Runtime,
    get_runtime,
    get_runtime_or_none,
    set_current_runtime,
)
from rayfed_tpu.transport.manager import TransportManager
from rayfed_tpu.utils.logging_utils import set_thread_party, setup_logger

logger = logging.getLogger(__name__)


def init(
    address: Optional[str] = None,
    cluster: Optional[Dict] = None,
    party: Optional[str] = None,
    tls_config: Optional[Dict] = None,
    logging_level: str = "info",
    cross_silo_retry_policy: Optional[Dict] = None,
    cross_silo_grpc_retry_policy: Optional[Dict] = None,  # reference-compat alias
    cross_silo_send_max_retries: Optional[int] = None,
    cross_silo_serializing_allowed_list: Optional[Dict] = None,
    exit_on_failure_cross_silo_sending: bool = False,
    cross_silo_messages_max_size_in_bytes: Optional[int] = None,
    cross_silo_timeout_in_seconds: float = 60,
    recv_backstop_in_seconds: Optional[float] = None,
    mailbox_ttl_in_seconds: Optional[float] = None,
    peer_failfast: bool = True,
    peer_health_interval_in_seconds: Optional[float] = None,
    peer_death_pings: Optional[int] = None,
    enable_waiting_for_other_parties_ready: bool = False,
    global_metadata: Optional[Dict] = None,
    grpc_metadata: Optional[Dict] = None,  # reference-compat alias
    mesh: Optional[Any] = None,
    mesh_shape: Optional[Dict[str, int]] = None,
    max_workers: int = 16,
    device_put_received: bool = True,
    process_default: bool = True,
    coordinator_address: Optional[str] = None,
    num_party_processes: Optional[int] = None,
    party_process_id: Optional[int] = None,
    trace: Optional[bool] = None,
    trace_capacity: Optional[int] = None,
    **kwargs,
) -> Runtime:
    """Initialize this party's controller.

    Reference-parity arguments follow ``fed/api.py:38-228``; the cluster
    dict has the same shape (``address``, optional ``listen_addr``,
    per-party ``metadata``/``grpc_metadata`` and
    ``transport_options``/``grpc_options``).  ``address`` exists for
    drop-in compat and accepts 'local'/None — there is no external cluster
    to join: the controller process *is* the party runtime.

    TPU-native arguments:

    - ``mesh``: a ``jax.sharding.Mesh`` for this party's devices, or
    - ``mesh_shape``: e.g. ``{'dp': 2, 'tp': 4}`` to build one over the
      locally visible devices (see :mod:`rayfed_tpu.parallel.mesh`);
    - ``device_put_received``: place received array payloads onto local
      devices eagerly;
    - ``peer_failfast`` (+ ``peer_health_interval_in_seconds``,
      ``peer_death_pings``): while recvs are parked on a party, ping its
      transport; after N consecutive failures the parked ``fed.get``
      raises :class:`~rayfed_tpu.exceptions.RemoteError` naming the dead
      party instead of waiting out the recv backstop;
    - ``process_default``: also register this runtime as the process-wide
      default (disable when simulating multiple parties in one process);
    - ``coordinator_address`` + ``num_party_processes`` +
      ``party_process_id``: this party spans several JAX processes (a
      multi-host pod slice).  ``jax.distributed`` is initialized so the
      party's mesh covers every host; only process 0 runs the cross-party
      wire transport and the other processes receive pushed values through
      the party process bridge (see :mod:`rayfed_tpu.distributed`).
    """
    assert cluster, "Cluster should be provided."
    assert party, "Party should be provided."
    assert party in cluster, f"Party {party} is not in cluster {cluster}."

    # Deterministic fault injection (tests/benches): a JSON schedule in
    # $RAYFED_CHAOS arms the transport/driver chaos hooks for this
    # process.  A no-op unless the variable is set.
    from rayfed_tpu import chaos as _chaos

    _chaos.maybe_install_from_env()

    # Flight recorder (rayfed_tpu/telemetry.py): RAYFED_TRACE=1 arms the
    # span ring like RAYFED_CHAOS arms faults; an env-armed (or
    # pre-armed) recorder without a party adopts this one.  The
    # JobConfig knob arms it below, once job_config exists.
    from rayfed_tpu import telemetry as _telemetry

    _telemetry.maybe_install_from_env(party=party)

    fed_utils.validate_address(address)
    fed_utils.validate_cluster_info(cluster)

    tls_config = tls_config or None
    if tls_config:
        from rayfed_tpu.transport.tls import validate_tls_config

        validate_tls_config(tls_config)

    retry_dict = cross_silo_retry_policy or cross_silo_grpc_retry_policy
    retry_policy = RetryPolicy.from_dict(retry_dict)
    if cross_silo_send_max_retries is not None:
        retry_policy.max_attempts = int(cross_silo_send_max_retries)

    cluster_config = ClusterConfig(
        parties={p: PartyConfig.from_dict(cfg) for p, cfg in cluster.items()},
        current_party=party,
        tls_config=tls_config,
        serializing_allowed_list=cross_silo_serializing_allowed_list,
    )
    job_config = JobConfig(
        cross_silo_timeout_s=float(cross_silo_timeout_in_seconds),
        cross_silo_messages_max_size=(
            int(cross_silo_messages_max_size_in_bytes)
            if cross_silo_messages_max_size_in_bytes is not None
            else DEFAULT_MAX_MESSAGE_SIZE
        ),
        retry_policy=retry_policy,
        metadata=dict(global_metadata or grpc_metadata or {}),
        exit_on_failure_sending=exit_on_failure_cross_silo_sending,
        wait_for_ready=enable_waiting_for_other_parties_ready,
        device_put_received=device_put_received,
    )
    if recv_backstop_in_seconds is not None:
        job_config.recv_backstop_s = float(recv_backstop_in_seconds)
    if mailbox_ttl_in_seconds is not None:
        job_config.mailbox_ttl_s = float(mailbox_ttl_in_seconds)
    job_config.peer_failfast = bool(peer_failfast)
    if peer_health_interval_in_seconds is not None:
        job_config.peer_health_interval_s = float(peer_health_interval_in_seconds)
    if peer_death_pings is not None:
        job_config.peer_death_pings = int(peer_death_pings)
    if trace is not None:
        job_config.trace = bool(trace)
    if trace_capacity is not None:
        job_config.trace_capacity = int(trace_capacity)
    if job_config.trace and _telemetry.installed() is None:
        _telemetry.install(party=party, capacity=job_config.trace_capacity)
    elif trace_capacity is not None and _telemetry.installed() is not None:
        # An env-armed (or test-installed) recorder already exists; an
        # EXPLICIT capacity request must still take effect — resize in
        # place (newest records kept) instead of silently ignoring it.
        _telemetry.installed().resize(int(trace_capacity))

    party_group = None
    if coordinator_address is not None:
        from rayfed_tpu.distributed import PartyProcessGroup

        if num_party_processes is None or party_process_id is None:
            raise ValueError(
                "coordinator_address requires num_party_processes and "
                "party_process_id"
            )
        # Must run before any JAX backend use so the global device view
        # spans the whole party.
        party_group = PartyProcessGroup(
            coordinator_address, num_party_processes, party_process_id
        )

    if mesh is None and mesh_shape is not None:
        from rayfed_tpu.parallel.mesh import create_mesh

        mesh = create_mesh(mesh_shape)

    runtime = Runtime(
        cluster_config=cluster_config,
        job_config=job_config,
        max_workers=max_workers,
        mesh=mesh,
    )
    set_current_runtime(runtime, process_default=process_default)
    set_thread_party(party)

    setup_logger(logging_level=logging_level, party=party)

    runtime.cleanup_manager = CleanupManager(
        exit_on_failure_sending=exit_on_failure_cross_silo_sending
    )
    runtime.cleanup_manager.start()

    if party_group is not None:
        from rayfed_tpu.distributed import MultiHostTransport

        inner = None
        if party_group.is_leader:
            inner = TransportManager(cluster_config, job_config)
            inner.mesh_provider = lambda: runtime.mesh
            # NOT started here: MultiHostTransport must install its
            # republish hook before the listener accepts the first frame.
        transport = MultiHostTransport(
            inner,
            party_group,
            allowed=cluster_config.serializing_allowed_list,
            device_put_received=device_put_received,
            # Same backstop as the leader's wire recv — the party's
            # processes must time out together or not at all (a lone
            # non-leader failure desyncs the SPMD program).
            timeout_s=job_config.recv_backstop_s,
            mesh_provider=lambda: runtime.mesh,
            job_config=job_config,
            tls_config=tls_config,
            # The party's advertised address IS the leader's listener:
            # non-leaders watchdog it so leader death poisons their
            # parked bridge recvs within the death deadline.
            leader_address=cluster_config.party_config(party).address,
        )
        # A fatal bridge republish is a send failure for watchdog
        # purposes: exit-on-failure applies to the intra-party bridge too.
        transport.failure_handler = (
            lambda ref, exc: runtime.cleanup_manager.push_to_sending(ref)
        )
    else:
        transport = TransportManager(cluster_config, job_config)
        transport.mesh_provider = lambda: runtime.mesh
        transport.start()
    runtime.send_proxy = transport
    runtime.recv_proxy = transport
    runtime.transport = transport

    # Pre-warm the fl package ON THIS THREAD, before any cross-thread
    # traffic exists: metrics_snapshot() and the encode/decode paths
    # all lazy-import fl submodules from worker threads, and two FIRST
    # imports racing across threads can observe a partially initialized
    # package (import deadlock-avoidance surfaces as KeyError
    # 'rayfed_tpu.fl' / "partially initialized module").  One eager
    # import here makes every later lookup a sys.modules hit.
    import rayfed_tpu.fl  # noqa: F401

    if enable_waiting_for_other_parties_ready:
        ping_others(cluster=cluster, self_party=party, max_retries=3600)
    logger.info("Started rayfed_tpu runtime for party %s.", party)
    return runtime


def ping_others(cluster: Dict[str, Dict], self_party: str, max_retries: int = 3600):
    """Ping other parties until all are ready (ref ``barriers.py:441-466``)."""
    runtime = get_runtime()
    transport: TransportManager = runtime.transport
    others = [p for p in cluster if p != self_party]
    tried = 0
    while tried < max_retries and others:
        logger.info(
            "Try ping %s at attempt %d, up to %d attempts.", others, tried, max_retries
        )
        tried += 1
        others = [o for o in others if not transport.ping(o, timeout_s=1.0)]
        if others:
            # fedlint: disable=FED001 — sync init-time retry loop on the caller's thread, before any round traffic; the transport event loop runs in its own thread and is never blocked by this wait
            time.sleep(2)
    if others:
        raise RuntimeError(
            f"Failed to wait for parties: {others} to start, abort `fed.init`."
        )
    return True


def set_max_message_length(max_bytes: int) -> None:
    """Mutate the cross-silo message-size cap AFTER ``init`` (parity
    with adjusting the reference's ``grpc.max_send_message_length`` /
    ``max_receive_message_length`` channel options, but live).

    Applies atomically to this party's transport server and every live
    per-peer client, and to clients created later.  Raises
    ``RuntimeError`` while any cross-party send is mid-flight — the cap
    change must reject cleanly rather than torn-apply to a payload
    already on the wire (drain with ``fed.get`` on the pending sends,
    or retry after the round completes).  Each party controls its own
    caps; lower both sides when actually shrinking a limit.

    On a multi-host party this is a **collective**: every process of
    the party must call it at the same program point (like any SPMD
    collective).  The processes rendezvous on a coordination-service
    barrier, the leader applies the cap to the cross-party wire and its
    bridge republish clients and publishes an ok/err verdict, and the
    siblings apply it to their bridge servers only on ok — a rejected
    mutation (e.g. in-flight sends) raises the same ``RuntimeError`` on
    every process and leaves the whole party on the old cap.
    """
    runtime = get_runtime()
    transport = getattr(runtime, "transport", None)
    if transport is None:
        raise RuntimeError("transport not started; call fed.init() first")
    # The manager also updates runtime.job_config (the same object), so
    # future clients inherit the new cap — one writer, no duplicate here.
    transport.set_max_message_size(int(max_bytes))


def trace_collect(
    rounds: Optional[Any] = None,
    parties: Optional[List[str]] = None,
    timeout: Optional[float] = None,
) -> Dict[str, Any]:
    """Pull every peer's flight-recorder ring window and merge with the
    local one into ONE cross-party timeline (``rayfed_tpu.telemetry``).

    ``rounds``: None (whole rings), an int, or an inclusive ``(lo, hi)``
    range of round tags; records carrying no round tag (mailbox waits,
    chaos wire faults) are always included.  ``parties``: restrict the
    peer set (default: every other cluster party).  Peers whose pull
    fails (dead, unreachable, pre-telemetry build) or whose recorder is
    disarmed land in ``missing`` with the reason — a partial timeline
    is returned, never an exception for a single dead peer; ``parties``
    and ``missing`` are disjoint.  Peers are pulled concurrently, so the
    collection wall is ~one ``timeout`` even with several peers down.

    Peer clocks are aligned onto THIS party's timeline with the
    NTP-style offset estimated from each collection round trip (error
    bound RTT/2, reported per peer in ``clock_offsets`` —
    :func:`rayfed_tpu.telemetry.estimate_clock_offset`).

    Returns ``{"collector", "records", "clock_offsets", "parties",
    "missing"}`` where ``records`` is the merged, time-sorted list of
    record dicts — feed it to
    :func:`rayfed_tpu.telemetry.to_trace_events` for a Chrome/Perfetto
    ``trace_event`` JSON export, or to ``tool/trace_report.py`` for a
    critical-path round report.  Works with the recorder disarmed
    locally (you still get the peers' windows); multi-host non-leader
    processes have no wire transport and raise loudly.
    """
    from rayfed_tpu import telemetry

    runtime = get_runtime()
    transport = runtime.transport
    me = runtime.party
    if not hasattr(transport, "collect_trace"):
        raise telemetry.TelemetryError(
            "this process has no cross-party wire transport to collect "
            "traces over (multi-host non-leader bridges cannot pull — "
            "run fed.trace_collect on the party leader)"
        )
    rec = telemetry.installed()
    local = rec.records(rounds=rounds) if rec is not None else []
    local = [r for r in local if r.party is None or r.party == me]
    peers = [
        p for p in (
            parties if parties is not None
            else list(runtime.cluster_config.parties)
        )
        if p != me
    ]
    party_records: Dict[str, list] = {me: local}
    offsets: Dict[str, Dict[str, float]] = {
        me: {"offset_s": 0.0, "rtt_s": 0.0, "bound_s": 0.0}
    }
    missing: Dict[str, str] = {}
    # Pull peers CONCURRENTLY: each pull is an independent request/
    # reply round trip, and a dead/unreachable peer costs its full
    # per-peer timeout — serialized, N dead peers would stack N
    # timeouts into the collection wall (exactly the post-chaos
    # situation this API exists to diagnose).  Concurrent, the wall is
    # ~one timeout regardless of how many peers are down.
    from concurrent.futures import ThreadPoolExecutor

    def _pull(p: str):
        return transport.collect_trace(p, rounds=rounds, timeout_s=timeout)

    if peers:
        with ThreadPoolExecutor(
            max_workers=min(len(peers), 8),
            thread_name_prefix="rayfed-trace-collect",
        ) as pool:
            futures = {p: pool.submit(_pull, p) for p in peers}
            for p in peers:
                try:
                    records, offset, rep = futures[p].result()
                except Exception as exc:
                    logger.warning(
                        "[%s] trace collection from %s failed: %r",
                        me, p, exc,
                    )
                    missing[p] = repr(exc)
                    continue
                if not rep["armed"] and not records:
                    # "parties" and "missing" are disjoint by contract:
                    # a disarmed peer contributed nothing, so it belongs
                    # in missing ONLY (consumers count parties as
                    # collected).
                    missing[p] = "recorder not armed"
                    continue
                party_records[p] = records
                offsets[p] = offset
    merged = telemetry.merge_records(party_records, offsets)
    return {
        "collector": me,
        "records": merged,
        "clock_offsets": offsets,
        "parties": sorted(party_records),
        "missing": missing,
    }


def metrics_snapshot() -> Dict[str, Any]:
    """Every subsystem's counters under one documented schema
    (``rayfed_tpu.metrics.METRICS_SCHEMA``): ``transport``, ``secagg``,
    ``object_plane``, ``telemetry``, ``quorum``.  See
    :func:`rayfed_tpu.metrics.metrics_snapshot`."""
    from rayfed_tpu.metrics import metrics_snapshot as _snapshot

    return _snapshot()


def join(coordinator: Optional[str] = None,
         timeout: Optional[float] = None) -> dict:
    """(Re)join an in-progress quorum run — elastic membership's entry
    door.  Sends a join request to the run's coordinator and parks until
    its next round boundary returns the **welcome ticket** (round index,
    session, roster epoch — applied to this runtime before returning —
    the current coordinator lease holder, and the current global model).
    Pass the ticket to ``fl.run_fedavg_rounds(..., quorum=k,
    join_ticket=ticket)`` to enter the loop; no other party restarts
    anything.  ``coordinator`` must name the run's CURRENT coordinator
    (after a failover, the announced successor).  See
    :mod:`rayfed_tpu.fl.quorum`.
    """
    from rayfed_tpu.fl.quorum import join_cluster

    return join_cluster(coordinator=coordinator, timeout=timeout)


def leave() -> None:
    """Gracefully leave an in-progress quorum run at the next round
    boundary.  The departure is announced by the coordinator (roster
    epoch advance) and this party's ``run_fedavg_rounds`` returns the
    last broadcast model once the roster drops it — it still
    participates in the round in flight.  When the COORDINATOR leaves,
    it completes its in-flight round and hands the coordinator lease to
    the announced successor (loud failure only when no successor is
    alive).  See :mod:`rayfed_tpu.fl.quorum`."""
    from rayfed_tpu.fl.quorum import request_leave

    request_leave()


def shutdown() -> None:
    """Shutdown this party's runtime (ref ``api.py:231-241``)."""
    runtime = get_runtime_or_none()
    if runtime is None:
        return
    if runtime.cleanup_manager is not None:
        runtime.cleanup_manager.wait_sending()
    if getattr(runtime, "transport", None) is not None:
        runtime.transport.stop()
    runtime.shutdown_actors()
    runtime.executor.shutdown(wait=False)
    set_current_runtime(None)
    set_thread_party(None)
    logger.info("Shutdowned rayfed_tpu.")


def _get_cluster():
    return get_runtime().cluster_config.cluster_addresses


def _get_party():
    return get_runtime().party


def _get_tls():
    return get_runtime().cluster_config.tls_config


class FedRemoteFunction:
    def __init__(self, func_or_class) -> None:
        self._node_party: Optional[str] = None
        self._func_body = func_or_class
        self._options: dict = {}
        self._fed_call_holder: Optional[FedCallHolder] = None

    def party(self, party: str) -> "FedRemoteFunction":
        self._node_party = party
        self._fed_call_holder = FedCallHolder(
            get_runtime(), self._node_party, self._execute_impl, self._options
        )
        return self

    def options(self, **options) -> "FedRemoteFunction":
        self._options = options
        if self._fed_call_holder:
            self._fed_call_holder.options(**options)
        return self

    def remote(self, *args, **kwargs):
        assert (
            self._node_party is not None
        ), "A fed function should be specified within a party to execute."
        return self._fed_call_holder.internal_remote(*args, **kwargs)

    def _execute_impl(self, args: tuple, kwargs: dict):
        runtime = get_runtime()
        num_returns = int(self._options.get("num_returns", 1))
        return runtime.executor.submit(
            self._func_body, args, kwargs, num_returns=num_returns
        )


class FedRemoteClass:
    def __init__(self, func_or_class) -> None:
        self._party: Optional[str] = None
        self._cls = func_or_class
        self._options: dict = {}

    def party(self, party: str) -> "FedRemoteClass":
        self._party = party
        return self

    def options(self, **options) -> "FedRemoteClass":
        self._options = options
        return self

    def remote(self, *cls_args, **cls_kwargs) -> FedActorHandle:
        runtime = get_runtime()
        fed_class_task_id = runtime.next_seq_id()
        fed_actor_handle = FedActorHandle(
            runtime,
            fed_class_task_id,
            self._cls,
            self._party,
            self._options,
        )
        fed_call_holder = FedCallHolder(
            runtime, self._party, fed_actor_handle._execute_impl, self._options
        )
        fed_call_holder.internal_remote(*cls_args, **cls_kwargs)
        return fed_actor_handle


def _is_cython_callable(obj) -> bool:
    """Cython-compiled functions (reference ``utils.py:131-144`` accepts
    them): not caught by ``inspect.isfunction``; identified by the type
    name ``cython_function_or_method`` on the object itself or — for
    Cython 3 bound methods, which expose ``__func__`` rather than
    ``func_name`` — on its underlying function."""

    def _is_cython_type(o) -> bool:
        return type(o).__name__ == "cython_function_or_method"

    return _is_cython_type(obj) or (
        hasattr(obj, "__func__") and _is_cython_type(obj.__func__)
    )


def remote(*args, **kwargs):
    """``@fed.remote`` decorator for functions and classes (ref ``api.py:332-350``)."""

    def _make_fed_remote(function_or_class, **options):
        if (
            inspect.isfunction(function_or_class)
            or inspect.isbuiltin(function_or_class)
            or _is_cython_callable(function_or_class)
        ):
            return FedRemoteFunction(function_or_class).options(**options)
        if inspect.isclass(function_or_class):
            return FedRemoteClass(function_or_class).options(**options)
        raise TypeError(
            "The @fed.remote decorator must be applied to either a function or a class."
        )

    if len(args) == 1 and len(kwargs) == 0 and callable(args[0]):
        return _make_fed_remote(args[0])
    assert len(args) == 0 and len(kwargs) > 0, "Remote args error."
    return functools.partial(_make_fed_remote, **kwargs)


def get(
    fed_objects: Union[LocalRef, FedObject, List[FedObject]],
    timeout: Optional[float] = None,
) -> Any:
    """Fetch real data of fed objects (ref ``api.py:353-421``).

    Owned objects are broadcast (pushed) to every other party not already
    holding them; unowned objects park on a recv keyed by the shared fake
    seq id allocated identically on all parties.
    """
    if is_local_refs(fed_objects):
        if isinstance(fed_objects, list):
            return [r.resolve(timeout=timeout) for r in fed_objects]
        return fed_objects.resolve(timeout=timeout)

    runtime = get_runtime()
    from rayfed_tpu.proxy import recv_on_runtime, send_many_on_runtime

    # Fake fed_task_id allocated on EVERY party to keep counters aligned
    # (ref api.py:368) — the determinism contract.
    fake_fed_task_id = runtime.next_seq_id()
    cluster_parties = list(runtime.cluster_config.parties)
    current_party = runtime.party
    is_individual_id = isinstance(fed_objects, FedObject)
    if is_individual_id:
        fed_objects = [fed_objects]

    refs: List[LocalRef] = []
    for fed_object in fed_objects:
        if isinstance(fed_object, LocalRef):
            refs.append(fed_object)
            continue
        if fed_object.get_party() == current_party:
            local_ref = fed_object.get_local_ref()
            assert local_ref is not None
            refs.append(local_ref)
            # Exactly-once broadcast dedup (ref api.py:389-394), then one
            # fan-out push: the payload is encoded/checksummed once and
            # streamed to every pending peer concurrently.
            pending = [
                party_name
                for party_name in cluster_parties
                if party_name != current_party
                and fed_object._mark_if_not_sending_to_party(party_name)
            ]
            if pending:
                send_many_on_runtime(
                    runtime,
                    dest_parties=pending,
                    data=local_ref,
                    upstream_seq_id=fed_object.get_fed_task_id(),
                    downstream_seq_id=fake_fed_task_id,
                    # Large immutable objects (plain PackedTrees at or
                    # above JobConfig.blob_broadcast_min_bytes) ship as
                    # fingerprint handles: receivers with a content-
                    # cache hit transfer ZERO payload bytes, misses
                    # pull from this owner (transport/objectstore.py).
                    blob_offer=True,
                )
        else:
            cached = fed_object.get_local_ref()
            if cached is not None:
                refs.append(cached)
            else:
                from rayfed_tpu.objects import maybe_resolve_handle

                plane = getattr(runtime.transport, "objects", None)
                received = recv_on_runtime(
                    runtime,
                    src_party=fed_object.get_party(),
                    upstream_seq_id=fed_object.get_fed_task_id(),
                    curr_seq_id=fake_fed_task_id,
                ).then(
                    # A broadcast that arrived as a fingerprint handle
                    # resolves through the object plane (cache hit =
                    # zero-copy, miss = BLOB_GET pull); ordinary
                    # payloads pass through untouched.  A cold pull
                    # BLOCKS for a holder round trip, so it runs on the
                    # plane's dedicated fetch pool — never the shared
                    # codec pool, which must stay free to decode and to
                    # SERVE the symmetric pulls of other parties.
                    lambda v: maybe_resolve_handle(runtime.transport, v),
                    executor=(
                        plane.fetch_executor if plane is not None else None
                    ),
                )
                fed_object._cache_local_ref(received)
                refs.append(received)

    values = [r.resolve(timeout=timeout) for r in refs]
    if is_individual_id:
        values = values[0]
    return values


def kill(actor: FedActorHandle, *, no_restart: bool = True) -> None:
    """Kill a fed actor — only effective in its owning party (ref ``api.py:424-428``)."""
    del no_restart  # no restart semantics in the in-process substrate
    runtime = get_runtime()
    if actor._node_party == runtime.party:
        actor._kill()
