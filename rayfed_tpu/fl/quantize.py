"""Shared-grid integer quantization: the compressed-domain wire codec.

The PR 1-5 comms campaign made the *wire* cheap (packed-tree codec,
delta cache, striping) but the *fold* still paid full price: every
quantized chunk was dequantized to f32 before it touched the donated
accumulator, so aggregation memory traffic scaled with the f32 model.
Per THC (arXiv:2302.08545), a **shared quantization grid** makes the
sum commute with the encoding::

    sum_i w_i * x_i  ==  scale_b * (sum_i w_i * q_i  -  zp_b * W)      (*)

where every party quantizes block ``b`` of its packed update with the
SAME per-block affine grid ``x ~ scale_b * (q - zp_b)`` and
``W = sum_i w_i``.  The aggregator then folds the **integer codes**
(a widening i32 multiply-add — exact, associative) and applies ONE
fused rescale at finalize.  Bytes on the wire drop to the integer
width (uint8 = half of bf16) and the fold's HBM traffic drops with
them.

This module is the **codec half** of the compressed-domain split:

- :class:`QuantGrid` — the per-round shared grid (scale/zero-point per
  :func:`rayfed_tpu.fl.fedavg.packed_block_grid` block — the single
  canonical chunking every fold schedule already uses).  Derived
  deterministically from a reference buffer every controller holds
  identically, so "negotiation" is a pure function: the coordinator's
  grid and every party's grid are bit-identical by construction, the
  compact descriptor rides every quantized frame's metadata
  (``wire.QUANT_GRID_KEY``), and the aggregator REJECTS any
  contribution whose grid fingerprint differs from its own.  The round
  loop uses ``mode="delta"``: parties code ``update − shared model``
  on a grid ranged by the PREVIOUS round's observed aggregate delta —
  per-round updates are orders of magnitude smaller than the params,
  so the 8-bit step resolves the learning signal itself and converged
  accuracy matches the bf16 baseline (coding absolute params on a
  model-ranged grid drowns the update in the grid step; measured: it
  stalls training completely).  The first round, with no observed
  delta, runs unquantized.
- :class:`QuantizedPackedTree` — the wire form: the packed buffer's
  integer codes + the grid's scale/zero-point vectors riding alongside
  (so a delta-base re-seed, a late retry or a rejoining party always
  carries its grid with it), registered as a JAX pytree like
  :class:`~rayfed_tpu.fl.compression.PackedTree`.
- :class:`QuantCompressor` — the sender-side error-feedback state: the
  residual the grid dropped this round is added back next round (same
  EF14 scheme as :class:`~rayfed_tpu.fl.compression.ErrorFeedback`),
  which is what keeps 8-bit wire convergent with the bf16 baseline.
  Quantization is two-phase (``quantize`` → ``commit``/``rollback``) so
  a ring round that aborts and re-aggregates over the coordinator
  topology re-quantizes the SAME update with the SAME residual instead
  of double-applying it.

The **aggregator half** lives where the folding already lives:
:func:`rayfed_tpu.fl.fedavg.packed_quantized_sum` /
:func:`~rayfed_tpu.fl.fedavg.quantized_accum_kernel` /
:func:`~rayfed_tpu.fl.fedavg.finalize_packed_quantized` (the one-shot
reduce, the donated-i32 chunk kernel and the single fused rescale) and
the integer-accumulate paths of
:class:`rayfed_tpu.fl.streaming.StreamingAggregator` /
:class:`~rayfed_tpu.fl.streaming.StripeAggregator`.  Codecs know
nothing about folding; aggregators select their fold kernel from the
codec's wire form — that seam is the codec/aggregator split.

Overflow headroom (i32 widening bound vs party count): a folded code
is bounded by ``qabs_max = max(|qmin|, |qmax|)`` (255 for uint8), so
the i32 accumulator holds ``|acc| <= qabs_max * W``.  The integer path
therefore requires non-negative **integral** weights (FedAvg example
counts) with ``qabs_max * W <= 2**31 - 1`` — W up to ~8.4M at uint8,
validated loudly at aggregator construction.  W also stays exactly
representable in the f32 finalize (8.4M < 2**24 * 2 is not enough on
its own; 2**31/255 ≈ 8.42e6 < 2**24 ≈ 16.7M is).
"""

from __future__ import annotations

import functools
import json
import zlib
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from rayfed_tpu.fl.compression import PackedTree, PackSpec

# Version of the shared-grid descriptor/semantics.  Bump when the grid
# schema (``grid_descriptor``) or the quantization transfer function
# changes — ``tool/check_wire_format.py`` fingerprints both, so drift
# without a bump fails the build like any wire drift.
QUANT_GRID_VERSION = 1

# Headroom factor for compressed-domain uplink grids: the grid range is
# the previous round's aggregate delta expanded by this — per-party
# deltas overshoot their mean (the mean averages them down), and what
# still clips rides the error-feedback residual into the next round.
# Shared by every driver loop (classic streaming, ring, quorum) so the
# grids they derive from identical reference buffers stay bit-identical.
QUANT_DELTA_EXPAND = 4.0

# Integer wire dtypes the grid supports → (qmin, qmax).
_QRANGES: Dict[str, Tuple[int, int]] = {
    "uint8": (0, 255),
    "int8": (-128, 127),
}


def _qrange(wire_dtype: str) -> Tuple[int, int]:
    try:
        return _QRANGES[wire_dtype]
    except KeyError:
        raise ValueError(
            f"unsupported quantized wire dtype {wire_dtype!r} — one of "
            f"{sorted(_QRANGES)}"
        ) from None


class QuantGrid:
    """The per-round shared quantization grid.

    ``scales``/``zps``: one f32 scale and zero-point per canonical
    packed-buffer block (:func:`~rayfed_tpu.fl.fedavg.packed_block_grid`
    over ``total_elems`` at ``chunk_elems`` granularity).  Code ``q`` of
    block ``b`` represents ``scales[b] * (q - zps[b])``.

    Every controller must hold a bit-identical grid for the round —
    :func:`make_round_grid` guarantees that when fed the identical
    reference buffer; :meth:`fingerprint` is what receivers compare.
    """

    __slots__ = ("scales", "zps", "chunk_elems", "total_elems",
                 "wire_dtype", "mode", "_fp")

    def __init__(self, scales: np.ndarray, zps: np.ndarray,
                 chunk_elems: int, total_elems: int,
                 wire_dtype: str = "uint8", mode: str = "delta") -> None:
        from rayfed_tpu.fl.fedavg import packed_block_grid

        _qrange(wire_dtype)
        if mode not in ("abs", "delta"):
            raise ValueError(
                f"grid mode must be 'abs' or 'delta', got {mode!r}"
            )
        self.mode = mode
        self.scales = np.ascontiguousarray(scales, np.float32)
        self.zps = np.ascontiguousarray(zps, np.float32)
        self.chunk_elems = int(chunk_elems)
        self.total_elems = int(total_elems)
        self.wire_dtype = str(wire_dtype)
        nb = packed_block_grid(self.total_elems, self.chunk_elems)
        if self.scales.shape != (nb,) or self.zps.shape != (nb,):
            raise ValueError(
                f"grid has {self.scales.shape}/{self.zps.shape} "
                f"scale/zero-point entries; the canonical grid over "
                f"{self.total_elems} elements at {self.chunk_elems} "
                f"elems/block has {nb} blocks"
            )
        if not np.all(self.scales > 0):
            raise ValueError("grid scales must be strictly positive")
        self._fp: Optional[int] = None

    @property
    def nblocks(self) -> int:
        return int(self.scales.shape[0])

    @property
    def qabs_max(self) -> int:
        """Bound on |code| — the i32 headroom term (see module doc)."""
        qmin, qmax = _qrange(self.wire_dtype)
        return max(abs(qmin), abs(qmax))

    def fingerprint(self) -> int:
        """CRC32 over the grid's exact bytes + geometry — what frame
        metadata carries and receivers compare.  Bit-identical grids
        (the only kind :func:`make_round_grid` produces from identical
        references) fingerprint identically."""
        if self._fp is None:
            head = json.dumps(
                [QUANT_GRID_VERSION, self.chunk_elems, self.total_elems,
                 self.wire_dtype, self.mode],
                separators=(",", ":"),
            ).encode()
            fp = zlib.crc32(head)
            fp = zlib.crc32(self.scales.tobytes(), fp)
            fp = zlib.crc32(self.zps.tobytes(), fp)
            self._fp = fp
        return self._fp

    def meta(self) -> "QuantMeta":
        """The static descriptor stamped into quantized wire forms."""
        return QuantMeta(
            QUANT_GRID_VERSION, self.chunk_elems, self.total_elems,
            self.wire_dtype, self.mode, self.fingerprint(),
        )

    def rows(self, blocks: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """(scales, zps) for a block subset — a ring stripe owner's
        rows, in the stripe's ascending-block compaction order."""
        idx = np.asarray(list(blocks), np.int64)
        return self.scales[idx], self.zps[idx]

    def check_weight_headroom(self, total_weight: int) -> None:
        """Loud i32 overflow guard: ``qabs_max * W`` must fit int32."""
        bound = self.qabs_max * int(total_weight)
        if bound > 2**31 - 1:
            raise ValueError(
                f"integer-fold overflow: qabs_max({self.wire_dtype})="
                f"{self.qabs_max} x total weight {total_weight} = "
                f"{bound} exceeds the i32 accumulator bound {2**31 - 1} "
                f"— the widening add holds only for total weight <= "
                f"{(2**31 - 1) // self.qabs_max}; rescale the example "
                f"counts or aggregate hierarchically"
            )

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, QuantGrid)
            and self.meta() == other.meta()
        )

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"QuantGrid({self.nblocks} blocks x {self.chunk_elems} "
            f"{self.wire_dtype} elems, {self.total_elems} total, "
            f"fp={self.fingerprint():#010x})"
        )


class QuantMeta(NamedTuple):
    """Hashable static descriptor of a grid (pytree aux / wire check).

    ``mode``: ``"delta"`` — the codes represent ``x - ref`` against the
    round's shared reference buffer (the starting model), the form the
    round loop uses (per-round updates are orders of magnitude smaller
    than the params, so the delta grid is correspondingly finer);
    ``"abs"`` — the codes represent the values themselves (no
    reference needed to decode; the downlink of a model whose receiver
    holds nothing yet).
    """

    version: int
    chunk_elems: int
    total_elems: int
    wire_dtype: str
    mode: str
    fp: int


def grid_descriptor(grid: QuantGrid) -> Dict[str, Any]:
    """The compact per-frame grid descriptor — single producer of the
    schema ``tool/check_wire_format.py`` fingerprints.  Rides the
    ordinary frame-metadata dict under ``wire.QUANT_GRID_KEY`` (JSON-
    encoded): receivers attribute a quantized frame to its round's grid
    without decoding the payload, and a mismatched fingerprint names
    both grids instead of folding wrong-grid codes.
    """
    return {
        "v": QUANT_GRID_VERSION,
        "fp": int(grid.fingerprint()),
        "nb": int(grid.nblocks),
        "ce": int(grid.chunk_elems),
        "el": int(grid.total_elems),
        "dt": str(grid.wire_dtype),
        "md": str(grid.mode),
    }


def check_descriptor(descriptor: Any, grid: QuantGrid) -> None:
    """Validate a received grid descriptor (JSON str or dict) against
    the locally derived grid; raises naming both on any mismatch."""
    gd = (
        json.loads(descriptor) if isinstance(descriptor, (str, bytes))
        else dict(descriptor)
    )
    if gd.get("v", 0) > QUANT_GRID_VERSION:
        raise ValueError(
            f"quantized frame uses grid descriptor v{gd.get('v')}; this "
            f"party understands up to v{QUANT_GRID_VERSION}"
        )
    want = grid_descriptor(grid)
    for key in ("fp", "nb", "ce", "el", "dt", "md"):
        if gd.get(key) != want[key]:
            raise ValueError(
                f"quantization grid mismatch: frame carries "
                f"{key}={gd.get(key)!r}, this round's grid has "
                f"{want[key]!r} — sender and receiver disagree on the "
                f"round's shared grid"
            )


def make_round_grid(
    reference: Any,
    chunk_elems: Optional[int] = None,
    wire_dtype: str = "uint8",
    expand: float = 1.25,
    min_scale: float = 1e-12,
    mode: str = "delta",
    floor_frac: float = 0.05,
) -> QuantGrid:
    """Derive a shared grid from a reference range buffer.

    ``reference``: a buffer every controller holds **bit-identically**
    whose per-block value range predicts the values to be coded.  For
    the round loop's ``mode="delta"`` uplink that is the PREVIOUS
    round's aggregate delta (``agg_r − agg_{r-1}``): per-party deltas
    live at the same scale, so the grid step lands orders of magnitude
    below the params and the codes carry the *signal*, not the
    ambient parameter range (the first round, with no observed delta
    yet, runs unquantized — the driver's bootstrap).  For ``mode=
    "abs"`` it is the values themselves (e.g. the aggregate the
    coordinator is about to broadcast).  The derivation is pure numpy
    over the shared buffer, so every controller computes the identical
    grid with no extra wire hop — that IS the negotiation, pinned by
    the fingerprint check on every quantized frame.

    Per block: the value range is the block's [min, max] expanded by
    ``expand`` around its midpoint (values drift past the reference
    range; out-of-range values clip and the clipped mass rides the
    error-feedback residual into the next round), floored at
    ``floor_frac`` of the buffer's global RMS (a near-constant block's
    range says nothing about where its values will move — a
    dispersion-proportional floor keeps it from degenerating into a
    clip-everything trap), then mapped affinely onto the integer
    range.  ``min_scale`` floors the fully-degenerate all-zero case.
    """
    if isinstance(reference, PackedTree):
        reference = reference.buf
    arr = np.asarray(reference).reshape(-1).astype(np.float32)
    if arr.size == 0:
        raise ValueError(
            "cannot derive a quantization grid from an empty buffer"
        )
    if chunk_elems is None:
        from rayfed_tpu.fl.streaming import DEFAULT_CHUNK_ELEMS

        chunk_elems = DEFAULT_CHUNK_ELEMS
    ce = int(chunk_elems)
    qmin, qmax = _qrange(wire_dtype)
    from rayfed_tpu.fl.fedavg import packed_block_grid

    nb = packed_block_grid(arr.size, ce)
    total = arr.size
    rms = float(np.sqrt(np.mean(np.square(arr, dtype=np.float64))))
    # Pad the tail block with its last value: min/max of the padded row
    # equal the true block min/max (a zero pad would drag the range
    # toward 0 for tail blocks that never contain 0).
    pad = nb * ce - total
    if pad:
        arr = np.concatenate([arr, np.full(pad, arr[-1], np.float32)])
    a2 = arr.reshape(nb, ce)
    lo = a2.min(axis=1)
    hi = a2.max(axis=1)
    mid = 0.5 * (hi + lo)
    half = np.maximum(
        0.5 * (hi - lo) * np.float32(expand),
        np.float32(float(floor_frac) * rms),
    )
    lo = mid - half
    hi = mid + half
    scales = np.maximum(
        (hi - lo) / np.float32(qmax - qmin), np.float32(min_scale)
    ).astype(np.float32)
    zps = (qmin - lo / scales).astype(np.float32)
    return QuantGrid(scales, zps, ce, total, wire_dtype, mode)


class QuantizedPackedTree(PackedTree):
    """Integer-coded wire form of a :class:`PackedTree`.

    ``buf`` holds the integer codes (``gmeta.wire_dtype``); ``scales``
    and ``zps`` are the grid's per-block vectors riding alongside (tiny
    — one f32 pair per 4 MB block — and they make every payload
    self-describing: a delta-base re-seed or a rejoining party always
    carries the grid it was coded with).  ``gmeta`` is the static
    :class:`QuantMeta` descriptor; the fold layer compares its ``fp``
    against the round grid before trusting any codes.

    Registered as a JAX pytree with children ``(buf, scales, zps,
    *passthrough)`` — leaf 0 stays the packed wire buffer, so the
    transport codec and the streaming aggregator's layout parse see
    exactly the shape they already handle.
    """

    __slots__ = ("scales", "zps", "gmeta")

    def __init__(self, buf: Any, scales: Any, zps: Any,
                 passthrough: Tuple, spec: PackSpec,
                 gmeta: QuantMeta) -> None:
        super().__init__(buf, passthrough, spec)
        self.scales = scales
        self.zps = zps
        self.gmeta = gmeta

    @property
    def nbytes(self) -> int:
        total = super().nbytes
        for extra in (self.scales, self.zps):
            total += getattr(extra, "nbytes", 0)
        return total

    def grid(self) -> QuantGrid:
        """Reconstruct the grid this tree was coded with (receiver
        side: the broadcast's grid needs no prior negotiation)."""
        g = QuantGrid(
            np.asarray(self.scales), np.asarray(self.zps),
            self.gmeta.chunk_elems, self.gmeta.total_elems,
            self.gmeta.wire_dtype, self.gmeta.mode,
        )
        if g.fingerprint() != self.gmeta.fp:
            raise ValueError(
                f"quantized payload is internally inconsistent: carried "
                f"grid fingerprints {g.fingerprint():#010x}, descriptor "
                f"says {self.gmeta.fp:#010x}"
            )
        return g

    def dequantize(self, out_dtype: Any = np.float32,
                   ref: Optional[Any] = None) -> PackedTree:
        """ONE fused rescale (+ reference add, for ``mode="delta"``
        codes) of the whole buffer back to ``out_dtype`` — the decode
        half of the codec."""
        grid = self.grid()
        ref = _check_ref(grid, ref)
        out_name = np.dtype(out_dtype).name
        if ref is None:
            import jax.numpy as jnp

            ref = jnp.zeros(0, jnp.float32)
        buf = _dequantize_kernel(
            self.gmeta.chunk_elems, self.gmeta.total_elems,
            self.gmeta.wire_dtype, out_name, grid.mode == "delta",
        )(self.buf, ref, np.asarray(self.scales), np.asarray(self.zps))
        spec = PackSpec(self.spec.entries, self.spec.treedef, out_name)
        return PackedTree(buf, self.passthrough, spec)

    def unpack(self, dtype: Any = None) -> Any:
        """Dequantize + unpack.  ``dtype=None`` decodes to f32 (integer
        codes are meaningless as float leaves).  Delta-coded trees need
        :meth:`dequantize` with the shared reference buffer first —
        calling this without it raises."""
        out = np.float32 if dtype is None else dtype
        return self.dequantize(out).unpack(out)

    def __reduce__(self):
        return (
            QuantizedPackedTree,
            (self.buf, self.scales, self.zps, self.passthrough,
             self.spec, self.gmeta),
        )

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"QuantizedPackedTree({self.gmeta.total_elems} "
            f"{self.gmeta.wire_dtype} codes, {self.gmeta.chunk_elems} "
            f"elems/block, fp={self.gmeta.fp:#010x}, "
            f"{len(self.passthrough)} passthrough)"
        )


import jax  # noqa: E402  (after numpy-only grid machinery)

jax.tree_util.register_pytree_node(
    QuantizedPackedTree,
    lambda qt: (
        (qt.buf, qt.scales, qt.zps, *qt.passthrough),
        (qt.spec, qt.gmeta),
    ),
    lambda aux, ch: QuantizedPackedTree(
        ch[0], ch[1], ch[2], tuple(ch[3:]), aux[0], aux[1]
    ),
)


@functools.lru_cache(maxsize=None)
def _quantize_kernel(chunk_elems: int, total_elems: int, wire_name: str,
                     with_ref: bool):
    """ONE fused (subtract-reference +) quantize + residual step over
    the whole packed buffer: add the carried residual, code onto the
    grid, dequantize in-kernel to carry the new residual.  Same EF14
    structure as ``compression._ef_kernel``, on the shared grid."""
    import jax
    import jax.numpy as jnp

    qmin, qmax = _qrange(wire_name)
    from rayfed_tpu.fl.fedavg import packed_block_grid

    nb = packed_block_grid(total_elems, chunk_elems)
    pad = nb * chunk_elems - total_elems

    @jax.jit
    def _q(buf, ref, scales, zps, resid):
        value = buf.astype(jnp.float32)
        if with_ref:
            value = value - ref
        corrected = value + resid
        a = jnp.pad(corrected, (0, pad)).reshape(nb, chunk_elems)
        q = jnp.clip(
            jnp.round(a / scales[:, None] + zps[:, None]), qmin, qmax
        )
        deq = scales[:, None] * (q - zps[:, None])
        qbuf = q.astype(jnp.dtype(wire_name)).reshape(-1)[:total_elems]
        new_resid = corrected - deq.reshape(-1)[:total_elems]
        return qbuf, new_resid

    return _q


@functools.lru_cache(maxsize=None)
def _dequantize_kernel(chunk_elems: int, total_elems: int,
                       wire_name: str, out_name: str, with_ref: bool):
    import jax
    import jax.numpy as jnp

    from rayfed_tpu.fl.fedavg import packed_block_grid

    nb = packed_block_grid(total_elems, chunk_elems)
    pad = nb * chunk_elems - total_elems

    @jax.jit
    def _dq(qbuf, ref, scales, zps):
        a = jnp.pad(qbuf.astype(jnp.float32), (0, pad)).reshape(
            nb, chunk_elems
        )
        x = scales[:, None] * (a - zps[:, None])
        x = x.reshape(-1)[:total_elems]
        if with_ref:
            x = ref + x
        return x.astype(jnp.dtype(out_name))

    return _dq


def _check_ref(grid: QuantGrid, ref: Optional[Any]):
    """Validate + normalize the shared reference buffer against the
    grid's mode (delta codes are meaningless without it, abs codes
    must not get one)."""
    if grid.mode == "delta":
        if ref is None:
            raise ValueError(
                "grid mode 'delta' codes x - ref: pass ref= (the "
                "round's shared reference buffer, e.g. the starting "
                "model's packed f32 buffer)"
            )
        if isinstance(ref, PackedTree):
            ref = ref.buf
        if int(getattr(ref, "size", 0)) != grid.total_elems:
            raise ValueError(
                f"reference buffer has {getattr(ref, 'size', 0)} "
                f"elements, grid covers {grid.total_elems}"
            )
        return ref
    if ref is not None:
        raise ValueError(
            "grid mode 'abs' codes the values themselves — ref= does "
            "not apply"
        )
    return None


def _quantize_with_resid(
    packed: PackedTree, grid: QuantGrid, resid: Optional[Any],
    ref: Optional[Any] = None,
) -> Tuple[QuantizedPackedTree, Any]:
    if isinstance(packed, QuantizedPackedTree):
        raise TypeError("tree is already quantized")
    if not isinstance(packed, PackedTree):
        raise TypeError(
            f"quantize_packed consumes PackedTree contributions, got "
            f"{type(packed).__name__} — pack with fl.compress(tree, "
            f"packed=True) first"
        )
    buf = packed.buf
    n = int(getattr(buf, "size", 0))
    if n != grid.total_elems:
        raise ValueError(
            f"packed buffer has {n} elements, grid covers "
            f"{grid.total_elems} — the grid must be derived on the same "
            f"packed layout the parties push"
        )
    ref = _check_ref(grid, ref)
    import jax.numpy as jnp

    if resid is None:
        resid = jnp.zeros(grid.total_elems, jnp.float32)
    if ref is None:
        ref = jnp.zeros(0, jnp.float32)  # unused placeholder arg
    qbuf, new_resid = _quantize_kernel(
        grid.chunk_elems, grid.total_elems, grid.wire_dtype,
        grid.mode == "delta",
    )(buf, ref, grid.scales, grid.zps, resid)
    spec = PackSpec(
        packed.spec.entries, packed.spec.treedef, grid.wire_dtype
    )
    qt = QuantizedPackedTree(
        np.asarray(qbuf), grid.scales, grid.zps, packed.passthrough,
        spec, grid.meta(),
    )
    return qt, new_resid


def quantize_packed(
    packed: PackedTree, grid: QuantGrid, ref: Optional[Any] = None
) -> QuantizedPackedTree:
    """Stateless (no error feedback) grid quantization of a PackedTree.

    ``ref``: the shared reference buffer (``mode="delta"`` grids code
    ``x - ref``)."""
    qt, _ = _quantize_with_resid(packed, grid, None, ref)
    return qt


def dequantize_packed(
    qtree: QuantizedPackedTree, out_dtype: Any = np.float32,
    ref: Optional[Any] = None,
) -> PackedTree:
    """Decode a quantized tree back to a float PackedTree (one fused
    rescale; ``ref`` required for delta-coded trees)."""
    if not isinstance(qtree, QuantizedPackedTree):
        raise TypeError(
            f"dequantize_packed consumes QuantizedPackedTree, got "
            f"{type(qtree).__name__}"
        )
    return qtree.dequantize(out_dtype, ref)


class QuantCompressor:
    """Per-sender error-feedback state for the grid codec.

    Two-phase on purpose: :meth:`quantize` computes the coded tree and
    the *pending* residual; :meth:`commit` promotes it once the round
    that shipped the codes succeeded; :meth:`rollback` discards it.  A
    ring round that aborts after quantizing re-aggregates the SAME
    update over the coordinator fallback — with one-phase state the
    residual would be applied twice for one round of wire.

    Keep one instance per outgoing stream (see :func:`compressor`);
    :meth:`reset` it when the tree structure changes.
    """

    def __init__(self) -> None:
        self._resid: Optional[Any] = None
        self._pending: Optional[Any] = None

    @property
    def residual(self) -> Any:
        """The committed f32 residual (None before the first commit)."""
        return self._resid

    def quantize(self, packed: PackedTree, grid: QuantGrid,
                 ref: Optional[Any] = None) -> QuantizedPackedTree:
        if (
            self._resid is not None
            and int(self._resid.shape[0]) != grid.total_elems
        ):
            raise ValueError(
                f"tree structure changed under quantized error feedback "
                f"({self._resid.shape[0]} residual elements vs grid over "
                f"{grid.total_elems}) — call reset() when switching "
                f"models"
            )
        qt, self._pending = _quantize_with_resid(
            packed, grid, self._resid, ref
        )
        return qt

    def commit(self) -> None:
        if self._pending is not None:
            self._resid = self._pending
            self._pending = None

    def rollback(self) -> None:
        self._pending = None

    def reset(self) -> None:
        self._resid = None
        self._pending = None


class RoundCodec:
    """ONE round's sender-side codec discipline, shared by every
    aggregation topology (streaming / ring / quorum).

    Bundles the pieces that must stay in lockstep — the grid, the
    normalized shared reference buffer, the per-frame descriptor, the
    pre-quantized-fingerprint check, and the error-feedback two-phase
    commit/rollback — so the ring-abort → coordinator-fallback
    residual guarantee cannot silently diverge between topologies.
    With ``grid=None`` every method is the identity/no-op (the
    unquantized path needs no branches at call sites).
    """

    __slots__ = ("grid", "ref", "descriptor", "_scope")

    def __init__(self, grid: Optional[QuantGrid],
                 ref: Optional[Any] = None,
                 scope: Optional[str] = None) -> None:
        self.grid = grid
        self._scope = scope
        self.ref: Optional[np.ndarray] = None
        self.descriptor: Optional[Dict[str, Any]] = None
        if grid is not None:
            self.descriptor = grid_descriptor(grid)
            if ref is not None:
                if isinstance(ref, PackedTree):
                    ref = ref.buf
                self.ref = np.asarray(ref).reshape(-1).astype(np.float32)

    def to_wire(self, value: Any) -> Any:
        """This party's contribution in wire form: quantized onto the
        round grid (a pre-quantized value passes through after a
        fingerprint check; with a scope, the error-feedback residual
        rides along — committed only after the round lands)."""
        if self.grid is None:
            return value
        if isinstance(value, QuantizedPackedTree):
            if value.gmeta != self.grid.meta():
                raise ValueError(
                    f"pre-quantized contribution was coded on a "
                    f"different grid (fp={value.gmeta.fp:#010x} vs "
                    f"{self.grid.fingerprint():#010x})"
                )
            return value
        if not isinstance(value, PackedTree):
            raise TypeError(
                "compressed-domain aggregation consumes PackedTree "
                f"contributions, got {type(value).__name__}"
            )
        if self._scope is not None:
            return compressor(self._scope).quantize(
                value, self.grid, ref=self.ref
            )
        return quantize_packed(value, self.grid, ref=self.ref)

    def commit(self) -> None:
        if self.grid is not None and self._scope is not None:
            compressor(self._scope).commit()

    def rollback(self) -> None:
        if self.grid is not None and self._scope is not None:
            compressor(self._scope).rollback()


def quantize_downlink(
    result: Any,
    grid: QuantGrid,
    ref: Optional[np.ndarray],
    scope: Optional[str],
    out_dtype: Any = np.float32,
) -> Tuple[QuantizedPackedTree, Any, Dict[str, Any]]:
    """Re-quantize a round aggregate for the result broadcast.

    The coordinator is the only sender, so the downlink grid can follow
    the exact data (FRESH grid from the aggregate itself, tiny error)
    and it rides the payload — receivers and rejoiners need no
    negotiation.  Delta rounds code ``aggregate − shared ref``, the form
    whose range the 8-bit step actually resolves.  Returns ``(wire
    form, dequantized aggregate, grid descriptor)`` — the coordinator
    returns the DEQUANTIZED codes so every controller holds the
    identical bytes.  ONE producer shared by ``streaming_aggregate``,
    ``quorum_aggregate`` and the hierarchy root: the quantized-quorum,
    quantized-streaming and hierarchical downlinks are byte-identical
    by construction, not by parallel maintenance.  Under a server
    optimizer (fl.server_opt) the caller steps BEFORE calling this, so
    ``result`` is the post-step model and the fresh grid here is
    automatically ranged by the post-step delta — no new metadata key,
    no schema change.  ``scope`` keys the downlink's own
    error-feedback residual (``{scope}/down``); None quantizes
    statelessly.
    """
    if ref is not None:
        down_src = np.asarray(result.buf).astype(np.float32) - ref
        down_grid = make_round_grid(
            down_src, chunk_elems=grid.chunk_elems,
            wire_dtype=grid.wire_dtype, mode="delta",
        )
    else:
        down_grid = make_round_grid(
            result.buf, chunk_elems=grid.chunk_elems,
            wire_dtype=grid.wire_dtype, mode="abs",
        )
    dcomp = compressor(f"{scope}/down") if scope is not None else None
    wire_result = (
        dcomp.quantize(result, down_grid, ref=ref)
        if dcomp is not None
        else quantize_packed(result, down_grid, ref=ref)
    )
    decoded = wire_result.dequantize(np.dtype(out_dtype), ref=ref)
    if dcomp is not None:
        dcomp.commit()
    return wire_result, decoded, grid_descriptor(down_grid)


# Per-process compressor registry, keyed by stream scope (one EF state
# per outgoing quantized stream, like the delta caches' stream keying).
_COMPRESSORS: Dict[str, QuantCompressor] = {}


def compressor(scope: str) -> QuantCompressor:
    """The process-wide :class:`QuantCompressor` for ``scope`` (created
    on first use).  Scope by stream name, e.g. ``"fedavg"`` for the
    round loop's uplink and ``"fedavg/down"`` for the coordinator's
    broadcast."""
    comp = _COMPRESSORS.get(scope)
    if comp is None:
        comp = _COMPRESSORS[scope] = QuantCompressor()
    return comp


def reset_compressors() -> None:
    """Drop every registered compressor's state (tests / model swap)."""
    _COMPRESSORS.clear()
