"""Recursive hierarchical aggregation: region rings/hubs + quantized
multi-level partial-sum streaming.

Every topology so far puts all N parties on ONE structure — a hub
(``fl.streaming``), a ring (``fl.ring``) or a quorum hub (``fl.quorum``)
— and benches at N ≤ 4.  At hundreds of silos the structure itself is
what breaks ("Understanding Communication Backends in Cross-Silo FL",
PAPERS.md): a hub coordinator eats O(N)·|model| ingress, and a single
N-party ring pays N-1 serial hops of latency per stripe.  Here the
sorted roster partitions **deterministically** into regions
(:func:`rayfed_tpu.transport.manager.partition_regions` — every
controller derives the same partition from the same roster epoch, no
negotiation) and the round becomes a three-hop tree over existing
bricks:

1. **Region reduce-scatter** (``fl.ring``'s chunk-striped schedule,
   region-scoped): each region runs the canonical chunk grid's stripe
   schedule over its own members; integer codes
   (:class:`~rayfed_tpu.fl.quantize.QuantGrid` — hierarchy ALWAYS runs
   in the compressed domain, see below) flow to stripe owners and fold
   into donated i32 accumulators
   (:class:`~rayfed_tpu.fl.streaming.StripeAggregator`) — but, unlike a
   flat ring round, the stripes are **not finalized**: each owner emits
   its stripe of the region's raw integer partial sum
   ``Σ_{p∈region} w_p·q_p``.

2. **Quantized cross-region streaming**: stripe owners hand their
   partial-sum stripes to the region coordinator (first live member of
   the region — :func:`~rayfed_tpu.transport.manager.roster_successor`
   semantics when the canonical first is dead), which assembles the
   region's full partial-sum buffer (a :class:`RegionSumTree`, shipped
   at the **narrowest exact integer width** —
   :func:`partial_sum_dtype`: int16 whenever ``qabs_max·W`` fits, half
   the bytes of i32) and streams it up to the ROOT coordinator, where a
   :class:`~rayfed_tpu.fl.streaming.StreamingAggregator` in
   ``presummed`` mode folds region sums at unit weight into the same
   donated i32 accumulator every flat path uses.

3. **Broadcast down the tree**: the root applies THE single fused
   rescale (:func:`~rayfed_tpu.fl.fedavg.finalize_packed_quantized`)
   once, then the aggregate travels root → region coordinators →
   members (optionally re-quantized for the wire, the shared
   :func:`~rayfed_tpu.fl.quantize.quantize_downlink` producer), with a
   commit/release pass so every controller reaches the same
   success/abort verdict (the ring's 2-pass commit, tree-shaped).

**Recursive regions (multi-level).**  The two-level shape generalizes:
leaf regions group into constant-degree interior nodes (``branch``
contiguous previous-level ids per node — :func:`region_layout` derives
the WHOLE tree from (sorted roster, region_size, branch, dead) with
zero negotiation), interior coordinators fold their children's
:class:`RegionSumTree` partial sums at unit weight through the same
donated-i32 kernel, and only the single top node's coordinator (the
root) finalizes.  Because integer folds are exact and associative, an
L-level fold == the 2-level fold == the flat fold, byte for byte, by
construction.  :func:`partial_sum_dtype` narrowing is re-derived PER
LEVEL from the level's maximum subtree roster weight, so deep levels
near the leaves ride int16 even when the root-level sums need int32.

**Per-region quorum cutoffs** (``region_quorum=``): a leaf region
switches from the stripe ring to a hub collection at its coordinator —
a quorum :class:`~rayfed_tpu.fl.streaming.StreamingAggregator`
(deadline-gated pin-members-and-refold, the same contract the flat
quorum path ships) emits the region's arrived-subset raw partial sum
instead of aborting the round.  The arrived Σw rides up the tree
inside each :class:`RegionSumTree`, and the root finalizes over the
TRUE arrived total — so a slow or partially-dead region degrades to a
subset refold (byte-identical to ``packed_quantized_sum`` over the
arrived members) and the flat fallback becomes the exception, not the
straggler path.  Interior levels stay strict: a dead region
COORDINATOR still aborts (and the next round's layout fails it over).

**Region-ring downlink** (``ring_downlink=True``, the default): the
post-finalize broadcast travels root → child coordinators (per level)
→ a relay chain inside each leaf region — the coordinator sends the
quantized result to the first participating member only, each member
forwards it to its successor on arrival and confirms with a tiny
commit token, so root egress is ~O(branch·|model|), flat in N, and no
leaf coordinator fans out O(region_size) copies.  Members excluded by
a region cutoff get a direct best-effort copy (they are not on the
chain — a straggler mid-chain would stall the relay behind the very
party the cutoff just routed around).

**Why this is byte-identical to flat.**  Integer adds are exact and
associative, so regrouping the fold as
``Σ_regions (Σ_{p∈region} w_p·q_p)`` produces bit-for-bit the
accumulator of the flat fold ``Σ_p w_p·q_p`` — and the ONE finalize is
shared — so ``hierarchy == flat streaming == packed_quantized_sum``
byte-identical BY CONSTRUCTION, whatever the arrival order at any
level.  This is also why hierarchy **requires** the compressed domain:
f32 partial sums would re-associate a non-associative fold (the same
delta-vs-abs class of lesson PR 10 measured), so an unquantized
hierarchy is a loud exclusion, never an approximate fallback.

**Why traffic stays flat in N.**  Per ordinary member: ~|codes| out
(reduce-scatter) + ~|codes| in + the broadcast — independent of N.  Per
region coordinator: the region's partial-sum gather (~2·|codes| at
int16) + one buffer up + the broadcast fan-down — independent of N for
a fixed region COUNT, and bounded by the region size otherwise.  The
root's ingress is (regions−1) partial-sum buffers — no node at any
level sees O(N) ingress (gated by ``bench.py --smoke``'s
traffic-vs-N section at N ∈ {4, 16, 64}).

**Failure story.**  Any mid-round failure poisons every key the
failing party owed (the ring's cascade, tree-shaped: errors travel up
to the root and back down), so :class:`HierarchyRoundError` raises on
EVERY controller and the driver falls back in lockstep —
``run_fedavg_rounds(mode="hierarchy")`` re-aggregates the SAME round
over the flat streaming path (classic loop) or the quorum coordinator
path (``quorum=``), where a dead region coordinator is just a dead
party: the quorum cutoff excludes it, the epoch announcement drops it,
and a dead QUORUM coordinator reaches the existing
``roster_successor`` failover arm (chaos-tested since PR 7).  The next
round re-derives the partition from the advanced roster.  For
mid-round re-runs with an explicitly agreed dead set,
:func:`region_layout` also takes ``dead=``: partition stays
roster-derived (stable), dead parties drop out of their region's
stripe ring, and each region's coordinator moves to the
``roster_successor``-derived next live member.
"""

from __future__ import annotations

import json
import logging
import time
import zlib
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from rayfed_tpu.fl.compression import PackedTree, PackSpec
from rayfed_tpu.fl.quantize import QuantizedPackedTree

logger = logging.getLogger(__name__)

# Version of the hierarchy region manifest ("hrm" sideband leaf) — bump
# when make_region_meta's schema changes.  Fingerprinted (with the
# schema) by tool/check_wire_format.py: region payloads are a
# cross-party contract layered on the ordinary payload manifest, like
# the ring stripe manifest.  The frame layout itself is untouched.
# v2: multi-level manifests — "lv" (tree level), "pa" (parent node id)
# and "rp" (the leaf region's path of interior node ids to the root).
HIERARCHY_VERSION = 2

# Region-ring downlink: longest relay chain one envelope travels.  The
# ring trades coordinator egress (ONE copy per chain instead of one
# per member) for serial hop latency, so an unbounded chain puts
# region_size-1 per-message costs on the round's critical path — at
# region_size=32 that relay alone regressed the N=64 round ~18%.
# Splitting the region into ceil(members/8) PARALLEL chains keeps
# coordinator egress region-size-bounded (k copies, k ≤ members/8,
# still far under fan-out's per-member copies) while capping the
# downlink critical path at 8 serial hops regardless of region size.
RING_RELAY_MAX_HOPS = 8

# Module-level round counters (the trainer's fallback path and tests
# read these — mirrors fl.ring.RING_STATS).
HIER_STATS: Dict[str, int] = {
    "rounds_completed": 0,
    "rounds_aborted": 0,
    "fallback_rounds": 0,
    # Rounds where >= 1 region completed on its arrived SUBSET (the
    # per-region quorum cutoff absorbed a straggler or corpse).
    "region_cutoffs": 0,
}

# Test-only fault injection: when set, called with (phase, party) at
# each step of the member flow ("local", "rs", "ps", "up", "down",
# "commit").  Raising simulates a failure at exactly that phase; the
# in-process chaos tests also hard-stop a virtual party's transport
# from here.  Takes the party because in-process virtual parties share
# one process (unlike fl.ring's per-process hook).
_fault_hook: Optional[Callable[[str, str], None]] = None


def _maybe_fault(phase: str, party: str) -> None:
    if _fault_hook is not None:
        _fault_hook(phase, party)


def _relay_chains(
    members: Sequence[str], max_hops: int = RING_RELAY_MAX_HOPS
) -> List[List[str]]:
    """Split a region's relay members into parallel bounded chains.

    Order-preserving contiguous split into ``ceil(len/max_hops)``
    chains of at most ``max_hops`` members each, sized as evenly as
    possible (the LONGEST chain is the downlink's critical path, so a
    33-member region becomes 7/7/7/6/6, never 8/8/8/8/1).  Every member
    appears in exactly one chain; relaying and the per-member commit
    tokens are unchanged — each envelope just carries its own chain.
    """
    if max_hops < 1:
        raise ValueError(f"max_hops must be >= 1, got {max_hops}")
    n = len(members)
    if n == 0:
        return []
    k = -(-n // max_hops)  # ceil
    base, extra = divmod(n, k)
    chains: List[List[str]] = []
    start = 0
    for i in range(k):
        size = base + (1 if i < extra else 0)
        chains.append(list(members[start:start + size]))
        start += size
    return chains


# Seq ids one hierarchy_aggregate call consumes — callers pre-allocating
# ids (the quorum driver derives string keys instead) pass exactly this
# many, in next_seq_id order: (rs, ps, up, down, commit, release).
HIER_SEQ_IDS = 6


class HierarchyRoundError(RuntimeError):
    """A hierarchy round aborted (peer death, wire failure, poisoned
    hop, partition disagreement).  The round's contributions are still
    intact on their owners — re-aggregate the SAME round over the flat
    streaming/quorum topology (``run_fedavg_rounds(mode="hierarchy")``
    does exactly that)."""


def members_fingerprint(members: Sequence[str]) -> int:
    """CRC32 over the sorted roster — what region manifests carry so
    two controllers that derived DIFFERENT partitions (a missed epoch
    advance) abort loudly instead of folding mismatched stripes."""
    return zlib.crc32("\n".join(sorted(members)).encode())


def partial_sum_dtype(qabs_max: int, total_weight: int) -> str:
    """Narrowest integer wire dtype that holds ``qabs_max · W`` exactly.

    A region partial sum ``Σ w_p·q_p`` is bounded by the ROSTER total's
    headroom bound, so int16 (half the i32 bytes) carries it exactly
    whenever ``qabs_max·W ≤ 2¹⁵−1`` — e.g. unit weights up to 128
    parties at uint8.  Every controller derives the same dtype from the
    same shared weights; the receiver's fold widens to i32 regardless.
    """
    bound = int(qabs_max) * int(total_weight)
    if bound <= 2**15 - 1:
        return "int16"
    if bound <= 2**31 - 1:
        return "int32"
    raise ValueError(
        f"integer-fold overflow: qabs_max {qabs_max} x total weight "
        f"{total_weight} = {bound} exceeds the i32 accumulator bound — "
        f"rescale the example counts"
    )


class TreeNode(NamedTuple):
    """One ACTIVE interior node of the derived tree."""

    children: tuple          # active child node ids at the level below
    coordinator: str         # == coordinator of the first active child


class HierarchyLayout(NamedTuple):
    """One round's derived tree topology (identical on every
    controller: pure function of (sorted members, region_size, branch,
    dead))."""

    regions: List[List[str]]      # full partition of the roster
    live: List[List[str]]         # per-region live members (sorted)
    coordinators: Dict[int, str]  # region index -> live coordinator
    active: List[int]             # region indices with >= 1 live member
    root: str                     # the root coordinator
    root_region: int
    # Interior levels 1..L (``levels[i]`` is level ``i+1``): active
    # node id -> TreeNode.  The LAST level always holds exactly one
    # active node, whose coordinator IS ``root``.  Node ids group the
    # FULL previous-level id range (``prev_id // branch``), so the
    # tree shape is stable under deaths — a dead subtree just drops
    # out of its parent's active children.
    levels: tuple = ()
    branch: int = 0


def region_layout(
    members: Sequence[str], region_size: int, dead: Sequence[str] = (),
    branch: Optional[int] = None,
) -> HierarchyLayout:
    """Derive the round's tree topology.

    The PARTITION derives from the roster alone (stable under a
    mid-round death — re-partitioning on health signals would move
    every stripe).  ``dead`` parties drop out of their region's stripe
    ring and fold set; a dead canonical coordinator's region fails over
    to the :func:`~rayfed_tpu.transport.manager.roster_successor`-
    derived next live member.  Above the leaf regions, every
    ``branch`` contiguous node ids group into one interior node
    (recursively, until a single top node remains); an interior node's
    coordinator is its first active child's coordinator, so the root
    is the first active region's coordinator — exactly the 2-level
    derivation when the region count fits one ``branch`` group.
    ``branch`` defaults to ``max(2, region_size)``.
    """
    from rayfed_tpu.transport.manager import (
        branch_groups, partition_regions, roster_successor,
    )

    regions = partition_regions(members, region_size)
    if branch is None:
        branch = max(2, int(region_size))
    branch = int(branch)
    if branch < 2:
        raise ValueError(
            f"branch must be >= 2 (a 1-ary interior level folds "
            f"nothing), got {branch}"
        )
    dead_set = set(dead)
    live = [[p for p in r if p not in dead_set] for r in regions]
    coordinators: Dict[int, str] = {}
    active: List[int] = []
    for g, r in enumerate(regions):
        if not live[g]:
            continue
        if r[0] in dead_set:
            succ = roster_successor(r, r[0], dead_set)
            if succ is None:  # pragma: no cover - live[g] non-empty
                continue
            coordinators[g] = succ
        else:
            coordinators[g] = r[0]
        active.append(g)
    if not active:
        raise HierarchyRoundError(
            f"no live party remains on the roster {sorted(members)} "
            f"(dead: {sorted(dead_set)})"
        )
    # Interior levels: fold the FULL id range of each level into
    # groups of ``branch`` until one node remains.  At least one
    # interior level always exists (the top node the root folds), so
    # a single-branch-group layout reproduces the 2-level shape.
    levels: List[Dict[int, TreeNode]] = []
    prev_active = list(active)
    prev_coord: Dict[int, str] = dict(coordinators)
    n_full = len(regions)
    while True:
        n_full = -(-n_full // branch)
        level = {
            nid: TreeNode(tuple(children), prev_coord[children[0]])
            for nid, children in branch_groups(prev_active, branch)
        }
        levels.append(level)
        if n_full <= 1:
            break
        prev_active = sorted(level)
        prev_coord = {nid: nd.coordinator for nid, nd in level.items()}
    root_region = active[0]
    return HierarchyLayout(
        regions, live, coordinators, active,
        coordinators[root_region], root_region,
        tuple(levels), branch,
    )


def make_region_meta(
    phase: str,
    region: int,
    n_regions: int,
    stripe: int,
    n_stripes: int,
    nblocks: int,
    total_elems: int,
    dtype: str,
    qgrid_fp: int,
    members_fp: int,
    epoch: Optional[int] = None,
    level: int = 0,
    parent: int = 0,
    path: str = "",
) -> Dict[str, Any]:
    """The ``hrm`` sideband of a hierarchy payload — single producer of
    its schema (``tool/check_wire_format.py`` fingerprints it).

    ``phase`` is ``"rs"`` (region reduce-scatter/hub codes) or ``"ps"``
    (a stripe of the region's integer partial sum).  Receivers
    cross-check every field against their independently derived
    layout, so a partition disagreement (``mf``: the roster
    fingerprint), a stale epoch (``ep``), a grid mismatch (``qg``) or
    a tree-shape disagreement (``lv``/``pa``/``rp``: the node's level,
    parent id and interior root path — v2, multi-level trees) fails
    loudly BEFORE any block folds.
    """
    return {
        "v": HIERARCHY_VERSION,
        "ph": str(phase),
        "rg": int(region),
        "nr": int(n_regions),
        "s": int(stripe),
        "n": int(n_stripes),
        "nb": int(nblocks),
        "el": int(total_elems),
        "dt": str(dtype),
        "qg": int(qgrid_fp),
        "mf": int(members_fp),
        "ep": -1 if epoch is None else int(epoch),
        "lv": int(level),
        "pa": int(parent),
        "rp": str(path),
    }


def check_region_meta(meta_json: str, want: Dict[str, Any]) -> None:
    """Validate a received ``hrm`` manifest against the locally derived
    layout; raises naming the first mismatched field."""
    hrm = json.loads(meta_json)
    if hrm.get("v", 0) > HIERARCHY_VERSION:
        raise HierarchyRoundError(
            f"region payload uses hierarchy manifest v{hrm.get('v')}; "
            f"this party understands up to v{HIERARCHY_VERSION}"
        )
    for key, expect in want.items():
        if hrm.get(key) != expect:
            raise HierarchyRoundError(
                f"region manifest mismatch: {key}={hrm.get(key)!r}, "
                f"expected {expect!r} — hierarchy peers disagree on the "
                f"round's partition/grid/epoch"
            )


class RegionSumTree(QuantizedPackedTree):
    """Wire form of a region's integer partial sum: ``Σ_{p∈region}
    w_p·q_p`` on the round's shared grid, at the narrowest exact
    integer width (:func:`partial_sum_dtype`), with the grid descriptor
    riding along (the root still verifies the fingerprint before
    folding).

    Deliberately NOT decodable on its own: a partial sum is meaningless
    before the root's single fused rescale over the WHOLE roster's
    weight — :meth:`dequantize`/:meth:`unpack` raise instead of
    silently rescaling a subtree's sum as if it were the round's.  Fold
    with a ``presummed`` :class:`~rayfed_tpu.fl.streaming.
    StreamingAggregator`, whose unit-weight integer fold reassembles
    exactly the flat accumulator.

    ``arrived_w``: the subtree's TRUE arrived integer Σw — set (and
    propagated up the tree in the pytree aux) when a per-region quorum
    cutoff excluded stragglers, so the root's finalize divides by the
    weight that actually folded.  ``None`` means the full subtree
    roster weight arrived (the all-of-n hot path carries no number).
    """

    __slots__ = ("arrived_w",)

    def __init__(self, buf, scales, zps, passthrough, spec, gmeta,
                 arrived_w: Optional[int] = None):
        super().__init__(buf, scales, zps, passthrough, spec, gmeta)
        self.arrived_w = None if arrived_w is None else int(arrived_w)

    def dequantize(self, out_dtype: Any = np.float32,
                   ref: Optional[Any] = None):
        raise HierarchyRoundError(
            "a RegionSumTree is an integer PARTIAL sum — only the root "
            "fold (StreamingAggregator(presummed=...)) may rescale it, "
            "once, over the whole roster's weight"
        )

    def unpack(self, dtype: Any = None):
        raise HierarchyRoundError(
            "a RegionSumTree cannot be unpacked — see dequantize"
        )

    def __reduce__(self):
        return (
            RegionSumTree,
            (self.buf, self.scales, self.zps, self.passthrough,
             self.spec, self.gmeta, self.arrived_w),
        )

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"RegionSumTree({self.gmeta.total_elems} partial-sum "
            f"elements on grid fp={self.gmeta.fp:#010x}"
            + ("" if self.arrived_w is None
               else f", arrived_w={self.arrived_w}") + ")"
        )


import jax  # noqa: E402  (after the numpy-only machinery, like quantize)

jax.tree_util.register_pytree_node(
    RegionSumTree,
    lambda rt: (
        (rt.buf, rt.scales, rt.zps, *rt.passthrough),
        (rt.spec, rt.gmeta, rt.arrived_w),
    ),
    lambda aux, ch: RegionSumTree(
        ch[0], ch[1], ch[2], tuple(ch[3:]), aux[0], aux[1], aux[2]
    ),
)


from rayfed_tpu.fl.streaming import StripeAggregator  # noqa: E402


class _RawStripeAggregator(StripeAggregator):
    """A region stripe owner's fold that emits the RAW i32 partial sum
    instead of a finalized stripe — the region level must NOT rescale
    (the single fused divide belongs to the root; a per-region divide
    would round twice and break hierarchical == flat byte-identity)."""

    def _finalize(self):
        # The donated accumulator holds Σ w_p·widen(q_p) on the padded
        # block grid; trim the pad, keep the exact integers.
        import jax

        acc = self._acc
        jax.block_until_ready(acc)
        return np.asarray(acc)[: self._total_elems]


from rayfed_tpu.fl.streaming import StreamingAggregator  # noqa: E402


class _RegionHubAggregator(StreamingAggregator):
    """A leaf region's QUORUM hub fold: the coordinator collects the
    members' full code trees and emits the region's RAW i32 partial sum
    over the ARRIVED subset — the deadline-gated pin-members-and-refold
    cutoff is the base class's (the flat quorum path's contract,
    region-scoped).  No rescale happens here: the single fused divide
    belongs to the root, over the true arrived Σw the subtree reports
    up (:attr:`RegionSumTree.arrived_w`)."""

    def _finalize(self):
        import jax

        members = (
            self._participating
            if self._participating is not None
            else list(range(self._n))
        )
        self._verify_quant_members(members)
        acc = self._acc
        if not self._np_fold:  # pragma: no cover - cpu benches use numpy
            jax.block_until_ready(acc)
        return np.asarray(acc)[: self._total_elems]


class _NodeAggregator(StreamingAggregator):
    """An interior node's fold of its children's :class:`RegionSumTree`
    partial sums (unit weight, strict all-of-children).  Emits the raw
    i32 subtree sum — except at the ROOT (``finalize_root=True``),
    where it applies THE single fused rescale over the subtree's TRUE
    arrived Σw (children's ``arrived_w``, falling back to their roster
    subtree weights when no cutoff happened — in which case the
    divisor is exactly the flat fold's Σw and the bytes are identical
    by construction)."""

    def __init__(self, *args, finalize_root: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self._finalize_root = bool(finalize_root)
        self.arrived_w: Optional[int] = None

    def _fold_members(self):
        return (
            self._participating
            if self._participating is not None
            else list(range(self._n))
        )

    def _finalize(self):
        import jax

        members = self._fold_members()
        self._verify_quant_members(members)
        arrived = 0
        for i in members:
            s = self._streams[i]
            tree = s.local_tree if s.local_tree is not None else (
                self._tree_of(s)
            )
            arrived += (
                int(tree.arrived_w) if tree.arrived_w is not None
                else int(self._weights[i])
            )
        self.arrived_w = arrived
        if self._finalize_root:
            # The ONE fused rescale of the whole round, over what
            # actually folded.  Integer totals are exact in f32 up to
            # the headroom bound (checked at grid construction).
            self._total_w = float(arrived)
            return super()._finalize()
        acc = self._acc
        if not self._np_fold:  # pragma: no cover - cpu benches use numpy
            jax.block_until_ready(acc)
        return np.asarray(acc)[: self._total_elems]


# Stripe geometry (compaction + short-tail arithmetic) is the SAME
# cross-party contract the flat ring uses — one definition, not a copy
# that could silently diverge.
from rayfed_tpu.fl.ring import _stripe_elems, _stripe_slice  # noqa: E402


class HierarchyRound:
    """One party's data-plane walk of a hierarchical round.

    Deliberately driven through a :class:`~rayfed_tpu.transport.manager.
    TransportManager`-shaped object (``send``/``send_many``/``recv``/
    ``recv_stream_many``/``cancel_stream``) rather than the fed runtime:
    the fed wrapper (:func:`hierarchy_aggregate`), the traffic bench
    (``bench.py``'s N∈{4,16,64} virtual parties) and the in-process
    chaos tests all drive EXACTLY this class, so what the bench gates is
    what the driver ships.

    ``keys`` are the round's six rendezvous ids ``(rs, ps, up, down,
    commit, release)`` — every controller passes identical ones.
    ``epoch`` stamps every frame (``wire.EPOCH_TAG_KEY``): a receiver
    whose roster advanced rejects stale-region frames loudly.
    """

    def __init__(
        self,
        transport: Any,
        *,
        party: str,
        members: Sequence[str],
        region_size: int,
        grid: Any,
        quant_ref: Optional[Any],
        keys: Sequence[Any],
        weights: Optional[Dict[str, float]] = None,
        stream: str = "hier",
        epoch: Optional[int] = None,
        round_tag: Optional[int] = None,
        backstop: Optional[float] = None,
        quant_scope: Optional[str] = None,
        allowed: Optional[Dict[str, Any]] = None,
        quant_downlink: bool = False,
        dead: Sequence[str] = (),
        timings: Optional[Dict[str, float]] = None,
        server_step: Optional[Any] = None,
        branch: Optional[int] = None,
        region_quorum: Optional[int] = None,
        region_deadline_s: Optional[float] = None,
        ring_downlink: bool = True,
    ) -> None:
        from rayfed_tpu.fl.fedavg import quant_weights
        from rayfed_tpu.fl.quantize import RoundCodec

        if grid is None:
            raise HierarchyRoundError(
                "hierarchical aggregation runs in the compressed domain "
                "ONLY: float partial sums would re-associate a "
                "non-associative fold and silently break hierarchical "
                "== flat byte-identity — pass the round's shared "
                "QuantGrid (wire_quant)"
            )
        if len(keys) != HIER_SEQ_IDS:
            raise ValueError(
                f"hierarchy rounds consume {HIER_SEQ_IDS} rendezvous "
                f"ids, got {len(keys)}"
            )
        self._t = transport
        self._me = str(party)
        self._members = sorted(members)
        if self._me not in self._members:
            raise HierarchyRoundError(
                f"{self._me!r} is not on the round roster "
                f"{self._members} — observer controllers are not "
                f"supported by hierarchy rounds"
            )
        self._dead = set(dead)
        if self._me in self._dead:
            raise HierarchyRoundError(
                f"{self._me!r} is in the round's agreed dead set"
            )
        self._lay = region_layout(
            self._members, region_size, self._dead, branch=branch,
        )
        self._grid = grid
        self._codec = RoundCodec(grid, quant_ref, quant_scope)
        self._qref = self._codec.ref
        self._keys = tuple(keys)
        self._stream = stream
        self._epoch = epoch
        self._round_tag = round_tag
        self._backstop = backstop
        self._allowed = allowed
        self._quant_scope = quant_scope
        self._quant_downlink = bool(quant_downlink)
        self._timings = timings
        # Server optimization (fl.server_opt): the state steps ONCE, at
        # the root, on the exact finalized f32 — the tree broadcast
        # below then carries the post-step model to every level.
        self._server_step = server_step
        contributors = [p for p in self._members if p not in self._dead]
        w_list = (
            None if weights is None
            else [float(weights[p]) for p in contributors]
        )
        iw, itotal = quant_weights(w_list, len(contributors))
        self._iw = dict(zip(contributors, iw))
        self._w_total = itotal
        grid.check_weight_headroom(itotal)
        lay = self._lay
        # Per-node subtree ROSTER weights (arrived <= roster, so every
        # level's wire dtype bound is safe under a region cutoff), and
        # the per-LEVEL partial-sum wire dtype: one dtype per level —
        # the max subtree weight at that level bounds every node's
        # emission, and a fold requires one uniform stream dtype.
        self._node_w: List[Dict[int, int]] = [{
            g: sum(self._iw[p] for p in lay.live[g]) for g in lay.active
        }]
        for level in lay.levels:
            below = self._node_w[-1]
            self._node_w.append({
                nid: sum(below[c] for c in nd.children)
                for nid, nd in level.items()
            })
        self._lvl_dtype = [
            partial_sum_dtype(grid.qabs_max, max(w.values()))
            for w in self._node_w[:-1]
        ] or [partial_sum_dtype(grid.qabs_max, itotal)]
        self._ps_dtype = self._lvl_dtype[0]
        self._members_fp = members_fingerprint(self._members)
        # The (level, node_id) pairs this party coordinates, ascending
        # from its leaf region.  Coordinatorship is prefix-closed: an
        # interior node's coordinator is its first active child's, so
        # the chain is a walk straight up from the leaf.
        g_mine = next(
            (j for j in lay.active if self._me in lay.live[j]), None
        )
        self._g = g_mine
        self._coordinated: List[tuple] = []
        if g_mine is not None and lay.coordinators[g_mine] == self._me:
            self._coordinated.append((0, g_mine))
            nid = g_mine
            for lv, level in enumerate(lay.levels, start=1):
                nid //= lay.branch
                if level[nid].coordinator != self._me:
                    break
                self._coordinated.append((lv, nid))
        if region_quorum is not None:
            rq = int(region_quorum)
            if rq < 1:
                raise ValueError(
                    f"region_quorum must be >= 1 (the minimum arrived "
                    f"member count per region), got {region_quorum}"
                )
            region_quorum = rq
        self._region_quorum = region_quorum
        self._region_deadline_s = (
            None if region_deadline_s is None else float(region_deadline_s)
        )
        if self._region_deadline_s is not None and region_quorum is None:
            raise ValueError(
                "region_deadline_s needs region_quorum= (the per-region "
                "minimum arrived count the deadline gates)"
            )
        self._ring_downlink = bool(ring_downlink)
        self._pending_cancels: List[tuple] = []

    # -- helpers --------------------------------------------------------------

    def _send(self, dest: str, value: Any, up: str, *, down: Any,
              stream: Optional[str] = None, quant_meta=None):
        return self._t.send(
            dest, value, up, down, stream=stream,
            round_tag=self._round_tag, epoch_tag=self._epoch,
            quant_meta=quant_meta,
        )

    def _recv(self, src: str, up: str, down: Any):
        return self._t.recv(src, up, down)

    def _coord_of(self, lv: int, nid: int) -> str:
        """Coordinator of active node ``nid`` at tree level ``lv``
        (level 0 = leaf regions)."""
        if lv == 0:
            return self._lay.coordinators[nid]
        return self._lay.levels[lv - 1][nid].coordinator

    def _node_path(self, g: int) -> str:
        """Region ``g``'s interior ancestor ids, leaf-to-root — the
        ``rp`` manifest field two peers cross-check so a tree-shape
        (branch) disagreement aborts before any block folds."""
        lay = self._lay
        nid = g
        parts: List[str] = []
        for _ in lay.levels:
            nid //= lay.branch
            parts.append(str(nid))
        return "/".join(parts)

    def _hrm(self, phase: str, g: int, stripe: int, n_stripes: int,
             nblocks: int, dtype: str) -> str:
        return json.dumps(
            make_region_meta(
                phase, g, len(self._lay.regions), stripe, n_stripes,
                nblocks, self._grid.total_elems, dtype,
                self._grid.fingerprint(), self._members_fp,
                epoch=self._epoch,
                level=0, parent=g // self._lay.branch,
                path=self._node_path(g),
            ),
            sort_keys=True,
        )

    def _hrm_want(self, phase: str, g: int, stripe: int, n_stripes: int,
                  nblocks: int, dtype: str) -> Dict[str, Any]:
        return {
            "ph": phase, "rg": g, "nr": len(self._lay.regions),
            "s": stripe, "n": n_stripes, "nb": nblocks,
            "el": self._grid.total_elems, "dt": dtype,
            "qg": self._grid.fingerprint(), "mf": self._members_fp,
            "ep": -1 if self._epoch is None else int(self._epoch),
            "lv": 0, "pa": g // self._lay.branch,
            "rp": self._node_path(g),
        }

    # -- the round ------------------------------------------------------------

    def run(self, local_value: Any) -> PackedTree:
        """Walk the round; returns the finalized aggregate (identical
        bytes on every controller) or raises
        :class:`HierarchyRoundError` on every controller."""
        t0 = time.perf_counter()
        try:
            result = self._run_inner(local_value)
        except BaseException as exc:
            self._codec.rollback()
            for up, down in self._pending_cancels:
                try:
                    self._t.cancel_stream(up, down)
                except Exception:  # pragma: no cover - best effort
                    pass
            self._poison_edges(exc)
            if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                # The poison still unparks the peers, but an interrupt
                # must STOP the caller unwrapped (the fl.ring contract).
                raise
            HIER_STATS["rounds_aborted"] += 1
            from rayfed_tpu import telemetry as _telemetry

            _telemetry.event(
                "hier.abort", round=self._round_tag, epoch=self._epoch,
                party=self._me,
                outcome="error", detail={"error": repr(exc)},
            )
            if isinstance(exc, HierarchyRoundError):
                raise
            raise HierarchyRoundError(
                f"hierarchy round aborted: {exc!r}"
            ) from exc
        self._codec.commit()
        HIER_STATS["rounds_completed"] += 1
        if self._timings is not None:
            self._timings["agg_s"] = time.perf_counter() - t0
            self._timings.setdefault("push_s", 0.0)
        return result

    def _run_inner(self, local_value: Any) -> PackedTree:
        from rayfed_tpu.fl import quantize as qz

        me = self._me
        lay = self._lay
        rs_id, ps_id, up_id, down_id, commit_id, release_id = self._keys
        backstop = self._backstop
        t_call0 = time.perf_counter()

        _maybe_fault("local", me)
        q = self._codec.to_wire(local_value)
        if q.passthrough:
            raise HierarchyRoundError(
                f"hierarchical aggregation covers the packed float "
                f"buffer only, but this update carries "
                f"{len(q.passthrough)} non-float (passthrough) leaf(s) "
                f"— their per-leaf reduce has no tree decomposition "
                f"yet; drop them from the update tree (loud exclusion, "
                f"never a silent partial aggregate)"
            )
        buf = np.asarray(q.buf).reshape(-1)
        g = next(
            j for j in lay.active if me in lay.live[j]
        )
        region = lay.live[g]
        m = region.index(me)
        coord = lay.coordinators[g]
        is_coord = me == coord
        is_root = me == lay.root
        from rayfed_tpu import telemetry as _telemetry

        t_mark = t_call0
        # Flight-recorder hierarchy phase boundaries, LEVEL-stamped
        # (region_rs / region_gather / up.l<k> / down.l<k> /
        # down.relay|down.fan / broadcast / commit) so trace_report can
        # attribute the critical path per tree level.  Disarmed: a bare
        # perf_counter read per phase; armed: a ring append.
        _phase_span = _telemetry.phase_spanner(
            "hier", round=self._round_tag, epoch=self._epoch,
            party=self._me,
            detail={"region": g, "coordinator": coord, "root": lay.root},
        )

        # -- 1+2. leaf phase: the region's raw integer partial sum ------
        # Classic mode stripes the fold across the region ring; quorum
        # mode collects code trees at the coordinator behind a
        # deadline-gated k-of-region cutoff.  Either way the
        # coordinator ends up holding the region's exact i32 sum.
        if self._region_quorum is None:
            ps_full, t_mark = self._leaf_stripe(
                q, buf, _phase_span, t_mark, t_call0
            )
            leaf_members = list(region)
        else:
            ps_full, leaf_members, t_mark = self._leaf_hub(
                q, _phase_span, t_mark, t_call0
            )

        # -- 3. the up walk: fold subtree sums level by level -----------
        # A coordinator climbs its prefix-closed chain of coordinated
        # nodes: at each level it folds its children's RegionSumTree
        # partial sums (unit weight -- exact + associative integer
        # adds, so ANY level count is byte-identical to the flat fold)
        # and either keeps climbing or ships the subtree sum to the
        # next coordinator.  The TOP node's coordinator is the root:
        # its fold applies the single fused rescale of the round.
        _maybe_fault("up", me)
        result = None
        ce = self._grid.chunk_elems
        n_levels = len(lay.levels)
        if is_coord:
            sub_raw = ps_full
            sub_arrived = sum(self._iw[p] for p in leaf_members)
            child_id = g
            for lv in range(1, n_levels + 1):
                nid = child_id // lay.branch
                node = lay.levels[lv - 1][nid]
                up_dt = self._lvl_dtype[lv - 1]
                sub_tree = RegionSumTree(
                    sub_raw, self._grid.scales, self._grid.zps, (),
                    PackSpec(q.spec.entries, q.spec.treedef, up_dt),
                    self._grid.meta(), arrived_w=sub_arrived,
                )
                if node.coordinator != me:
                    ref = self._send(
                        node.coordinator, sub_tree,
                        f"{up_id}.{lv}.{child_id}", down=up_id,
                        stream=f"{self._stream}/up/{lv}.{child_id}",
                        quant_meta=self._codec.descriptor,
                    )
                    if not ref.resolve(timeout=backstop):
                        raise HierarchyRoundError(
                            f"level-{lv - 1} partial sum of node "
                            f"{child_id} to {node.coordinator!r} failed"
                        )
                    t_mark = _phase_span(f"up.l{lv}", t_mark)
                    break
                children = node.children
                at_top = lv == n_levels
                node_agg = _NodeAggregator(
                    len(children),
                    weights=[
                        float(self._node_w[lv - 1][c]) for c in children
                    ],
                    allowed=self._allowed,
                    party=self._me,
                    chunk_elems=ce,
                    labels=[
                        f"level-{lv - 1} node {c}" for c in children
                    ],
                    quant=self._grid,
                    quant_ref=self._qref,
                    presummed=up_dt,
                    finalize_root=at_top,
                )
                entries = []
                for idx, c in enumerate(children):
                    if c == child_id:
                        continue
                    entries.append((
                        self._coord_of(lv - 1, c), f"{up_id}.{lv}.{c}",
                        up_id, node_agg.sink(idx),
                    ))
                    self._pending_cancels.append(
                        (f"{up_id}.{lv}.{c}", up_id)
                    )
                if entries:
                    self._t.recv_stream_many(entries)
                node_agg.add_local(children.index(child_id), sub_tree)
                folded = node_agg.result(timeout=backstop)
                sub_arrived = node_agg.arrived_w
                t_mark = _phase_span(f"up.l{lv}", t_mark)
                if at_top:
                    # ``finalize_root``: the top node's coordinator IS
                    # the round root by construction.
                    result = folded
                    break
                # Interior emission: exact i32, narrowed to the level's
                # wire dtype (bounded by its max subtree roster weight).
                sub_raw = np.asarray(folded).astype(
                    np.dtype(self._lvl_dtype[lv])
                )
                child_id = nid

        # -- 4. broadcast down the tree ---------------------------------
        _maybe_fault("down", me)
        down_descr = None
        wire_down = None
        chain: List[str] = []
        extras: List[str] = []
        if is_root:
            if self._server_step is not None:
                # The single server step of the round: exact finalized
                # f32 in, post-step model out -- the downlink recode's
                # fresh grid is therefore ranged by the POST-step
                # delta.  A failure here aborts through the standard
                # poison cascade (every controller raises
                # HierarchyRoundError and the driver falls back in
                # lockstep, re-running the SAME step from the SAME
                # state on the flat path).
                result = self._server_step(result)
            wire_down = result
            if self._quant_downlink:
                wire_down, result, down_descr = qz.quantize_downlink(
                    result, self._grid, self._qref, self._quant_scope,
                )
        elif self._coordinated:
            lvh, nidh = self._coordinated[-1]
            parent = self._coord_of(lvh + 1, nidh // lay.branch)
            value = self._recv(
                parent, f"{down_id}.c", down_id
            ).resolve(timeout=backstop)
            result = self._decode_down(value)
            wire_down = value
            if isinstance(value, QuantizedPackedTree):
                down_descr = qz.grid_descriptor(value.grid())
        if self._coordinated:
            # Interior fan-down, top level first: every child
            # coordinator of every node I coordinate -- constant
            # out-degree, so ROOT egress stays ~O(branch*|model|) flat
            # in N (the region ring below amortizes the rest).
            for lv, nid in reversed(self._coordinated[1:]):
                dests = [
                    self._coord_of(lv - 1, c)
                    for c in lay.levels[lv - 1][nid].children
                ]
                dests = [p for p in dests if p != me]
                if dests:
                    refs = self._t.send_many(
                        dests, wire_down, f"{down_id}.c", down_id,
                        stream=f"{self._stream}/down",
                        round_tag=self._round_tag,
                        epoch_tag=self._epoch,
                        quant_meta=down_descr,
                    )
                    for p, ref in refs.items():
                        if not ref.resolve(timeout=backstop):
                            raise HierarchyRoundError(
                                f"result fan-down to level-{lv - 1} "
                                f"coordinator {p!r} failed"
                            )
                    t_mark = _phase_span(f"down.l{lv}", t_mark)
            # Leaf region delivery.  Ring mode: the result relays
            # member -> member (forward-on-arrival -- the all-gather
            # relay machinery on the shared downlink codes), so the
            # coordinator sends ONE copy per chain regardless of
            # region size -- parallel chains of at most
            # RING_RELAY_MAX_HOPS members bound the serial-hop
            # latency (see the constant's comment).
            chain = [p for p in leaf_members if p != me]
            extras = [
                p for p in region if p != me and p not in leaf_members
            ]
            if self._ring_downlink:
                if chain:
                    head_refs = []
                    for sub in _relay_chains(chain):
                        env = {"chain": sub, "data": wire_down}
                        head_refs.append((sub[0], self._send(
                            sub[0], env, f"{down_id}.m", down=down_id,
                            stream=f"{self._stream}/down",
                            quant_meta=down_descr,
                        )))
                    for head, ref in head_refs:
                        if not ref.resolve(timeout=backstop):
                            raise HierarchyRoundError(
                                f"ring downlink head push to {head!r} "
                                f"failed"
                            )
                for p in extras:
                    # Best effort: a quorum-excluded member may be
                    # dead; a live straggler still gets the model.
                    if not self._send(
                        p, wire_down, f"{down_id}.m", down=down_id,
                        stream=f"{self._stream}/down",
                        quant_meta=down_descr,
                    ).resolve(timeout=backstop):
                        logger.warning(
                            "[%s] downlink to excluded member %s "
                            "failed", me, p,
                        )
            else:
                if chain:
                    refs = self._t.send_many(
                        chain, wire_down, f"{down_id}.m", down_id,
                        stream=f"{self._stream}/down",
                        round_tag=self._round_tag,
                        epoch_tag=self._epoch,
                        quant_meta=down_descr,
                    )
                    for p, ref in refs.items():
                        if not ref.resolve(timeout=backstop):
                            raise HierarchyRoundError(
                                f"result broadcast to member {p!r} "
                                f"failed"
                            )
                for p in extras:
                    if not self._send(
                        p, wire_down, f"{down_id}.m", down=down_id,
                        stream=f"{self._stream}/down",
                        quant_meta=down_descr,
                    ).resolve(timeout=backstop):
                        logger.warning(
                            "[%s] downlink to excluded member %s "
                            "failed", me, p,
                        )
            t_mark = _phase_span(
                "down.relay" if self._ring_downlink else "down.fan",
                t_mark,
            )
        else:
            value = self._recv(
                coord, f"{down_id}.m", down_id
            ).resolve(timeout=backstop)
            relay = None
            if isinstance(value, dict) and "chain" in value:
                # Region-ring envelope: forward the SAME envelope to my
                # ring successor BEFORE decoding (forward-on-arrival),
                # then confirm my hop with a tiny commit token so the
                # coordinator's commit covers the whole chain.
                relay = [str(p) for p in value["chain"]]
                inner = value["data"]
            else:
                inner = value
            if relay is not None and me in relay:
                pos = relay.index(me)
                if pos + 1 < len(relay):
                    fwd_meta = (
                        qz.grid_descriptor(inner.grid())
                        if isinstance(inner, QuantizedPackedTree)
                        else None
                    )
                    ref = self._send(
                        relay[pos + 1], value, f"{down_id}.m",
                        down=down_id, stream=f"{self._stream}/down",
                        quant_meta=fwd_meta,
                    )
                    if not ref.resolve(timeout=backstop):
                        raise HierarchyRoundError(
                            f"ring downlink relay to "
                            f"{relay[pos + 1]!r} failed"
                        )
            result = self._decode_down(inner)
            if relay is not None and me in relay:
                ref = self._send(
                    coord, {"ok": 1}, f"{commit_id}.m.{g}.{me}",
                    down=commit_id,
                )
                if not ref.resolve(timeout=backstop):
                    raise HierarchyRoundError(
                        f"relay commit token to coordinator "
                        f"{coord!r} failed"
                    )
            t_mark = _phase_span("broadcast", t_mark)

        # -- 5. commit/release: agree the round landed everywhere -------
        # Tree-shaped two-phase commit (fl.ring's token ring, L levels
        # up): every coordinator confirms its region's delivery (relay
        # commit tokens in ring mode, send acks otherwise) plus its
        # child coordinators' commits, the root collects the top
        # node's, and a release travels back down every branch -- a
        # member only RETURNS once released, so success/abort is a
        # lockstep verdict.  Like any atomic commit, a crash inside the
        # tiny release pass itself can strand waiters until the
        # backstop; the bulk phases are fully covered.
        _maybe_fault("commit", me)
        token = {"ok": 1}
        if self._coordinated:
            if self._ring_downlink:
                for p in chain:
                    self._recv(
                        p, f"{commit_id}.m.{g}.{p}", commit_id
                    ).resolve(timeout=backstop)
            for lv, nid in self._coordinated[1:]:
                for c in lay.levels[lv - 1][nid].children:
                    cc = self._coord_of(lv - 1, c)
                    if cc == me:
                        continue
                    self._recv(
                        cc, f"{commit_id}.{lv - 1}.{c}", commit_id
                    ).resolve(timeout=backstop)
            if not is_root:
                lvh, nidh = self._coordinated[-1]
                parent = self._coord_of(lvh + 1, nidh // lay.branch)
                ref = self._send(
                    parent, token, f"{commit_id}.{lvh}.{nidh}",
                    down=commit_id,
                )
                if not ref.resolve(timeout=backstop):
                    raise HierarchyRoundError(
                        f"commit token of node {nidh} (level {lvh}) "
                        f"to {parent!r} failed"
                    )
                self._recv(
                    parent, f"{release_id}.r", release_id
                ).resolve(timeout=backstop)
            rel_dests: List[str] = []
            for lv, nid in self._coordinated[1:]:
                rel_dests.extend(
                    self._coord_of(lv - 1, c)
                    for c in lay.levels[lv - 1][nid].children
                )
            rel_dests.extend(p for p in region if p != me)
            rel_dests = [
                p for p in dict.fromkeys(rel_dests) if p != me
            ]
            if rel_dests:
                refs = self._t.send_many(
                    rel_dests, token, f"{release_id}.r", release_id,
                    round_tag=self._round_tag, epoch_tag=self._epoch,
                )
                for p, ref in refs.items():
                    if not ref.resolve(timeout=backstop):
                        # Post-commit best effort: the stranded waiter
                        # aborts at its backstop (residual window).
                        logger.warning(
                            "[%s] release token to %s failed", me, p,
                        )
        else:
            self._recv(
                coord, f"{release_id}.r", release_id
            ).resolve(timeout=backstop)
        _phase_span("commit", t_mark)
        return result

    def _leaf_stripe(self, q, buf, _phase_span, t_mark, t_call0):
        """Sections 1-2, classic mode: region reduce-scatter over the
        stripe ring + partial-sum gather to the coordinator.  Returns
        ``(ps_full, t_mark)`` -- the region's raw integer sum in the
        level-0 wire dtype at the coordinator (``None`` elsewhere)."""
        from rayfed_tpu.fl.fedavg import packed_block_grid
        from rayfed_tpu.fl.fedavg import packed_stripe_schedule

        me = self._me
        lay = self._lay
        rs_id, ps_id = self._keys[0], self._keys[1]
        backstop = self._backstop
        g = self._g
        region = lay.live[g]
        m = region.index(me)
        coord = lay.coordinators[g]
        is_coord = me == coord
        ce = self._grid.chunk_elems
        total_elems = self._grid.total_elems
        nblocks = packed_block_grid(total_elems, ce)
        s_n = len(region)
        stripes = packed_stripe_schedule(nblocks, s_n)
        wire_name = self._grid.wire_dtype

        def elems(k: int) -> int:
            return _stripe_elems(stripes[k], ce, nblocks, total_elems)

        # -- 1. region reduce-scatter (codes -> stripe owners) ---------
        agg = None
        my_se = elems(m)
        if my_se:
            want = self._hrm_want("rs", g, m, s_n, nblocks, wire_name)
            agg = _RawStripeAggregator(
                s_n,
                weights=[float(self._iw[p]) for p in region],
                allowed=self._allowed,
                party=self._me,
                chunk_elems=ce,
                expect_elems=my_se,
                label=f"region {g} stripe {m}",
                meta_check=lambda v: check_region_meta(v, want),
                quant=self._grid,
                quant_blocks=stripes[m],
                quant_ref=(
                    None if self._qref is None else _stripe_slice(
                        self._qref, stripes[m], ce, total_elems
                    )
                ),
            )
            entries = []
            for i, p in enumerate(region):
                if i == m:
                    continue
                entries.append(
                    (p, f"{rs_id}.{g}.{i}.{m}", rs_id, agg.sink(i))
                )
                self._pending_cancels.append(
                    (f"{rs_id}.{g}.{i}.{m}", rs_id)
                )
            if entries:
                self._t.recv_stream_many(entries)

        _maybe_fault("rs", me)
        rs_refs = []
        for k, p in enumerate(region):
            if k == m or not elems(k):
                continue
            payload = {
                "data": _stripe_slice(buf, stripes[k], ce, total_elems),
                "hrm": self._hrm("rs", g, k, s_n, nblocks, wire_name),
            }
            rs_refs.append((p, f"{rs_id}.{g}.{m}.{k}", self._send(
                p, payload, f"{rs_id}.{g}.{m}.{k}", down=rs_id,
                stream=f"{self._stream}/rs",
                quant_meta=self._codec.descriptor,
            )))
        if agg is not None:
            agg.add_local(
                m, _stripe_slice(buf, stripes[m], ce, total_elems)
            )
        for p, up, ref in rs_refs:
            if not ref.resolve(timeout=backstop):
                raise HierarchyRoundError(
                    f"region reduce-scatter push {up!r} to {p!r} failed"
                )
        if self._timings is not None:
            self._timings["push_s"] = time.perf_counter() - t_call0

        raw_stripe = None
        if agg is not None:
            raw = agg.result(timeout=backstop)  # exact i32 partial sums
            # Narrowest exact width for the wire: bounded by
            # qabs_max * W_total by construction, so the cast is exact.
            raw_stripe = raw.astype(np.dtype(self._ps_dtype))

        # -- 2. partial-sum gather to the region coordinator -----------
        t_mark = _phase_span("region_rs", t_mark)
        _maybe_fault("ps", me)
        if not is_coord:
            if raw_stripe is not None:
                ref = self._send(
                    coord,
                    {
                        "data": raw_stripe,
                        "hrm": self._hrm(
                            "ps", g, m, s_n, nblocks, self._ps_dtype
                        ),
                    },
                    f"{ps_id}.{g}.{m}", down=ps_id,
                    quant_meta=self._codec.descriptor,
                )
                if not ref.resolve(timeout=backstop):
                    raise HierarchyRoundError(
                        f"partial-sum stripe {m} of region {g} to "
                        f"coordinator {coord!r} failed"
                    )
        else:
            ps_full = np.zeros(total_elems, np.dtype(self._ps_dtype))

            def scatter(stripe_arr: np.ndarray, blocks) -> None:
                off = 0
                for b in blocks:
                    size = min(ce, total_elems - b * ce)
                    ps_full[b * ce : b * ce + size] = (
                        stripe_arr[off : off + size]
                    )
                    off += size

            if raw_stripe is not None:
                scatter(raw_stripe, stripes[m])
            ps_refs = {}
            for k, p in enumerate(region):
                if k == m or not elems(k):
                    continue
                ps_refs[k] = (p, self._recv(p, f"{ps_id}.{g}.{k}", ps_id))
            for k, (p, ref) in ps_refs.items():
                value = ref.resolve(timeout=backstop)
                check_region_meta(
                    value["hrm"],
                    self._hrm_want(
                        "ps", g, k, s_n, nblocks, self._ps_dtype
                    ),
                )
                arr = np.asarray(value["data"]).reshape(-1)
                if arr.size != elems(k):
                    raise HierarchyRoundError(
                        f"partial-sum stripe {k} of region {g} carries "
                        f"{arr.size} elements, schedule says {elems(k)}"
                    )
                scatter(arr, stripes[k])

        t_mark = _phase_span("region_gather", t_mark)
        return (ps_full if is_coord else None), t_mark

    def _leaf_hub(self, q, _phase_span, t_mark, t_call0):
        """Sections 1-2, quorum mode: members stream their full code
        trees to the region coordinator, whose deadline-gated quorum
        fold (the flat quorum path's pin-members-and-refold contract,
        region-scoped) emits the ARRIVED subset's raw integer sum --
        the slow/partially-dead region contributes what landed instead
        of aborting the round.  Returns ``(ps_full, arrived_members,
        t_mark)``; non-coordinators report the full live region."""
        from rayfed_tpu import telemetry as _telemetry

        me = self._me
        lay = self._lay
        rs_id = self._keys[0]
        backstop = self._backstop
        g = self._g
        region = lay.live[g]
        m = region.index(me)
        coord = lay.coordinators[g]

        if me != coord:
            _maybe_fault("rs", me)
            ref = self._send(
                coord, q, f"{rs_id}.q.{g}.{m}", down=rs_id,
                stream=f"{self._stream}/rs",
                quant_meta=self._codec.descriptor,
            )
            if not ref.resolve(timeout=backstop):
                raise HierarchyRoundError(
                    f"code-tree push of member {m} of region {g} to "
                    f"coordinator {coord!r} failed"
                )
            if self._timings is not None:
                self._timings["push_s"] = time.perf_counter() - t_call0
            t_mark = _phase_span("region_rs", t_mark)
            _maybe_fault("ps", me)
            t_mark = _phase_span("region_gather", t_mark)
            return None, list(region), t_mark

        agg = _RegionHubAggregator(
            len(region),
            weights=[float(self._iw[p]) for p in region],
            allowed=self._allowed,
            party=self._me,
            chunk_elems=self._grid.chunk_elems,
            quorum=min(self._region_quorum, len(region)),
            labels=list(region),
            quant=self._grid,
            quant_ref=self._qref,
        )
        entries = []
        for i, p in enumerate(region):
            if i == m:
                continue
            entries.append(
                (p, f"{rs_id}.q.{g}.{i}", rs_id, agg.sink(i))
            )
            self._pending_cancels.append((f"{rs_id}.q.{g}.{i}", rs_id))
        if entries:
            self._t.recv_stream_many(entries)
        _maybe_fault("rs", me)
        agg.add_local(m, q)
        if self._timings is not None:
            self._timings["push_s"] = time.perf_counter() - t_call0
        raw = agg.result(
            timeout=backstop, deadline_s=self._region_deadline_s
        )
        t_mark = _phase_span("region_rs", t_mark)
        _maybe_fault("ps", me)
        arrived = [region[i] for i in agg.quorum_members]
        if len(arrived) < len(region):
            HIER_STATS["region_cutoffs"] += 1
            _telemetry.event(
                "hier.region_cutoff", round=self._round_tag,
                epoch=self._epoch, party=me, outcome="cutoff",
                detail={
                    "region": g,
                    "arrived": arrived,
                    "excluded": [
                        p for p in region if p not in arrived
                    ],
                },
            )
        # Narrowest exact width for the wire: bounded by qabs_max * W
        # of the FULL region roster (arrived <= roster), so the cast
        # is exact under any cutoff.
        ps_full = raw.astype(np.dtype(self._ps_dtype))
        t_mark = _phase_span("region_gather", t_mark)
        return ps_full, arrived, t_mark


    def _decode_down(self, value: Any) -> PackedTree:
        if isinstance(value, RegionSumTree):
            raise HierarchyRoundError(
                "broadcast carried a RegionSumTree — the downlink must "
                "be the FINALIZED aggregate"
            )
        if isinstance(value, QuantizedPackedTree):
            return value.dequantize(
                np.float32,
                ref=self._qref if value.gmeta.mode == "delta" else None,
            )
        if not isinstance(value, PackedTree):
            raise HierarchyRoundError(
                f"broadcast carried {type(value).__name__}, expected "
                f"the aggregated PackedTree"
            )
        return value

    def _poison_edges(self, exc: BaseException) -> None:
        """Best-effort poison of every rendezvous key this party
        produces, so peers parked on them raise within a round trip
        (the fl.ring cascade, tree-shaped: the abort travels up the
        coordinated chain and back down every branch)."""
        poison = getattr(self._t, "_send_poison", None)
        if poison is None:
            return
        lay = self._lay
        me = self._me
        rs_id, ps_id, up_id, down_id, commit_id, release_id = self._keys
        g = self._g
        if g is None:  # pragma: no cover - run() rejects dead callers
            return
        region = lay.live[g]
        m = region.index(me)
        coord = lay.coordinators[g]
        edges: List[tuple] = []
        if self._region_quorum is None:
            for k, p in enumerate(region):
                if k != m:
                    edges.append((p, f"{rs_id}.{g}.{m}.{k}", rs_id))
            if me != coord:
                edges.append((coord, f"{ps_id}.{g}.{m}", ps_id))
        elif me != coord:
            # The hub sink: a poisoned stream marks this member FAILED,
            # which lets the coordinator's quorum cut off immediately
            # instead of waiting out the deadline.
            edges.append((coord, f"{rs_id}.q.{g}.{m}", rs_id))
        if me != coord:
            if self._ring_downlink:
                # My relay commit token: the coordinator unparks (and
                # its own cascade then unparks my ring successor).
                edges.append(
                    (coord, f"{commit_id}.m.{g}.{me}", commit_id)
                )
        else:
            # Up/commit toward my parent coordinator...
            if self._coordinated and me != lay.root:
                lvh, nidh = self._coordinated[-1]
                parent = self._coord_of(lvh + 1, nidh // lay.branch)
                edges.append(
                    (parent, f"{up_id}.{lvh + 1}.{nidh}", up_id)
                )
                edges.append(
                    (parent, f"{commit_id}.{lvh}.{nidh}", commit_id)
                )
            # ...and down/release toward every child coordinator and
            # region member parked on my broadcast.
            for lv, nid in self._coordinated[1:]:
                for c in lay.levels[lv - 1][nid].children:
                    cc = self._coord_of(lv - 1, c)
                    if cc != me:
                        edges.append((cc, f"{down_id}.c", down_id))
                        edges.append(
                            (cc, f"{release_id}.r", release_id)
                        )
            for p in region:
                if p != me:
                    edges.append((p, f"{down_id}.m", down_id))
                    edges.append((p, f"{release_id}.r", release_id))
        for dest, up, down in edges:
            if dest == me:
                continue
            try:
                poison(dest, up, down, exc)
            except Exception:  # pragma: no cover - best effort
                logger.exception(
                    "[%s] failed to poison hierarchy edge (%s, %s) at "
                    "%s", me, up, down, dest,
                )



def hierarchy_aggregate(
    fed_objects: Sequence[Any],
    weights: Optional[Sequence[float]] = None,
    *,
    region_size: int,
    stream: str = "hier",
    timeout: Optional[float] = None,
    quant: Any = None,
    quant_ref: Optional[Any] = None,
    quant_scope: Optional[str] = None,
    quant_downlink: bool = False,
    seq_ids: Optional[Sequence[Any]] = None,
    round_tag: Optional[int] = None,
    epoch: Optional[int] = None,
    timings: Optional[Dict[str, float]] = None,
    dead: Sequence[str] = (),
    server_step: Optional[Any] = None,
    region_branch: Optional[int] = None,
    region_quorum: Optional[int] = None,
    region_deadline_s: Optional[float] = None,
    ring_downlink: bool = True,
) -> Any:
    """FedAvg round over the derived multi-level hierarchy (see module
    docstring).

    ``region_branch``: interior tree degree (default
    ``max(2, region_size)`` — one interior level, i.e. the classic
    2-level shape, until the region count exceeds it).
    ``region_quorum``/``region_deadline_s``: per-region quorum cutoffs
    — each leaf region contributes its deadline-gated arrived-subset
    partial sum instead of aborting the round; the root's finalize
    reweights to the true arrived Σw.  ``ring_downlink``: relay the
    broadcast member→member inside each region (default) instead of a
    coordinator fan-out.

    ``server_step`` (:mod:`rayfed_tpu.fl.server_opt`): applied ONCE, at
    the root, to the exact finalized f32 aggregate; the tree broadcast
    (and its ``quantize_downlink`` recode) carries the post-step model,
    so every controller returns the stepped bytes — byte-identical to
    the flat streaming/quorum paths applying the same step.

    Drop-in for ``streaming_aggregate``/``ring_aggregate`` when the
    contributions are PackedTrees with one contribution per party and
    the round runs in the compressed domain (``quant`` is REQUIRED —
    hierarchical float sums are a loud exclusion): every controller
    calls it at the same program point with the same arguments and
    returns the identical aggregate bytes — byte-identical to
    :func:`~rayfed_tpu.fl.fedavg.packed_quantized_sum` over the same
    contributions, and therefore to the flat quantized streaming path.

    ``region_size`` partitions the sorted roster deterministically
    (:func:`~rayfed_tpu.transport.manager.partition_regions`).
    ``seq_ids``: :data:`HIER_SEQ_IDS` pre-allocated rendezvous ids (the
    quorum driver passes round-derived string keys).  ``epoch`` stamps
    every frame (stale-region frames are rejected loudly).  Aborted
    rounds raise :class:`HierarchyRoundError` on EVERY controller so
    the driver can fall back in lockstep.  Multi-host parties: leader
    processes only (like ``streaming_aggregate``).
    """
    from rayfed_tpu.fed_object import FedObject
    from rayfed_tpu.runtime import get_runtime

    runtime = get_runtime()
    objs = list(fed_objects)
    if not objs:
        raise ValueError(
            "hierarchy_aggregate needs at least one contribution"
        )
    for obj in objs:
        if not isinstance(obj, FedObject):
            raise TypeError(
                "hierarchy_aggregate consumes FedObjects (party-owned "
                f"contributions), got {type(obj).__name__}"
            )
    owners = [obj.get_party() for obj in objs]
    if len(set(owners)) != len(owners):
        raise ValueError(
            "hierarchy_aggregate needs exactly one contribution per "
            f"party (owners: {owners}) — aggregate duplicates locally "
            f"first"
        )
    if weights is not None and len(weights) != len(objs):
        raise ValueError(
            f"{len(weights)} weights for {len(objs)} contributions"
        )
    if seq_ids is None:
        seq_ids = [runtime.next_seq_id() for _ in range(HIER_SEQ_IDS)]
    me = runtime.party
    backstop = (
        timeout if timeout is not None
        else runtime.job_config.recv_backstop_s
    )
    w_map = (
        None if weights is None
        else {p: float(w) for p, w in zip(owners, weights)}
    )
    if me not in owners:
        raise HierarchyRoundError(
            f"{me!r} contributes nothing this round — observer "
            f"controllers are not supported by hierarchy rounds (use "
            f"the flat streaming path)"
        )
    rnd = HierarchyRound(
        runtime.send_proxy,
        party=me,
        members=owners,
        region_size=region_size,
        grid=quant,
        quant_ref=quant_ref,
        keys=seq_ids,
        weights=w_map,
        stream=stream,
        epoch=epoch,
        round_tag=round_tag,
        backstop=backstop,
        quant_scope=quant_scope,
        allowed=runtime.cluster_config.serializing_allowed_list,
        quant_downlink=quant_downlink,
        dead=dead,
        timings=timings,
        server_step=server_step,
        branch=region_branch,
        region_quorum=region_quorum,
        region_deadline_s=region_deadline_s,
        ring_downlink=ring_downlink,
    )
    local_value = (
        objs[owners.index(me)].get_local_ref().resolve(timeout=backstop)
    )
    return rnd.run(local_value)
