"""Server-side federated optimization (FedOpt) and FedProx.

The reference ships the *engine* only; the algorithms FL practitioners
reach for next are small, deterministic pytree transforms that fit the
multi-controller model (every party runs the identical update on the
identical aggregate, so no extra coordination is needed):

- **FedOpt** (Reddi et al., "Adaptive Federated Optimization", 2021):
  treat the round's aggregate as a *pseudo-gradient*
  ``Δ = global − average(client updates)`` and apply a first-class
  server optimizer (SGD+momentum / Adam / Yogi) instead of plain
  replacement.  Plain FedAvg is the special case lr=1, no momentum.
- **FedProx** (Li et al., 2020): a client-side proximal term
  ``(μ/2)·‖w − w_global‖²`` that keeps heterogeneous parties from
  drifting; implemented as a loss wrapper so any local step works.

Everything here is jit-compiled pytree arithmetic — one fused XLA op
per leaf on device, the same shape as :func:`rayfed_tpu.fl.tree_average`.

These are the LEGACY (unpacked-tree) optimizers: they run per-leaf on
the driver's decompressed tree, which is why they are excluded from
every packed-domain path (``wire_quant``, ``quorum``,
``mode="hierarchy"``).  :mod:`rayfed_tpu.fl.server_opt` is the packed
rework — server momentum and FedAC as fused finalize-side kernels over
the packed wire buffers, composing with all of the above — and is what
new code should reach for.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class ServerOptimizer(NamedTuple):
    """A server optimizer as an (init, apply) pair.

    ``init(params) -> state``; ``apply(params, round_average, state) ->
    (new_params, new_state)`` where ``round_average`` is the plain
    FedAvg aggregate of the round's client updates.  Both are pure and
    deterministic: every controller computes the identical result.
    """

    init: Callable[[Any], Any]
    apply: Callable[[Any, Any, Any], tuple]


def _tree_zeros(params: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params
    )


def server_sgd(lr: float = 1.0, momentum: float = 0.0) -> ServerOptimizer:
    """FedAvgM: pseudo-gradient SGD with (optional) server momentum.

    ``lr=1, momentum=0`` reproduces plain FedAvg exactly.
    """

    def init(params):
        return _tree_zeros(params) if momentum else ()

    @jax.jit
    def apply(params, avg, state):
        delta = jax.tree_util.tree_map(
            lambda p, a: p.astype(jnp.float32) - a.astype(jnp.float32),
            params,
            avg,
        )
        if momentum:
            state = jax.tree_util.tree_map(
                lambda m, d: momentum * m + d, state, delta
            )
            step = state
        else:
            step = delta
        new = jax.tree_util.tree_map(
            lambda p, s: (p.astype(jnp.float32) - lr * s).astype(p.dtype),
            params,
            step,
        )
        return new, state

    return ServerOptimizer(init, apply)


def _adaptive(
    lr: float, b1: float, b2: float, eps: float, yogi: bool
) -> ServerOptimizer:
    def init(params):
        return {"m": _tree_zeros(params), "v": _tree_zeros(params)}

    @jax.jit
    def apply(params, avg, state):
        delta = jax.tree_util.tree_map(
            lambda p, a: p.astype(jnp.float32) - a.astype(jnp.float32),
            params,
            avg,
        )
        m = jax.tree_util.tree_map(
            lambda m, d: b1 * m + (1 - b1) * d, state["m"], delta
        )
        if yogi:
            # Yogi: additive, sign-controlled second-moment update —
            # less aggressive forgetting than Adam under heavy-tailed
            # pseudo-gradients (Reddi et al. §3).
            v = jax.tree_util.tree_map(
                lambda v, d: v - (1 - b2) * jnp.sign(v - d * d) * d * d,
                state["v"],
                delta,
            )
        else:
            v = jax.tree_util.tree_map(
                lambda v, d: b2 * v + (1 - b2) * d * d, state["v"], delta
            )
        new = jax.tree_util.tree_map(
            lambda p, m, v: (
                p.astype(jnp.float32) - lr * m / (jnp.sqrt(v) + eps)
            ).astype(p.dtype),
            params,
            m,
            v,
        )
        return new, {"m": m, "v": v}

    return ServerOptimizer(init, apply)


def server_adam(
    lr: float = 0.01, b1: float = 0.9, b2: float = 0.99, eps: float = 1e-3
) -> ServerOptimizer:
    """FedAdam (Reddi et al. alg. 2; their recommended eps is large)."""
    return _adaptive(lr, b1, b2, eps, yogi=False)


def server_yogi(
    lr: float = 0.01, b1: float = 0.9, b2: float = 0.99, eps: float = 1e-3
) -> ServerOptimizer:
    """FedYogi (Reddi et al. alg. 2 with Yogi's second moment)."""
    return _adaptive(lr, b1, b2, eps, yogi=True)


def fedprox_loss(
    loss_fn: Callable[..., jax.Array], mu: float
) -> Callable[..., jax.Array]:
    """Wrap a local loss with FedProx's proximal term.

    ``loss_fn(params, *batch) -> scalar`` becomes
    ``wrapped(params, global_params, *batch) -> scalar`` adding
    ``(μ/2)·‖params − global_params‖²`` — heterogeneous parties stay
    anchored to the round's global model.  ``mu=0`` is plain FedAvg.
    """

    def wrapped(params, global_params, *batch):
        base = loss_fn(params, *batch)
        # tree_map, not a zip of flat leaves: a structure mismatch
        # (extra/missing leaf) must raise, not silently pair leaves
        # against the wrong counterparts.
        sq_tree = jax.tree_util.tree_map(
            lambda p, g: jnp.sum(
                (p.astype(jnp.float32) - g.astype(jnp.float32)) ** 2
            ),
            params,
            global_params,
        )
        sq = sum(jax.tree_util.tree_leaves(sq_tree))
        return base + 0.5 * mu * sq

    return wrapped
