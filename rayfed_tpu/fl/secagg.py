"""Secure aggregation: pairwise-masked integer folds, sum-only reveal.

Cross-silo FL's canonical privacy primitive (Bonawitz et al., "Practical
Secure Aggregation for Privacy-Preserving Machine Learning", CCS 2017),
built as a free rider on the compressed-domain integer folds of
:mod:`rayfed_tpu.fl.quantize`:

1. **Key agreement rides the HELLO handshake**
   (:mod:`rayfed_tpu.transport.secagg`): each party publishes an
   ephemeral per-session key in the connection HELLO it already performs
   with every peer, and per-(pair, session, stream, round) mask seeds
   derive via HKDF.  Masks are *generated, never shipped* — zero payload
   bytes on the wire.

2. **Masking in the quantized integer domain**: after delta-quantization
   onto the round's shared grid, a party's contribution becomes
   ``w·q + Σ_j ±PRG(seed_pair(j))  (mod 2³²)`` — its own integer weight
   folded in, plus one pairwise keystream per active peer, added by the
   lower-named endpoint of each pair and subtracted by the higher-named
   (one fused jit, :func:`rayfed_tpu.fl.fedavg.masked_code_kernel`).
   The masked codes ship as i32 and fold through the UNCHANGED integer
   kernels (:func:`~rayfed_tpu.fl.fedavg.quantized_accum_kernel` at unit
   weight — i32 addition wraps mod 2³², is associative, and every pair
   mask appears exactly once positive and once negative), so the
   accumulator after cancellation holds exactly ``Σ w_i·q_i`` and the
   ONE fused rescale emits bytes **identical to the unmasked round's**.
   The aggregator learns only the sum; any single masked contribution is
   uniform ring noise.

3. **Quorum-dropout mask recovery** (:mod:`rayfed_tpu.fl.quorum`): the
   deadline-gated cutoff pins the member set; the coordinator's cutoff
   announcement names it, each survivor replies with its pairwise seeds
   toward the dropped parties (scoped to THAT round's seeds — the
   per-round HKDF keeps every other round dark), and the coordinator
   subtracts the orphaned masks (:func:`mask_correction`) before the
   finalize rescale.

Overflow/exactness: the masked values wrap mod 2³² BY DESIGN; after the
pair masks cancel, the residual is the true ``Σ w_i·q_i``, which the
grid's existing headroom guard (``qabs_max · W ≤ 2³¹−1``) keeps exactly
representable — the same bound the unmasked integer fold already
enforces, so masked and unmasked rounds are byte-identical, not merely
close.

This module also absorbs the seed-era :mod:`rayfed_tpu.fl.secure` demo:
its in-process fixed-point primitives (:func:`pairwise_key`,
:func:`mask_update`, :func:`unmask_sum`) live here now, and
``fl/secure.py`` is a thin deprecated shim.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Any, Dict, Optional, Sequence

import numpy as np

from rayfed_tpu.fl.quantize import (
    QuantGrid,
    QuantizedPackedTree,
    QuantMeta,
    RoundCodec,
)
from rayfed_tpu.fl.compression import PackSpec
from rayfed_tpu.transport.secagg import (  # noqa: F401  (re-exported API)
    HAVE_AES,
    HAVE_X25519,
    SECAGG_STATS,
    SECAGG_VERSION,
    KeyAgreement,
    SecAggError,
    hkdf_sha256,
)

logger = logging.getLogger(__name__)

# Wire dtype of masked contributions: the quantized codes widen to i32
# and live in the mod-2³² ring the masks are drawn from.  (8-bit masked
# codes cannot exist: the masked value must be uniform over the ring the
# SUM lives in, or the mask leaks through the wrap.)
MASKED_WIRE_DTYPE = "int32"


# ---------------------------------------------------------------------------
# Mask keystream (PRG)
# ---------------------------------------------------------------------------


def prg_mask(seed: bytes, n: int, scheme: Optional[str] = None) -> np.ndarray:
    """Expand a 256-bit pair seed into ``n`` uint32 mask words.

    ``scheme``: ``"aes"`` — AES-256-CTR keystream (the ``cryptography``
    optional dependency; fast and cryptographic) or ``"philox"`` — the
    numpy Philox counter PRG keyed from the seed (stdlib fallback;
    deterministic and statistically strong but NOT a cryptographic PRG —
    see ``docs/source/secure_aggregation.rst``).  Defaults to the best
    available.  Both endpoints of a pair must expand the identical
    keystream — the scheme is advertised in the HELLO suite and a
    mismatch fails loudly at seed derivation
    (:meth:`~rayfed_tpu.transport.secagg.KeyAgreement.pair_secret`).
    """
    if len(seed) < 32:
        raise SecAggError(f"prg_mask needs a 32-byte seed, got {len(seed)}")
    if scheme is None:
        scheme = "aes" if HAVE_AES else "philox"
    n = int(n)
    if scheme == "aes":
        from cryptography.hazmat.primitives.ciphers import (
            Cipher,
            algorithms,
            modes,
        )

        enc = Cipher(
            algorithms.AES(seed[:32]), modes.CTR(b"\x00" * 16)
        ).encryptor()
        stream = enc.update(b"\x00" * (4 * n))
        return np.frombuffer(stream, dtype="<u4").copy()
    if scheme == "philox":
        key = np.frombuffer(seed[:16], np.uint64)
        gen = np.random.Generator(np.random.Philox(key=key))
        return gen.integers(0, 1 << 32, size=n, dtype=np.uint32)
    raise SecAggError(f"unknown mask PRG scheme {scheme!r}")


# ---------------------------------------------------------------------------
# Per-round masking
# ---------------------------------------------------------------------------


class RoundMasker:
    """One party's mask state for ONE round attempt.

    Binds the key-agreement plane to a concrete ``(session, stream,
    round)``: derives (and caches) the pair seed toward every active
    peer, expands the party's **net mask** ``Σ_j ±PRG(seed_j)`` (sign by
    sorted-name order, so each pair's keystream appears exactly once
    positive and once negative across the parties), and answers dropout
    recovery with the seeds toward the dropped parties.  A coordinator
    failover re-attempts the round under a fresh (successor-scoped)
    stream, so a fresh masker — and fresh seeds — per attempt.

    ``weight``: this party's integral fold weight (FedAvg example
    count); the masked wire value is ``weight·q + net mask`` so unit-
    weight integer folds reproduce the weighted sum exactly (weighted
    pairwise masks could not cancel: ``w_i·m − w_j·m ≠ 0``).

    ``self_mask`` (quorum rounds): Bonawitz **double-masking** — the
    net mask additionally includes ``PRG(b)`` for a fresh private
    per-round seed ``b`` known only to this party, revealed (via the
    cutoff round trip) only if this party MADE the round's member set.
    This is what protects a deadline-excluded-but-alive straggler:
    dropout recovery necessarily reveals the survivors' pairwise seeds
    toward it — which by symmetry are its own seeds toward them — but
    its late-arriving payload still carries ``PRG(b)``, which nobody
    else ever learns, so it stays uniform ring noise to the
    coordinator.  The all-of-n streaming path runs pairwise-only
    (``self_mask=False``): it has no reveal round trip, and no seed is
    ever disclosed there.

    :meth:`prefetch` expands the net mask on a background thread — the
    keystream depends on nothing round-specific beyond the seeds, so
    generation overlaps local training / the wire instead of sitting on
    the round's critical path.
    """

    def __init__(
        self,
        keys: KeyAgreement,
        party: str,
        peers: Sequence[str],
        *,
        session: str,
        stream: str,
        round_index: int,
        weight: int = 1,
        self_mask: bool = False,
    ) -> None:
        if keys is None:
            raise SecAggError(
                "secure aggregation needs the transport's key-agreement "
                "plane (TransportManager.secagg_keys) — this transport "
                "has none"
            )
        self._keys = keys
        self.party = str(party)
        self.peers = sorted(str(p) for p in peers)
        if self.party in self.peers:
            raise SecAggError("a party cannot be its own mask peer")
        self.session = str(session)
        self.stream = str(stream)
        self.round_index = int(round_index)
        self.weight = int(weight)
        if self.weight < 0:
            raise SecAggError(
                f"masked folds need a non-negative integral weight, got "
                f"{weight!r}"
            )
        # The self-mask seed is PRIVATE randomness (never derived from
        # shared state, never equal across attempts) — a failover
        # attempt builds a fresh masker and so a fresh b.
        self._self_seed: Optional[bytes] = (
            os.urandom(32) if self_mask else None
        )
        self._seeds: Dict[str, bytes] = {}
        self._net: Optional[np.ndarray] = None
        self._net_thread: Optional[threading.Thread] = None
        self._net_err: Optional[BaseException] = None
        self._lock = threading.Lock()

    def seed_for(self, peer: str) -> bytes:
        """The (cached) pair seed toward ``peer`` for this round."""
        with self._lock:
            s = self._seeds.get(peer)
        if s is None:
            s = self._keys.pair_seed(
                peer, session=self.session, stream=self.stream,
                round_index=self.round_index,
            )
            with self._lock:
                self._seeds[peer] = s
        return s

    def _compute_net(self, n: int) -> np.ndarray:
        net = np.zeros(n, np.uint32)
        if self._self_seed is not None:
            net += prg_mask(self._self_seed, n, self._keys.prg_scheme)
        for peer in self.peers:
            ks = prg_mask(self.seed_for(peer), n, self._keys.prg_scheme)
            if self.party < peer:
                net += ks  # uint32 wraps mod 2**32 — the ring we want
            else:
                net -= ks
        return net

    def self_seed_hex(self) -> str:
        """The self-mask seed, hex — revealed ONLY by a party that made
        the member set (its contribution is in the sum, so its ``b``
        must be subtracted); an excluded party never discloses it."""
        if self._self_seed is None:
            raise SecAggError(
                "this masker carries no self-mask (self_mask=False — "
                "the all-of-n streaming path)"
            )
        return self._self_seed.hex()

    def prefetch(self, n: int) -> None:
        """Start expanding the net mask on a background thread (no-op if
        already running/done).  :meth:`net_mask` joins it."""
        with self._lock:
            if self._net is not None or self._net_thread is not None:
                return

            def _run():
                try:
                    net = self._compute_net(int(n))
                    with self._lock:
                        self._net = net
                # fedlint: disable=FED004 — transferred, not swallowed: the error re-raises from net_mask() on the round's thread
                except BaseException as e:
                    self._net_err = e

            self._net_thread = threading.Thread(
                target=_run, name="rayfed-secagg-prg", daemon=True
            )
            self._net_thread.start()

    def net_mask(self, n: int) -> np.ndarray:
        """This party's net mask for an ``n``-element code buffer
        (uint32; add it to ``weight·q`` mod 2³²)."""
        n = int(n)
        with self._lock:
            th = self._net_thread
        if th is not None:
            th.join()
            if self._net_err is not None:
                raise self._net_err
        with self._lock:
            if self._net is not None:
                if self._net.size != n:
                    raise SecAggError(
                        f"prefetched mask covers {self._net.size} "
                        f"elements, round needs {n}"
                    )
                return self._net
        net = self._compute_net(n)
        with self._lock:
            self._net = net
        return net

    def recovery_seeds(self, dropped: Sequence[str]) -> Dict[str, str]:
        """This survivor's pairwise seeds toward the dropped parties —
        the recovery reply body (hex-encoded; coordinator-only, scoped
        to THIS round's seeds)."""
        out: Dict[str, str] = {}
        for j in dropped:
            j = str(j)
            if j == self.party:
                continue
            if j not in self.peers:
                raise SecAggError(
                    f"recovery asked for seeds toward {j!r}, which was "
                    f"not a mask peer this round ({self.peers})"
                )
            out[j] = self.seed_for(j).hex()
        return out


def _seed_from_hex(hexseed: str, who: str, what: str) -> bytes:
    try:
        return bytes.fromhex(hexseed)
    except (ValueError, TypeError) as e:
        raise SecAggError(
            f"malformed {what} from {who!r}: not a hex seed ({e})"
        ) from None


def mask_correction(
    survivor_seeds: Dict[str, Dict[str, str]],
    dropped: Sequence[str],
    n: int,
    prg_scheme: Optional[str] = None,
    members: Optional[Sequence[str]] = None,
    self_seeds: Optional[Dict[str, str]] = None,
) -> np.ndarray:
    """The mask correction of a quorum round's cutoff (coordinator).

    ``survivor_seeds``: ``{survivor: {dropped party: seed hex}}`` — one
    entry per member of the pinned set (the coordinator contributes its
    own seeds without a wire hop).  The folded accumulator holds, beyond
    ``Σ_{i∈M} w_i·q_i``, the residual ``Σ_{i∈M} Σ_{j∈D} ±PRG(seed_ij)``
    (each survivor's masks toward the dropped never met their negatives)
    — this function expands exactly that residual (uint32, mod 2³²) for
    the aggregator to SUBTRACT before the finalize rescale.

    ``self_seeds``: ``{member: self-mask seed hex}`` (double-masking,
    see :class:`RoundMasker`) — each member's ``PRG(b_i)`` rides its
    folded contribution and is added to the correction here; a dropped
    party's ``b`` is neither needed (its contribution was not folded)
    nor ever revealed, which is what keeps its late payload noise.

    Raises loudly when any (survivor, dropped) pair's seed or member
    self-seed is missing, and — when ``members`` is given — when the
    survivor set does not cover the pinned member set exactly: an
    incomplete (or mis-keyed) correction would silently corrupt the
    round.
    """
    if members is not None:
        want = {str(p) for p in members}
        have = {str(p) for p in survivor_seeds}
        if have != want:
            raise SecAggError(
                f"mask recovery incomplete: seeds collected from "
                f"{sorted(have)} but the pinned member set is "
                f"{sorted(want)} — cannot finalize the round"
            )
    dropped = sorted(str(j) for j in dropped)
    corr = np.zeros(int(n), np.uint32)
    recovered = 0
    for i in sorted(survivor_seeds):
        seeds = survivor_seeds[i]
        for j in dropped:
            if j == i:
                continue
            hexseed = seeds.get(j)
            if not hexseed:
                raise SecAggError(
                    f"mask recovery incomplete: survivor {i!r} supplied "
                    f"no seed toward dropped party {j!r} — cannot "
                    f"finalize the round"
                )
            ks = prg_mask(
                _seed_from_hex(hexseed, i, f"recovery seed toward {j!r}"),
                int(n), prg_scheme,
            )
            if i < j:
                corr += ks
            else:
                corr -= ks
            recovered += 1
    if self_seeds is not None:
        for i in sorted({str(p) for p in (members or self_seeds)}):
            b = self_seeds.get(i)
            if not b:
                raise SecAggError(
                    f"mask recovery incomplete: member {i!r} supplied "
                    f"no self-mask seed — cannot finalize the round"
                )
            corr += prg_mask(
                _seed_from_hex(b, i, "self-mask seed"), int(n),
                prg_scheme,
            )
    SECAGG_STATS["recovered_seeds"] += recovered
    return corr


# ---------------------------------------------------------------------------
# Recovery control messages (cross-party contract — fingerprinted by
# tool/check_wire_format.py like the ring stripe manifest: payload-level
# schemas, no frame-layout change)
# ---------------------------------------------------------------------------


def make_recovery_request(
    members: Sequence[str], dropped: Sequence[str]
) -> Dict[str, Any]:
    """The coordinator's post-cutoff announcement to every active party:
    the pinned member set and the dropped parties whose masks need
    recovery (empty ``dr`` = nothing to recover; survivors just proceed
    to the result broadcast).  Single producer of the schema."""
    return {
        "v": SECAGG_VERSION,
        "m": sorted(str(p) for p in members),
        "dr": sorted(str(p) for p in dropped),
    }


def make_recovery_reply(
    party: str, seeds: Dict[str, str], self_seed: str
) -> Dict[str, Any]:
    """One member's cutoff reply: its pairwise seeds toward the dropped
    parties (hex; empty dict when nobody dropped) and its OWN self-mask
    seed ``b`` (revealed because this party made the member set — its
    contribution is in the sum).  Single producer of the schema."""
    return {
        "v": SECAGG_VERSION,
        "p": str(party),
        "sd": dict(seeds),
        "b": str(self_seed),
    }


def check_recovery_message(msg: Any, kind: str) -> Dict[str, Any]:
    """Validate a received recovery request/reply (version + shape);
    raises naming the problem instead of KeyError-ing mid-recovery."""
    if not isinstance(msg, dict):
        raise SecAggError(f"malformed secagg {kind}: {type(msg).__name__}")
    try:
        ver = int(msg.get("v", 0))
    except (TypeError, ValueError):
        raise SecAggError(
            f"malformed secagg {kind}: non-integer version "
            f"{msg.get('v')!r}"
        ) from None
    if ver > SECAGG_VERSION:
        raise SecAggError(
            f"secagg {kind} uses schema v{msg.get('v')}; this party "
            f"speaks up to v{SECAGG_VERSION}"
        )
    want = ("m", "dr") if kind == "request" else ("p", "sd", "b")
    for k in want:
        if k not in msg:
            raise SecAggError(f"secagg {kind} is missing field {k!r}")
    return msg


# ---------------------------------------------------------------------------
# Masked wire form + codec
# ---------------------------------------------------------------------------


class MaskedCodeTree(QuantizedPackedTree):
    """Wire form of a masked contribution: ``weight·q + net mask`` as an
    i32 buffer, with the round grid's descriptor riding along (the fold
    layer still verifies the grid fingerprint before folding).

    Deliberately NOT decodable: a masked buffer is uniform ring noise
    without the peers' contributions — :meth:`dequantize`/:meth:`unpack`
    raise instead of silently rescaling garbage.  Fold with a masked
    :class:`~rayfed_tpu.fl.streaming.StreamingAggregator`, whose unit-
    weight integer fold cancels the masks bit-exactly.
    """

    __slots__ = ()

    def dequantize(self, out_dtype: Any = np.float32,
                   ref: Optional[Any] = None):
        raise SecAggError(
            "a MaskedCodeTree is uniform ring noise on its own — only "
            "the masked FOLD (StreamingAggregator(masked=True)) can "
            "cancel the pairwise masks; there is nothing to dequantize"
        )

    def unpack(self, dtype: Any = None):
        raise SecAggError(
            "a MaskedCodeTree cannot be unpacked — see dequantize"
        )

    def __reduce__(self):
        return (
            MaskedCodeTree,
            (self.buf, self.scales, self.zps, self.passthrough,
             self.spec, self.gmeta),
        )

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"MaskedCodeTree({self.gmeta.total_elems} masked i32 codes "
            f"on grid fp={self.gmeta.fp:#010x})"
        )


import jax  # noqa: E402  (after the numpy-only machinery, like quantize)

jax.tree_util.register_pytree_node(
    MaskedCodeTree,
    lambda mt: (
        (mt.buf, mt.scales, mt.zps, *mt.passthrough),
        (mt.spec, mt.gmeta),
    ),
    lambda aux, ch: MaskedCodeTree(
        ch[0], ch[1], ch[2], tuple(ch[3:]), aux[0], aux[1]
    ),
)


class MaskedRoundCodec(RoundCodec):
    """The masked sender-side codec discipline: grid quantization (with
    the inherited fingerprint check + error-feedback two-phase commit)
    followed by the fused weight-and-mask step.  Drop-in where a
    :class:`~rayfed_tpu.fl.quantize.RoundCodec` goes — streaming and
    quorum call ``to_wire``/``commit``/``rollback`` identically."""

    __slots__ = ("masker",)

    def __init__(self, grid: Optional[QuantGrid], ref: Optional[Any],
                 scope: Optional[str], masker: RoundMasker) -> None:
        if grid is None:
            raise SecAggError(
                "secure aggregation requires the shared quantization "
                "grid (wire_quant) — masks live in the integer domain"
            )
        super().__init__(grid, ref, scope)
        self.masker = masker

    def to_wire(self, value: Any) -> MaskedCodeTree:
        if isinstance(value, MaskedCodeTree):
            raise SecAggError("contribution is already masked")
        # Overlap the keystream expansion with the quantize kernel.
        self.masker.prefetch(self.grid.total_elems)
        qt = super().to_wire(value)
        if qt.passthrough:
            # Non-float (passthrough) leaves do not live on the packed
            # buffer, so the masks cannot cover them — shipping them in
            # the clear would silently break the "uniform ring noise"
            # guarantee for exactly the leaves the caller forgot about.
            # Loud exclusion, like every other composition gap.
            raise SecAggError(
                f"secure aggregation covers the packed float buffer "
                f"only, but this update carries "
                f"{len(qt.passthrough)} non-float (passthrough) "
                f"leaf(s) that would ship UNMASKED — drop them from "
                f"the update tree (or encode them as floats) before "
                f"masking"
            )
        from rayfed_tpu.fl.fedavg import masked_code_kernel

        mask = self.masker.net_mask(self.grid.total_elems)
        buf = masked_code_kernel()(
            qt.buf, np.int32(self.masker.weight), mask
        )
        SECAGG_STATS["masked_rounds"] += 1
        spec = PackSpec(qt.spec.entries, qt.spec.treedef, MASKED_WIRE_DTYPE)
        return MaskedCodeTree(
            np.asarray(buf), qt.scales, qt.zps, qt.passthrough, spec,
            qt.gmeta,
        )


# ---------------------------------------------------------------------------
# Seed-era in-process primitives (moved from fl/secure.py — that module
# is now a deprecated shim over these)
# ---------------------------------------------------------------------------

_MOD = 2**32


def pairwise_key(group_key: bytes, a: str, b: str, round_num: int) -> bytes:
    """256-bit seed for the (a, b) pair at one round — order-independent.

    The seed-era group-key derivation, kept for the in-process
    :func:`mask_update`/:func:`unmask_sum` primitives.  The transport
    rounds derive their seeds from the HELLO key agreement instead
    (:meth:`~rayfed_tpu.transport.secagg.KeyAgreement.pair_seed`).

    The full digest feeds the mask XOF: truncating to a JAX PRNGKey
    would cap the keyspace at threefry's 64 bits, which an
    honest-but-curious aggregator could brute-force offline against a
    single masked update.
    """
    lo, hi = sorted((a, b))
    lo_b, hi_b = lo.encode(), hi.encode()
    # Length-prefixed components: a '|'-delimited preimage would let
    # names containing '|' collide across pairs (('a','b|c') vs
    # ('a|b','c')), handing one pair another pair's mask seed.
    return hashlib.sha256(
        b"rayfed-secagg|%d:%s|%d:%s|%d|"
        % (len(lo_b), lo_b, len(hi_b), hi_b, round_num)
        + group_key
    ).digest()


def _encode(tree: Any, clip: float, frac_bits: int) -> Any:
    """Float pytree → uint32 fixed-point (two's-complement wrap).

    Values are clipped to ±``clip`` first: fixed-point needs a known
    range, and secure aggregation deployments clip updates anyway (the
    mask hides magnitudes only within the ring).
    """
    import jax.numpy as jnp

    scale = float(2**frac_bits)

    def enc(x):
        x = jnp.clip(x.astype(jnp.float32), -clip, clip)
        # int32 → uint32 astype is the two's-complement embedding into
        # the ring (wraps mod 2³²); clip·2^frac_bits < 2³¹ keeps the
        # int32 exact.  No int64 needed (x64 mode stays off).
        return jnp.round(x * scale).astype(jnp.int32).astype(jnp.uint32)

    return jax.tree_util.tree_map(enc, tree)


def _decode(tree: Any, frac_bits: int) -> Any:
    """uint32 fixed-point sum → float pytree.

    uint32 → int32 astype is the two's-complement read (values ≥ 2³¹
    become negative) — exact while |true sum| < 2³¹, which
    :func:`unmask_sum` guards.
    """
    import jax.numpy as jnp

    scale = float(2**frac_bits)
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.int32).astype(jnp.float32) / scale, tree
    )


def _mask_for(seed: bytes, tree: Any) -> Any:
    """One uint32 mask per element, expanded from the 256-bit pair seed.

    SHAKE-256 as the XOF (domain-separated per leaf index) keeps the
    full seed entropy — unlike JAX's threefry PRNG, whose 64-bit key
    would be the scheme's effective security level.
    """
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    masks = []
    for i, leaf in enumerate(leaves):
        stream = hashlib.shake_256(
            seed + b"|leaf|%d" % i
        ).digest(4 * leaf.size)
        masks.append(
            jnp.asarray(
                np.frombuffer(stream, dtype=np.uint32).reshape(leaf.shape)
            )
        )
    return jax.tree_util.tree_unflatten(treedef, masks)


def mask_update(
    tree: Any,
    *,
    party: str,
    parties: Sequence[str],
    round_num: int,
    group_key: bytes,
    clip: float = 8.0,
    frac_bits: int = 16,
) -> Any:
    """Fixed-point-encode ``tree`` and add this party's pairwise masks.

    The in-process (whole-tree, group-key) primitive — use
    ``run_fedavg_rounds(secure_agg=True)`` for transport rounds, where
    key agreement rides the HELLO handshake and the masks live in the
    shared-grid integer domain instead of a private fixed-point one.

    Returns a uint32 pytree safe to push: without the peers' masked
    updates it is uniformly random in the ring.  ``clip``/``frac_bits``
    must match across parties and in :func:`unmask_sum`.
    """
    if party not in parties:
        raise ValueError(f"party {party!r} not in {list(parties)!r}")
    out = _encode(tree, clip, frac_bits)
    for peer in parties:
        if peer == party:
            continue
        mask = _mask_for(pairwise_key(group_key, party, peer, round_num), out)
        sign = 1 if party < peer else -1
        out = jax.tree_util.tree_map(
            # uint32 arithmetic wraps mod 2^32 — exactly the ring we want.
            (lambda o, m: o + m) if sign > 0 else (lambda o, m: o - m),
            out,
            mask,
        )
    return out


def unmask_sum(
    masked_trees: Sequence[Any], *, frac_bits: int = 16, clip: float = 8.0
) -> Any:
    """Sum all parties' masked updates; masks cancel bit-exactly.

    Returns the float **sum** of the clipped updates (divide by the
    party count for the average).  ``clip`` bounds the representable
    sum: n·clip must stay below 2^(31−frac_bits) or the ring wraps.
    """
    import jax

    n = len(masked_trees)
    if n == 0:
        raise ValueError("unmask_sum needs at least one masked update")
    if n * clip >= float(2 ** (31 - frac_bits)):
        raise ValueError(
            f"{n} parties at clip={clip} overflow the ring at "
            f"frac_bits={frac_bits}; lower frac_bits or clip"
        )
    total = masked_trees[0]
    for t in masked_trees[1:]:
        total = jax.tree_util.tree_map(lambda a, b: a + b, total, t)
    return _decode(total, frac_bits)
