"""Quorum (k-of-n) federated rounds + elastic party membership.

Every aggregation path built so far — coordinator, streaming, ring,
overlap — assumes a fixed roster where every party answers every round:
one slow or dead silo stalls or aborts the round for everyone.  This
module makes the round **survive partial failure**:

- **Quorum rounds** (``run_fedavg_rounds(quorum=k, round_deadline_s=d)``):
  the coordinator aggregates the first *k* of *n* contributions per
  round; once the deadline passes (or the stragglers provably cannot
  arrive) it stops waiting and reweights by the arrived Σw
  (:class:`~rayfed_tpu.fl.streaming.StreamingAggregator` quorum cutoff).
  The aggregate over the member subset *M* is exactly
  ``Σ_{p∈M} w_p·x_p / Σ_{p∈M} w_p`` — bit-identical to
  ``packed_weighted_sum`` over the subset in sorted-party order.

- **Late fold, not drop**: a straggler whose round-*r* contribution
  missed the cutoff still receives the round-*r* broadcast; its local
  progress ``Δ = u_r − input_r`` folds into its round-*r+1* starting
  point via the PR-4 DGA recurrence
  (:func:`~rayfed_tpu.fl.overlap.dga_correct`):
  ``input_{r+1} = agg_r + (u_r − input_r)`` — the party resyncs onto the
  global model while its work survives into the next round's
  contribution.  No party ever diverges: everyone's base is the same
  broadcast.

- **Elastic membership**: the live roster is an epoch-numbered object on
  the transport (:class:`~rayfed_tpu.transport.manager.RosterState`).
  ``fed.join()`` / ``fed.leave()`` / monitor-declared death advance the
  epoch **at a round boundary**, announced by the coordinator in the
  round broadcast so every controller applies the identical transition —
  no consensus protocol, no fed-runtime restart on churn.  Quorum-round
  frames are stamped with their sender's epoch
  (``wire.EPOCH_TAG_KEY``) and STALE-epoch frames are rejected loudly
  (newer-epoch frames pass: the advanced coordinator's broadcast is
  what carries the roster transition to lagging stragglers).

- **Ring rounds honor the quorum** too: ``mode="ring"`` runs the
  chunk-striped ring as usual; a straggler or death aborts the ring
  (its existing poison cascade) and the round re-aggregates over the
  coordinator topology **with the quorum cutoff** — the straggler is
  excluded there instead of failing the round.

Determinism without the global seq counter: every rendezvous key of a
quorum round is derived from ``(session, stream, round index)`` — so a
party that crashed and rejoined only needs the round index (from its
join welcome) to re-align, with no shared counter to reconstruct.  The
session id itself is drawn once per run from the ordinary seq stream
(identical on every non-joining controller) and handed to joiners in
the welcome.

- **Coordinator failover**: the coordinator role is a rotating,
  crash-tolerant lease, not a pinned single point of failure.  When a
  controller's health monitor declares the coordinator dead mid-round,
  it derives the **successor** locally — the next alive party after the
  coordinator on the sorted roster ring
  (:func:`rayfed_tpu.transport.manager.roster_successor`; no election,
  no new consensus) — and **re-establishes the same round** there:
  every survivor re-pushes its retained round-*r* contribution to the
  successor (fresh rendezvous keys derived from the successor-scoped
  stream name), the successor runs the same deadline-gated cutoff and
  refold, and the result stays bit-identical to ``packed_weighted_sum``
  over the arrived member set.  The successor's first announcement
  drops the dead coordinator (epoch advance), so a crash costs at most
  one round of extra latency and zero divergence.  A coordinator
  ``fed.leave()`` is gentler still: it completes the in-flight round,
  and its announcement **names the successor** (a graceful handover) —
  the loud failure remains only when no successor is alive.

- **Checkpointable**: with a ``checkpointer`` each party snapshots
  ``(round, roster epoch, member log, session, params)`` at round
  boundaries; a fully-crashed cluster resumes the quorum run
  deterministically, re-deriving the coordinator from the restored
  roster (a resumed party that was mid-failover lands on the same
  successor every other party derives).
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from rayfed_tpu import chaos, telemetry

logger = logging.getLogger(__name__)

# Cumulative per-process counters of the coordinator-lease transitions
# this controller observed (the bench/CI failover gate reads them):
# ``coordinator_failovers`` — crash-driven successions this controller
# re-established a round through; ``graceful_handovers`` — announced
# coordinator ``fed.leave()`` handovers applied.
QUORUM_STATS = {"coordinator_failovers": 0, "graceful_handovers": 0}


class QuorumRoundError(RuntimeError):
    """A quorum round failed on this controller (quorum unreachable,
    coordinator death with no live successor, broadcast lost)."""


class QuorumRoundOutcome:
    """One quorum round's result on this controller."""

    __slots__ = ("result", "members", "announce", "welcomes")

    def __init__(self, result: Any, members: List[str],
                 announce: Optional[Dict[str, Any]],
                 welcomes: List[Tuple[str, str]]):
        self.result = result  # aggregated PackedTree
        self.members = members  # parties whose contributions made the cut
        self.announce = announce  # {"epoch", "members"} roster advance or None
        self.welcomes = welcomes  # coordinator only: [(party, nonce)] joiners


def _round_key(session: str, stream: str, r: int) -> str:
    return f"q.{session}.{stream}.{r}"


def _poison_round_key(runtime, parties, up, down, exc) -> None:
    """Best-effort poison of one promised rendezvous key on every
    listed party — peers parked on it raise the coordinator's error
    within a round trip instead of waiting out their backstop."""
    poison = getattr(runtime.transport, "_send_poison", None)
    if poison is None:
        return
    for p in parties:
        try:
            poison(p, up, down, exc)
        except Exception:  # pragma: no cover - best effort
            logger.exception("failed to poison quorum key for %s", p)


def quorum_aggregate(
    runtime,
    updates: Dict[str, Any],
    weights: Optional[Dict[str, float]],
    *,
    session: str,
    round_index: int,
    quorum: int,
    deadline_s: Optional[float],
    coordinator: str,
    stream: str,
    epoch: int,
    announce_fn: Optional[Callable[[List[str]], tuple]] = None,
    backstop: Optional[float] = None,
    timings: Optional[Dict[str, float]] = None,
    quant: Optional[Any] = None,
    quant_ref: Optional[Any] = None,
    quant_scope: Optional[str] = None,
    secagg: Optional[Any] = None,
    server_step: Optional[Any] = None,
) -> QuorumRoundOutcome:
    """One k-of-n streaming round over the coordinator topology.

    ``server_step`` (:mod:`rayfed_tpu.fl.server_opt`): applied by the
    coordinator to the exact finalized aggregate — AFTER the
    deadline-gated cutoff's subset refold, so the step's pseudo-
    gradient is the arrived members' reweighted mean (the subset Σw is
    the effective divisor) — and BEFORE the result broadcast /
    quantized downlink, which therefore carry the POST-step model.
    Mutually exclusive with ``secagg`` (loud).

    ``updates``: ``{party: FedObject}`` for the round's active roster
    (sorted-party order defines the fold order).  Every active
    controller calls this at the same program point; the coordinator
    decides the member set (quorum cutoff) and broadcasts
    ``{"d": aggregate, "m": members, "a": roster announcement}`` — the
    one value every controller agrees on.

    ``announce_fn(members) -> (announce | None, welcomes)`` runs on the
    coordinator after the cutoff: it drains join/leave requests, folds
    in monitor-declared deaths, and advances the roster — the driver
    supplies it so this function stays transport-pure.

    ``quant``: the round's shared
    :class:`~rayfed_tpu.fl.quantize.QuantGrid` — the quorum round runs
    **in the compressed domain**: contributions are quantized onto the
    grid before the push (frames carry the grid descriptor), the
    coordinator folds integer codes into a donated i32 accumulator,
    and the deadline-gated cutoff's refold over the arrived member
    subset stays bit-identical to
    :func:`~rayfed_tpu.fl.fedavg.packed_quantized_sum` over that
    subset (integer adds are exact whatever the refold order).  The
    broadcast re-quantizes the aggregate on a FRESH payload-carried
    grid (:func:`~rayfed_tpu.fl.quantize.quantize_downlink`, shared
    with ``streaming_aggregate`` — quantized-quorum and quantized-
    streaming rounds are byte-identical by construction).
    ``quant_scope`` keys the error-feedback residual as in
    ``streaming_aggregate``; it commits only when this round's
    broadcast lands, so a failover re-push re-quantizes the SAME
    update with the SAME residual.

    ``secagg``: the transport's key-agreement plane
    (:class:`~rayfed_tpu.transport.secagg.KeyAgreement`) — the round
    runs **masked** (:mod:`rayfed_tpu.fl.secagg`): contributions ship
    as ``w·q + pairwise masks`` in i32, the coordinator folds at unit
    weight (masks cancel bit-exactly; it learns only the sum), and the
    deadline-gated cutoff triggers dropout mask recovery — the
    coordinator's post-cutoff announcement (``<round>.sa.c``) names the
    pinned members, each survivor replies with its pairwise seeds
    toward the dropped parties (``<round>.sa.r.<party>``; scoped to
    THIS round's seeds — the per-round HKDF keeps other rounds dark),
    and the orphaned masks are subtracted before the finalize rescale.
    A coordinator crash anywhere in that window reaches the driver's
    failover arm like any other coordinator death: the successor
    re-establishes the round under its own stream scope, which re-keys
    every mask.  Requires ``quant``.
    """
    from rayfed_tpu.proxy import recv_on_runtime

    me = runtime.party
    parties = sorted(updates)
    down = _round_key(session, stream, round_index)
    backstop = (
        backstop if backstop is not None
        else runtime.job_config.recv_backstop_s
    )
    t0 = time.perf_counter()

    # ONE shared sender-side codec discipline (fl.quantize.RoundCodec:
    # grid-fingerprint check + EF two-phase commit, identical across
    # streaming/ring/quorum); no-op when quant is None.  With secagg,
    # the masked codec rides the same discipline plus the fused
    # weight-and-mask step (fl.secagg).
    from rayfed_tpu.fl import quantize as qz
    from rayfed_tpu.fl.quantize import RoundCodec

    masker = None
    if secagg is not None:
        if server_step is not None:
            raise QuorumRoundError(
                "server_step does not compose with masked (secure_agg) "
                "rounds yet — loud exclusion, see fl.server_opt"
            )
        if quant is None:
            raise QuorumRoundError(
                "secure aggregation requires the quantized domain "
                "(quant=) — masks live on the shared integer grid"
            )
        from rayfed_tpu.fl import secagg as sa
        from rayfed_tpu.fl.fedavg import quant_weights

        iw, _ = quant_weights(
            None if weights is None
            else [float(weights[p]) for p in parties],
            len(parties),
        )
        masker = sa.RoundMasker(
            secagg, me, [p for p in parties if p != me],
            session=session, stream=stream, round_index=round_index,
            weight=iw[parties.index(me)],
            # Double-masking: quorum rounds can EXCLUDE a live
            # straggler, and recovering its pairwise masks would
            # otherwise unmask its late-arriving payload — the private
            # self-mask (revealed only by members) keeps it noise.
            self_mask=True,
        )
        codec = sa.MaskedRoundCodec(quant, quant_ref, quant_scope, masker)
    else:
        codec = RoundCodec(quant, quant_ref, quant_scope)
    qref = codec.ref
    q_descriptor = codec.descriptor
    _to_wire = codec.to_wire
    _quant_commit = codec.commit
    _quant_rollback = codec.rollback

    # Quorum control-plane sends go DIRECTLY through the transport, not
    # proxy.send_on_runtime: that helper registers every ref with the
    # cleanup send-watchdog, and with exit_on_failure_cross_silo_sending
    # a PROTOCOL-TOLERATED failure (an epoch-rejected late push, a
    # broadcast to a just-crashed party) would SIGTERM a perfectly
    # healthy process.  Partial failure is this path's normal weather.
    if me != coordinator:
        obj = updates[me]
        local_ref = obj.get_local_ref()
        if quant is not None:
            local_ref = local_ref.then(_to_wire)
        runtime.send_proxy.send(
            coordinator, local_ref, f"{down}.up.{me}",
            down,
            # Masked codes are fresh uniform noise every round — a
            # delta stream would hash every chunk and pin a model-
            # sized base for zero hits; plain sends skip all of that.
            stream=None if masker is not None else f"{stream}/up/{me}",
            round_tag=round_index,
            epoch_tag=epoch, quant_meta=q_descriptor,
        )
        # The push result is deliberately not awaited as a success
        # gate: a late push may be epoch-rejected (the membership
        # advanced) — that is the protocol working, not a failure; the
        # local progress folds into the next round via dga_correct.
        try:
            if masker is not None:
                # Masked round: the coordinator's post-cutoff
                # announcement arrives BEFORE the result broadcast,
                # naming the pinned member set and any dropped parties.
                # Survivors reply with their pairwise seeds toward the
                # dropped so the coordinator can subtract the orphaned
                # masks pre-finalize; excluded-but-alive stragglers
                # (not in "m") just fall through to the broadcast.
                # Inside this try on purpose: a coordinator crash in
                # the recovery window must reach the driver's failover
                # arm as a QuorumRoundError like any other
                # coordinator death.
                ctl = sa.check_recovery_message(
                    recv_on_runtime(
                        runtime, coordinator, f"{down}.sa.c", down
                    ).resolve(timeout=backstop),
                    "request",
                )
                dropped = list(ctl["dr"])
                if me in ctl["m"]:
                    # EVERY member replies: its self-mask seed (its
                    # contribution is in the sum, so its PRG(b) must be
                    # subtracted) plus, on a dropout, its pairwise
                    # seeds toward the dropped.  An EXCLUDED party
                    # falls through silently — its b stays private,
                    # which is exactly what keeps its late payload
                    # uniform noise despite the pairwise recovery.
                    runtime.send_proxy.send(
                        coordinator,
                        sa.make_recovery_reply(
                            me, masker.recovery_seeds(dropped),
                            masker.self_seed_hex(),
                        ),
                        f"{down}.sa.r.{me}", down,
                        round_tag=round_index, epoch_tag=epoch,
                    )
            value = recv_on_runtime(
                runtime, coordinator, f"{down}.down", down
            ).resolve(timeout=backstop)
        except BaseException as exc:
            _quant_rollback()
            raise QuorumRoundError(
                f"round {round_index}: result broadcast from coordinator "
                f"{coordinator!r} failed: {exc!r}"
            ) from exc
        _quant_commit()
        result_val = value["d"]
        if quant is not None and isinstance(
            result_val, qz.QuantizedPackedTree
        ):
            # Quantized downlink: decode with the grid the payload
            # itself carries — bit-identical to the coordinator's own
            # return value (same codes, same rescale, same shared ref).
            import numpy as _np

            result_val = result_val.dequantize(
                _np.float32,
                ref=qref if result_val.gmeta.mode == "delta" else None,
            )
        if timings is not None:
            timings["agg_s"] = time.perf_counter() - t0
        return QuorumRoundOutcome(
            result_val, list(value["m"]), value.get("a"), []
        )

    # -- coordinator ---------------------------------------------------------
    from rayfed_tpu.fl.streaming import StreamingAggregator

    idx = {p: i for i, p in enumerate(parties)}
    w_list = (
        None if weights is None else [float(weights[p]) for p in parties]
    )
    others = [p for p in parties if p != me]
    agg_kwargs = {}
    if quant is not None:
        # The fold grid IS the quantization grid.
        agg_kwargs["chunk_elems"] = quant.chunk_elems
        agg_kwargs["quant_ref"] = qref
    elif server_step is not None:
        # The server step consumes the exact f32 aggregate (re-casting
        # the mean to the wire dtype first would be exactly the loss no
        # residual compensates); quantized rounds finalize in f32
        # already.
        import numpy as _np

        agg_kwargs["out_dtype"] = _np.float32
    if masker is not None:
        def _mask_recovery(member_labels):
            # Runs on the aggregator worker between the cutoff (member
            # set pinned) and the finalize rescale.  The chaos hook
            # sits FIRST: a harness can kill the coordinator in the
            # recovery window — survivors parked on the announcement
            # can only be saved by the health monitor + failover.
            chaos.fire(
                "secagg_recovery", party=me, round=round_index,
                epoch=epoch,
            )
            dropped = sorted(set(parties) - set(member_labels))
            # Announce to EVERY active peer (excluded stragglers too —
            # they are parked on this key and fall through to the
            # broadcast); a dead party's send just fails best-effort.
            runtime.send_proxy.send_many(
                others,
                sa.make_recovery_request(member_labels, dropped),
                f"{down}.sa.c", down,
                round_tag=round_index, epoch_tag=epoch,
            )
            from rayfed_tpu.fl.secagg import SECAGG_STATS

            if dropped:
                SECAGG_STATS["mask_recoveries"] += 1
                logger.warning(
                    "round %d: recovering masks of dropped parties %s "
                    "from %d survivors", round_index, dropped,
                    len(member_labels),
                )
            survivor_seeds = {}
            self_seeds = {}
            if me in member_labels:
                survivor_seeds[me] = masker.recovery_seeds(dropped)
                self_seeds[me] = masker.self_seed_hex()
            # Park every member's reply recv FIRST, resolve after: the
            # replies are independent, so the cutoff round trip costs
            # one RTT, not len(members) sequential ones.
            reply_refs = {
                p: recv_on_runtime(runtime, p, f"{down}.sa.r.{p}", down)
                for p in member_labels if p != me
            }
            for p, ref in reply_refs.items():
                reply = sa.check_recovery_message(
                    ref.resolve(timeout=backstop), "reply",
                )
                if str(reply["p"]) != p:
                    # The reply's self-declared sender decides mask
                    # SIGNS (sorted-name order) — a mis-stamped reply
                    # would silently corrupt the correction.
                    raise sa.SecAggError(
                        f"recovery reply on {p!r}'s rendezvous claims "
                        f"to be from {reply['p']!r} — refusing to "
                        f"finalize the round"
                    )
                survivor_seeds[p] = dict(reply["sd"])
                self_seeds[p] = str(reply["b"])
            return sa.mask_correction(
                survivor_seeds, dropped, quant.total_elems,
                secagg.prg_scheme, members=member_labels,
                self_seeds=self_seeds,
            )

        agg_kwargs["masked"] = True
        agg_kwargs["mask_recovery"] = _mask_recovery
    agg = StreamingAggregator(
        len(parties),
        weights=w_list,
        allowed=runtime.cluster_config.serializing_allowed_list,
        quorum=min(int(quorum), len(parties)),
        labels=parties,
        party=me,
        quant=quant,
        **agg_kwargs,
    )
    sink_entries = []
    cancel_keys = []
    for p in parties:
        if p == me:
            local_ref = updates[p].get_local_ref()

            def _feed(ref, i=idx[p]):
                exc = ref.exception()
                if exc is not None:
                    # The coordinator's own training failed — survivable
                    # under quorum, like any other party's failure.
                    agg._on_error(i, exc)
                else:
                    try:
                        agg.add_local(i, _to_wire(ref.resolve()))
                    # fedlint: disable=FED004 — transferred, not swallowed: a quantize failure of the coordinator's OWN update is survivable under quorum exactly like its training failing
                    except BaseException as e:
                        agg._on_error(i, e)

            local_ref.add_done_callback(_feed)
        else:
            sink_entries.append(
                (p, f"{down}.up.{p}", down, agg.sink(idx[p]))
            )
            cancel_keys.append((p, f"{down}.up.{p}", down))
    if sink_entries:
        runtime.transport.recv_stream_many(sink_entries)
    try:
        result = agg.result(timeout=backstop, deadline_s=deadline_s)
        members = [parties[i] for i in agg.quorum_members]
        if server_step is not None:
            # Post-cutoff, pre-broadcast: the step's pseudo-gradient is
            # the arrived subset's reweighted mean, and the broadcast /
            # quantized downlink below carry the POST-step model.
            # Inside the poison-protected block: a step failure must
            # reach the parked peers like any coordinator-side failure.
            result = server_step(result)
        # Excluded stragglers' sinks must not linger: an armed sink
        # keeps the health monitor probing its source forever, and a
        # very late payload would park unread.  Cancelled sinks drop
        # late frames into the mailbox where the TTL GC bounds them.
        member_set = set(members)
        for p, up, dwn in cancel_keys:
            if p not in member_set:
                runtime.transport.cancel_stream(up, dwn)
        # Inside the poison-protected block deliberately: announce_fn
        # can raise (a coordinator fed.leave, a roster conflict), and
        # the peers are ALREADY parked on the broadcast — they must
        # hear about any coordinator-side failure promptly, whatever
        # stage it happened at.
        announce, welcomes = (None, [])
        if announce_fn is not None:
            announce, welcomes = announce_fn(members)
    except BaseException as exc:
        _quant_rollback()
        if isinstance(exc, chaos.ChaosPartyCrash):
            # An injected crash must look like a REAL one: no poison,
            # no graceful QuorumRoundError wrap — the survivors' health
            # monitors + failover are what the harness is exercising.
            # (The secagg_recovery hook fires on the aggregator worker,
            # so the crash surfaces here rather than at a driver-level
            # chaos.fire call.)
            raise
        # Peers are parked on the broadcast — poison it so they learn
        # the round died now, not at their backstop.  Masked peers may
        # still be parked one step earlier, on the recovery
        # announcement — poison that key too.
        _poison_round_key(runtime, others, f"{down}.down", down, exc)
        if masker is not None:
            _poison_round_key(runtime, others, f"{down}.sa.c", down, exc)
        for _p, up, dwn in cancel_keys:
            runtime.transport.cancel_stream(up, dwn)
        raise QuorumRoundError(
            f"round {round_index}: quorum aggregation failed: {exc!r}"
        ) from exc
    _quant_commit()
    # The round is decided but nobody has heard: the chaos "announce"
    # hook sits exactly here so a harness can kill the coordinator in
    # the nastiest window (peers parked on the broadcast with no poison
    # coming — only the health monitor + failover can save the round).
    # Deliberately OUTSIDE the poison-protected block: an injected
    # crash must look like a real one, not a graceful goodbye.
    chaos.fire("announce", party=me, round=round_index, epoch=epoch)
    wire_result = result
    down_descriptor = None
    if quant is not None:
        # Quantize the result broadcast too — the downlink is the
        # other half of the round's bytes.  Shared producer with
        # streaming_aggregate (qz.quantize_downlink), so quantized-
        # quorum and quantized-streaming rounds stay byte-identical.
        wire_result, result, down_descriptor = qz.quantize_downlink(
            result, quant, qref, quant_scope
        )
    payload = {"d": wire_result, "m": members, "a": announce}
    refs = runtime.send_proxy.send_many(
        others, payload, f"{down}.down", down,
        stream=f"{stream}/down", round_tag=round_index, epoch_tag=epoch,
        quant_meta=down_descriptor,
    )
    delivered = 0
    for p, ref in refs.items():
        if ref.resolve(timeout=backstop):
            delivered += 1
        else:
            # Dead or just-crashed party: its recv will fail via the
            # health monitor, and a rejoin resyncs from a welcome — the
            # surviving members' round must not abort for it.
            logger.warning(
                "round %d: result broadcast to %s failed (dead or "
                "departed party?)", round_index, p,
            )
    if timings is not None:
        timings["agg_s"] = time.perf_counter() - t0
    return QuorumRoundOutcome(result, members, announce, welcomes)


def _coordinator_announce_fn(
    runtime, trainers: Dict[str, Any], active: List[str],
    coordinator: str, leaving: bool = False,
):
    """Build the coordinator's per-round roster-transition hook.

    Returns ``announce_fn(members)`` for :func:`quorum_aggregate`: it
    drains join/leave requests from the membership inbox, drops parties
    that are both monitor-declared dead AND missed the round, and
    advances the roster epoch when the set changed.  Join requests
    always produce a welcome (a restarted party still on the roster
    needs one to resync even though the member set is unchanged).

    ``leaving``: the coordinator itself requested ``fed.leave()`` — it
    completes this round, removes itself from the roster, and the
    announcement carries a **handover** naming the successor (the next
    alive party on the sorted roster ring), so the peers rotate the
    coordinator lease at the same boundary they apply the roster.  The
    loud failure fires only when no successor is alive.
    """
    from rayfed_tpu.transport.manager import roster_successor

    transport = runtime.transport
    roster = transport.roster

    def announce_fn(members: List[str]):
        joins: Dict[str, str] = {}
        leaves = set()
        for req in transport.drain_membership_requests():
            op, p = req.get("op"), req.get("party")
            if op == "join" and p in trainers:
                joins[p] = str(req.get("nonce", ""))
            elif op == "leave" and p:
                leaves.add(p)
            else:
                logger.warning(
                    "ignoring malformed membership request: %r", req
                )
        if leaving:
            leaves.add(coordinator)
        dead = set(transport.get_stats().get("dead_parties", ()))
        # Drop only parties that BOTH missed the round and are declared
        # dead — a straggler that merely missed the cutoff stays a
        # member (its progress folds into the next round).
        dropped = (set(active) - set(members)) & dead
        established = set(active) - dropped - leaves
        new_members = established | set(joins)
        handover = None
        if coordinator not in new_members:
            # Graceful departure: the round in flight completes HERE,
            # and the announcement names who anchors the next one.
            # Successor candidates are the ESTABLISHED members only —
            # a same-round joiner is not in the round loop yet and its
            # welcome delivery is best-effort, so handing it the lease
            # could anchor every peer at a party that never shows up.
            handover = roster_successor(established, coordinator, dead)
            if handover is None:
                raise QuorumRoundError(
                    f"coordinator {coordinator!r} is leaving the roster "
                    f"but no live established successor remains "
                    f"(members {sorted(new_members)}, dead "
                    f"{sorted(dead)}) — the run cannot continue"
                )
        announce = None
        if new_members != set(active):
            epoch = roster.advance(sorted(new_members))
            announce = {"epoch": epoch, "members": sorted(new_members)}
            if handover is not None:
                announce["handover"] = handover
        return announce, [(p, n) for p, n in sorted(joins.items())]

    return announce_fn


def run_quorum_rounds(
    trainers: Dict[str, Any],
    params: Any,
    rounds: int,
    *,
    quorum: int,
    round_deadline_s: Optional[float],
    weights: Optional[Sequence[float]] = None,
    coordinator: Optional[str] = None,
    wire_dtype: Any = None,
    mode: str = "coordinator",
    ring_chunk_elems: Optional[int] = None,
    on_round: Optional[Callable[[int, Any], None]] = None,
    timings: Optional[list] = None,
    stream: str = "fedavg",
    join_ticket: Optional[Dict[str, Any]] = None,
    round_log: Optional[list] = None,
    checkpointer: Any = None,
    checkpoint_every: int = 0,
    wire_quant: Optional[str] = None,
    secure_agg: bool = False,
    region_size: Optional[int] = None,
    region_branch: Optional[int] = None,
    region_quorum: Optional[int] = None,
    region_deadline_s: Optional[float] = None,
    server_opt: Optional[Any] = None,
) -> Any:
    """The quorum-mode round loop behind ``run_fedavg_rounds(quorum=k)``.

    Differences from the classic loop:

    - aggregation is always the quorum-aware streaming round
      (:func:`quorum_aggregate`); ``mode="ring"`` tries the ring first
      and falls back to it when the ring aborts; ``mode="hierarchy"``
      (requires ``wire_quant`` + ``region_size``) tries the region
      topology (:mod:`rayfed_tpu.fl.hierarchy`) first — two-level by
      default, recursively multi-level via ``region_branch=``, with
      per-region quorum cutoffs via ``region_quorum=`` /
      ``region_deadline_s=`` (a straggling region's arrived subset is
      folded at the deadline and the root reweights to the arrived
      Σw, so the flatten fallback below is reserved for structural
      failures) — a
      hierarchy abort (e.g. a dead region coordinator) re-aggregates
      the SAME round over the flat quorum path, where the cutoff
      excludes the corpse, the announcement drops it, and a dead
      QUORUM coordinator reaches this driver's ``roster_successor``
      failover arm like always;
    - each party's next-round input is the broadcast aggregate — except
      a straggler's, which is ``dga_correct(agg, update, input)`` so its
      missed progress folds into the next round;
    - the active set is the live roster (epoch-advanced at round
      boundaries by coordinator announcements); a party that finds
      itself off the roster returns its last broadcast (graceful
      ``fed.leave``) — a dropped-as-dead party that is in fact alive
      must ``fed.join()`` to re-enter;
    - the coordinator is a rotating lease: a controller whose health
      monitor declares the coordinator dead mid-round fails over to the
      deterministic successor and **re-establishes the same round**
      there (see the module docstring); a coordinator ``fed.leave()``
      completes its round and hands the lease over via the announcement;
    - ``weights`` align with ``sorted(trainers)`` and are subset per
      round to the active members;
    - ``join_ticket``: the welcome returned by ``fed.join()`` — the
      (re)joining controller starts at the welcome's round from the
      welcome's params, with the welcome's roster epoch already applied
      and the welcome's ``coordinator`` anchoring its rounds.
    - ``round_log``: optional list receiving one ``{"round", "epoch",
      "active", "members", "coordinator"}`` dict per round — the audit
      trail of who was on the roster, who made each round's quorum, and
      who coordinated it (tests and the chaos bench replay the exact
      FedAvg recurrence from it).
    - ``wire_quant`` (``"uint8"``/``"int8"``): quorum rounds run **in
      the compressed domain** — every controller derives the identical
      shared grid from the previous round's observed aggregate delta
      (the first round bootstraps unquantized, exactly like the classic
      loop), contributions quantize onto it, the coordinator folds
      integer codes with the deadline-gated cutoff, and BOTH directions
      ride 8-bit codes (the downlink re-quantizes on a fresh payload-
      carried grid shared with ``streaming_aggregate`` — quantized-
      quorum and quantized-streaming rounds are byte-identical).  A
      joiner's welcome carries the current grid reference delta, so
      elastic membership composes.  ``mode="ring"`` composes too: the
      quorum ring quantizes on the shared round grid (the grid
      chunking doubles as the stripe grid), and a ring abort falls
      back to the flat quantized quorum round with the same
      uncommitted error-feedback residual.
    - ``secure_agg``: mask the quantized contributions with pairwise
      masks derived from the transport's HELLO key agreement
      (:mod:`rayfed_tpu.fl.secagg`) — the coordinator learns only the
      sum; a quorum dropout triggers mask recovery before finalize, and
      a coordinator crash in the recovery window reaches the failover
      arm like any other coordinator death (the successor re-runs
      recovery on its failover stream).  Requires ``wire_quant``; the
      bootstrap round (no grid yet) runs unquantized AND unmasked —
      see ``docs/source/secure_aggregation.rst``.
    - ``checkpointer`` (+ ``checkpoint_every``): snapshot ``(round,
      roster epoch, member log, session, params)`` at round boundaries;
      the next call restores the latest snapshot — round index, roster
      epoch/members, rendezvous session and the member log all come
      back, and the coordinator is **re-derived from the restored
      roster** (so a cluster that fully crashed mid-failover resumes on
      the same successor everywhere).  A pending DGA late fold is NOT
      checkpointed: a restored straggler simply resyncs from the
      restored global model — at most one round of its local work is
      lost, the same bound a crash already implies.
    """
    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu.fl import quantize as qz
    from rayfed_tpu.fl.compression import PackedTree, compress, decompress
    from rayfed_tpu.fl.overlap import dga_correct
    from rayfed_tpu.runtime import get_runtime

    runtime = get_runtime()
    transport = runtime.transport
    roster = getattr(transport, "roster", None)
    if roster is None:
        raise QuorumRoundError(
            "this transport has no roster (quorum rounds need the "
            "single-process TransportManager or a multi-host leader)"
        )
    if mode == "hierarchy":
        if wire_quant is None:
            raise QuorumRoundError(
                "mode='hierarchy' requires wire_quant — hierarchical "
                "aggregation is compressed-domain only (fl.hierarchy)"
            )
        if region_size is None or int(region_size) < 1:
            raise QuorumRoundError(
                "mode='hierarchy' requires region_size= (the "
                "deterministic partition width)"
            )
        if secure_agg:
            raise QuorumRoundError(
                "mode='hierarchy' and secure_agg are mutually "
                "exclusive — pairwise masks only cancel over the full "
                "party set (fl.hierarchy)"
            )
        if region_branch is not None and int(region_branch) < 2:
            raise QuorumRoundError(
                f"region_branch must be >= 2, got {region_branch!r}"
            )
        if region_quorum is not None and int(region_quorum) < 1:
            raise QuorumRoundError(
                f"region_quorum must be >= 1, got {region_quorum!r}"
            )
        if region_deadline_s is not None and region_quorum is None:
            raise QuorumRoundError(
                "region_deadline_s needs region_quorum= (the "
                "per-region minimum arrived count the deadline gates)"
            )
    elif (region_branch is not None or region_quorum is not None
          or region_deadline_s is not None):
        raise QuorumRoundError(
            "region_branch/region_quorum/region_deadline_s only apply "
            "to mode='hierarchy'"
        )
    sopt = None
    sopt_descr = None
    if server_opt is not None:
        from rayfed_tpu.fl.server_opt import (
            PackedServerOpt,
            PackedServerOptimizer,
        )

        if not isinstance(server_opt, PackedServerOpt):
            raise QuorumRoundError(
                "quorum rounds take a fl.server_opt.PackedServerOpt "
                "(the packed-domain server optimizer, e.g. fl.server_opt"
                ".fedac(...)); legacy fedopt.ServerOptimizer optimizers "
                "run per-leaf tree arithmetic and need the exact "
                "fixed-roster classic loop"
            )
        if secure_agg:
            raise QuorumRoundError(
                "server_opt does not compose with secure_agg yet — the "
                "masked recovery window has not been exercised with a "
                "post-finalize step (loud exclusion, fl.server_opt)"
            )
        sopt = PackedServerOptimizer(server_opt)
    from rayfed_tpu.fl.server_opt import describe_server_opt

    sopt_descr = describe_server_opt(server_opt)
    secagg_keys = None
    if secure_agg:
        if wire_quant is None:
            raise QuorumRoundError(
                "secure_agg requires wire_quant — masks live in the "
                "shared-grid integer domain (fl.secagg)"
            )
        secagg_keys = getattr(transport, "secagg_keys", None)
        if secagg_keys is None or not hasattr(
            transport, "ensure_secagg_peer_keys"
        ):
            raise QuorumRoundError(
                "secure_agg needs the transport key-agreement plane "
                "(TransportManager.secagg_keys) — this transport has "
                "none"
            )
    me = runtime.party
    all_parties = sorted(trainers)
    cluster_parties = sorted(runtime.cluster_config.parties)
    if all_parties != cluster_parties:
        # Observer (non-trainer) controllers are supported by the
        # classic aggregation paths but NOT yet by quorum rounds: the
        # roster, the broadcast fan-out and the membership
        # announcements all equate "cluster party" with "training
        # party".  Fail loudly instead of KeyError-ing mid-round.
        raise QuorumRoundError(
            f"quorum rounds require every cluster party to train: "
            f"trainers {all_parties} vs cluster {cluster_parties} — "
            f"observer controllers are not supported with quorum= "
            f"(use the classic aggregation paths there)"
        )
    # The pinned anchor (coord0) vs the live lease (coord): coord0 is
    # what every controller derives from the arguments; coord rotates
    # via failover/handover.  The effective stream name is derived from
    # the pair, so all controllers that agree on the lease agree on
    # every rendezvous key WITHOUT any shared counter (see
    # _effective_stream).
    coord0 = coordinator if coordinator is not None else min(trainers)
    coord = coord0
    w_map = (
        None if weights is None
        else dict(zip(all_parties, [float(w) for w in weights]))
    )
    import jax.numpy as _jnp

    from rayfed_tpu.transport.manager import roster_successor

    wire_dt = _jnp.bfloat16 if wire_dtype is None else wire_dtype
    backstop = runtime.job_config.recv_backstop_s
    # One shared log even when the caller passed none: the checkpoint
    # snapshots embed it (the restored run replays the same recurrence).
    log = round_log if round_log is not None else []

    restored = None
    if checkpointer is not None and join_ticket is None:
        restored = _restore_quorum_snapshot(
            checkpointer, params, roster, log, sopt=sopt,
            sopt_descr=sopt_descr,
        )

    # Compressed-domain state: the previous round's observed aggregate
    # delta (derived from broadcast values only — bit-identical on every
    # controller), the range reference for the next round's grid.  None
    # until one round has been observed: the first round bootstraps
    # unquantized (and, under secure_agg, unmasked).
    quant_prev_delta = None

    if join_ticket is not None:
        start_round = int(join_ticket["round"])
        session = str(join_ticket["session"])
        params = join_ticket["params"]
        # The welcome names the run's CURRENT coordinator — a rejoiner
        # entering after a failover or handover must not anchor at the
        # departed party.
        coord = str(join_ticket.get("coordinator", coord))
        # Quantized runs: the welcome carries the grid reference delta,
        # so the joiner derives the SAME round grid as everyone else
        # instead of desyncing into an unquantized bootstrap.
        if wire_quant is not None:
            quant_prev_delta = join_ticket.get("qd")
        # Server-opt runs: the welcome carries the optimizer spec + a
        # handle to the replicated state (resolved through the object
        # plane), so a joiner resyncs the trajectory instead of being
        # a loud exclusion.  Both sides must agree on the spec — a
        # silent mismatch IS the trajectory reset this guards against.
        _apply_ticket_server_opt(
            runtime.transport, join_ticket, sopt, sopt_descr
        )
    elif restored is not None:
        start_round, session, params = restored
        if start_round >= rounds:
            return params
        # Re-derive the coordinator from the restored roster: a run that
        # checkpointed after a failover/handover has the old coordinator
        # off the roster, and every resuming controller must land on the
        # same successor — the deterministic succession rule gives it.
        _, members_now = roster.snapshot()
        if coord not in members_now:
            coord = roster_successor(members_now, coord)
            if coord is None:
                raise QuorumRoundError(
                    f"restored roster {sorted(members_now)} has no live "
                    f"successor for coordinator {coord0!r}"
                )
            logger.info(
                "[%s] restored roster lacks coordinator %s; re-derived "
                "successor %s", me, coord0, coord,
            )
    else:
        start_round = 0
        # One id per run, drawn identically on every (non-joining)
        # controller — every rendezvous key of the run derives from it,
        # so two runs in one process can never collide in the
        # mailbox's consumed-key dedupe.
        session = str(runtime.next_seq_id())

    current = (
        params if isinstance(params, PackedTree)
        else compress(params, packed=True, wire_dtype=wire_dt)
    )
    late_inputs: Dict[str, Any] = {}
    dga = fed.remote(dga_correct)
    # A fed.leave() stays pending until the announced roster drops us:
    # the request is re-sent each boundary so it survives a coordinator
    # failover in between (the old coordinator's inbox died with it).
    leave_pending = False

    r = start_round
    while r < rounds:
        chaos.fire("round", party=me, round=r)
        epoch, roster_members = roster.snapshot()
        if me not in roster_members:
            # We left (fed.leave announced) or were dropped as dead —
            # exit gracefully with the last agreed model.
            logger.info(
                "[%s] off the roster at epoch %d; leaving the round "
                "loop at round %d", me, epoch, r,
            )
            break
        if roster.consume_leave_request():
            leave_pending = True
        if leave_pending and me != coord:
            # fed.leave(): tell the coordinator; we participate until
            # the announcement drops us (next boundary).  Direct
            # transport send — see quorum_aggregate on why membership
            # control traffic skips the cleanup send-watchdog.
            nonce = uuid.uuid4().hex
            runtime.send_proxy.send(
                coord, {"op": "leave", "party": me, "nonce": nonce},
                f"roster.req.{me}.{nonce}", "roster",
            )
        active = [p for p in all_parties if p in roster_members]
        # A party that left the roster forfeits its pending late fold:
        # a rejoin resyncs from the welcome's global model, and a stale
        # correction from before the drop must never leak into it.
        for p in list(late_inputs):
            if p not in active:
                late_inputs.pop(p)
        if len(active) < quorum:
            raise QuorumRoundError(
                f"round {r}: live roster {active} is smaller than the "
                f"quorum ({quorum}) — the run cannot make progress"
            )
        if secure_agg:
            # Pairwise key agreement rides the HELLO handshake; one
            # ping per missing pair establishes it, and a no-op when
            # every active peer's key is already recorded (so elastic
            # joins compose: the round after a joiner's epoch advance
            # pings it once).
            transport.ensure_secagg_peer_keys(active)
        # Compressed-domain round: the shared grid derives from the
        # previous round's observed aggregate delta, the reference is
        # the round's shared starting model — both bit-identical on
        # every controller (that IS the negotiation; the fingerprint
        # rides every quantized frame).
        round_grid = None
        round_ref = None
        if wire_quant is not None:
            round_ref = np.asarray(current.buf).astype(
                np.float32
            ).reshape(-1)
            if quant_prev_delta is not None:
                round_grid = qz.make_round_grid(
                    quant_prev_delta, wire_dtype=wire_quant,
                    mode="delta", expand=qz.QUANT_DELTA_EXPAND,
                    # The grid chunking IS the topology's stripe/chunk
                    # grid (ring_chunk_elems doubles as the override,
                    # exactly as in the classic loop): the quorum ring
                    # quantizes on this same grid or ring_aggregate's
                    # chunk-match guard would abort (and fall back)
                    # every quantized round, and a default-chunked grid
                    # over a small model would collapse to ~1 block and
                    # degenerate every region ring to a single stripe
                    # owner.
                    chunk_elems=(
                        ring_chunk_elems
                        if mode in ("ring", "hierarchy") else None
                    ),
                )
        # Server optimization (fl.server_opt): the round's shared
        # starting buffer anchors both the step (at the finalizing
        # node) and the post-round state resync (on EVERY controller) —
        # it is the broadcast every party already byte-agrees on.
        step_fn = None
        x_srv = None
        if sopt is not None:
            x_srv = (
                round_ref if round_ref is not None
                else np.asarray(current.buf).astype(
                    np.float32
                ).reshape(-1)
            )
            sopt.ensure(x_srv)
            step_fn = sopt.step_fn(x_srv)
        rec = None
        # Flight recorder: armed, every round emits a driver-side span
        # carrying the SAME round/epoch keys the transport stamps on
        # frames (rayfed_tpu/telemetry.py) — the driver's view and the
        # wire's view join on one timeline.
        trace_round = telemetry.armed()
        if timings is not None or trace_round:
            rec = {"local_s": 0.0, "push_s": 0.0, "agg_s": 0.0,
                   "hidden_s": 0.0}
            t_r0 = time.perf_counter()
            t_r0_wall = time.time()
        inputs = {p: late_inputs.pop(p, current) for p in active}
        updates = {
            p: trainers[p].train.remote(inputs[p]) for p in active
        }
        if rec is not None and me in updates:
            my_ref = updates[me].get_local_ref()
            if my_ref is not None:
                my_ref.add_done_callback(
                    lambda _ref, rec=rec, t0=t_r0: rec.__setitem__(
                        "local_s", time.perf_counter() - t0
                    )
                )
        # --- the aggregation attempt loop: deterministic coordinator
        # failover.  The happy path runs once.  When the attempt dies
        # BECAUSE the coordinator is (locally) declared dead, every
        # survivor derives the same successor from the sorted roster
        # ring and re-establishes the SAME round there: fresh rendezvous
        # keys (the successor-scoped stream), re-pushed retained
        # contributions, the same deadline-gated cutoff — bit-identical
        # to packed_weighted_sum over whoever arrives.
        failed_over: set = set()
        while True:
            announce_fn = (
                _coordinator_announce_fn(
                    runtime, trainers, active, coordinator=coord,
                    leaving=leave_pending,
                )
                if me == coord else None
            )
            try:
                outcome = _aggregate_with_mode(
                    runtime, updates, w_map, session=session,
                    round_index=r, quorum=quorum,
                    deadline_s=round_deadline_s, coordinator=coord,
                    stream=_effective_stream(stream, coord, coord0),
                    epoch=epoch, mode=mode,
                    ring_chunk_elems=ring_chunk_elems,
                    region_size=region_size,
                    region_branch=region_branch,
                    region_quorum=region_quorum,
                    region_deadline_s=region_deadline_s,
                    announce_fn=announce_fn, backstop=backstop,
                    active=active, timings=rec,
                    quant=round_grid, quant_ref=round_ref,
                    # EF residual keyed by the CALLER's stream name, not
                    # the failover-scoped one: the residual must carry
                    # across attempts and coordinators.
                    quant_scope=stream if round_grid is not None else None,
                    secagg=(
                        secagg_keys if round_grid is not None else None
                    ),
                    server_step=step_fn,
                )
                break
            except QuorumRoundError as exc:
                dead = set(
                    runtime.transport.get_stats().get("dead_parties", ())
                )
                if me == coord or coord not in dead:
                    # Not a coordinator death (a quorum shortfall, a
                    # poisoned round, our own coordination failing):
                    # nothing a new lease could fix — fail loudly.
                    raise
                failed_over.add(coord)
                successor = roster_successor(
                    active, coord, dead | failed_over
                )
                if successor is None:
                    raise QuorumRoundError(
                        f"round {r}: coordinator {coord!r} died and no "
                        f"live successor remains on the roster "
                        f"{active} (dead: {sorted(dead)})"
                    ) from exc
                QUORUM_STATS["coordinator_failovers"] += 1
                telemetry.event(
                    "quorum.failover", round=r, epoch=epoch,
                    party=me, peer=successor, outcome="failover",
                    detail={
                        "from": coord, "to": successor,
                        "dead": sorted(dead), "error": repr(exc),
                    },
                )
                logger.warning(
                    "[%s] round %d: coordinator %s declared dead (%s); "
                    "failing over to successor %s and re-establishing "
                    "the round", me, r, coord, exc, successor,
                )
                coord = successor
        avg, members = outcome.result, outcome.members
        # Stragglers fold their missed round-r progress into round r+1
        # (DGA recurrence) instead of dropping it — each correction is a
        # party-local fed task, no extra wire traffic.  Under server_opt
        # the broadcast is the POST-step model, so the straggler's
        # preserved delta rides into its NEXT contribution and reaches
        # the optimizer one round late as part of that round's
        # pseudo-gradient, scaled by the step like any fresh signal.
        # This is the deliberate, bounded (one straggler-round of local
        # work, exceptional-path-only) generalization of "late fold,
        # not drop" — documented in server_optimization.rst; contrast
        # overlap=True, which stays excluded because there EVERY party
        # EVERY round would compose stale raw deltas with the stepped
        # broadcast, changing the recurrence systematically.
        for p in active:
            if p not in members:
                late_inputs[p] = dga.party(p).remote(
                    avg, updates[p], inputs[p]
                )
        next_coord = coord
        if outcome.announce is not None:
            if me != coord:
                roster.apply(
                    outcome.announce["epoch"], outcome.announce["members"]
                )
            handover = outcome.announce.get("handover")
            if handover is not None:
                # Graceful coordinator departure: the lease rotates at
                # this boundary to the announced successor — the very
                # announcement that drops the leaver from the roster.
                next_coord = str(handover)
                QUORUM_STATS["graceful_handovers"] += 1
                telemetry.event(
                    "quorum.handover", round=r, epoch=epoch,
                    party=me, peer=next_coord,
                    detail={"from": coord, "to": next_coord},
                )
                logger.info(
                    "[%s] round %d: coordinator %s handed the lease to "
                    "%s", me, r, coord, next_coord,
                )
            # Guarded (not just event()'s internal check): this fires
            # EVERY round, and disarmed cost is one global read — the
            # sorted()/detail construction must not run untraced.
            if telemetry.active() is not None:
                telemetry.event(
                    "quorum.announce", round=r, party=me, peer=coord,
                    epoch=int(outcome.announce["epoch"]),
                    detail={
                        "members": sorted(outcome.announce["members"]),
                        "handover": handover,
                    },
                )
        log.append({
            "round": r, "epoch": epoch, "active": list(active),
            "members": list(members), "coordinator": coord,
        })
        current = avg
        plane = getattr(transport, "objects", None)
        if plane is not None and runtime.job_config.blob_publish_round_models:
            # Every controller publishes the round broadcast into its
            # content cache (pinned in the "model" slot; the previous
            # round's entry becomes an ordinary LRU citizen).  This is
            # what makes every member a named HOLDER in welcome
            # handles, keeps a graceful leaver's cache warm for a
            # zero-payload rejoin, and seeds checkpoint-by-fingerprint.
            # Residency-canonicalized so every controller — device-held
            # coordinator aggregate or decoded member view — derives
            # the IDENTICAL fingerprint from the byte-agreed values.
            from rayfed_tpu.objects import canonical_host

            plane.publish_slot("model", canonical_host(current))
        if sopt is not None:
            # Every controller advances its state replica from the
            # round's byte-agreed broadcast pair — the broadcast IS the
            # post-step model (the coordinator/root stepped before the
            # downlink), so all replicas stay byte-identical and any
            # successor can coordinate the next round with the right
            # state in hand.  A failed attempt never reaches here: the
            # failover re-runs the SAME step from the SAME state.
            sopt.resync(x_srv, np.asarray(avg.buf))
        if wire_quant is not None:
            # Next round's grid range: how far the global model just
            # moved, per block — derived from broadcast values only,
            # so bit-identical on every controller.  Under server_opt
            # the broadcast is the POST-step model, so the next
            # round's uplink grid is ranged by the post-step delta.
            quant_prev_delta = (
                np.asarray(avg.buf).astype(np.float32).reshape(-1)
                - round_ref
            )
        if rec is not None:
            rec["agg_s"] = max(
                0.0, rec.get("agg_s", 0.0) - rec["local_s"]
            )
            # Correlation stamp: the keys the transport rides on every
            # frame, so a timings row joins the wire's view of its
            # round — plus the quorum facts the classic loop lacks.
            rec["round"] = r
            rec["epoch"] = epoch
            rec["coordinator"] = coord
            if timings is not None:
                timings.append(rec)
            if trace_round:
                telemetry.emit(
                    "driver.round", round=r, epoch=epoch, party=me,
                    peer=coord, t_start=t_r0_wall,
                    dur_s=time.perf_counter() - t_r0,
                    detail={
                        k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in rec.items()
                    } | {"members": sorted(members)},
                )
        if on_round is not None:
            on_round(r, decompress(current))
        if me == coord and outcome.welcomes:
            _send_welcomes(
                runtime, outcome.welcomes, roster, current, r + 1,
                session, backstop, coordinator=next_coord,
                quant_delta=quant_prev_delta,
                server_opt_descr=sopt_descr,
                # The post-resync state — what anchors round r+1 on
                # every controller; the joiner loads exactly it.
                server_state=sopt.state if sopt is not None else None,
            )
        coord = next_coord
        if checkpointer is not None and checkpoint_every and (
            (r + 1) % checkpoint_every == 0
        ):
            ep_now, mem_now = roster.snapshot()
            snap = {"params": decompress(current)}
            if sopt is not None:
                # The server-opt state rides the snapshot; its spec
                # stamp below is what makes a cross-config restore
                # refuse loudly instead of silently resetting momentum.
                snap["server_state"] = sopt.state
            checkpointer.save(
                r + 1, snap,
                metadata={
                    "quorum_session": session,
                    "epoch": int(ep_now),
                    "members": list(mem_now),
                    "coordinator": coord,
                    "member_log": [dict(e) for e in log],
                    "server_opt": sopt_descr,
                },
            )
        r += 1
    return decompress(current)


def _effective_stream(stream: str, coord: str, coord0: str) -> str:
    """The round's delta-stream scope under the current coordinator
    lease.  The pinned coordinator keeps the caller's stream name (the
    no-fault path stays byte-for-byte what it was); a successor gets a
    coordinator-scoped name — which makes every failover rendezvous key
    FRESH (the original round's keys were consumed when the monitor
    failed the parked recvs) while staying identical across controllers
    with no negotiation, and keeps the successor's delta caches warm for
    every later round it coordinates."""
    return stream if coord == coord0 else f"{stream}.fo.{coord}"


def _aggregate_with_mode(
    runtime, updates, w_map, *, session, round_index, quorum, deadline_s,
    coordinator, stream, epoch, mode, ring_chunk_elems, announce_fn,
    backstop, active, timings, quant=None, quant_ref=None,
    quant_scope=None, secagg=None, region_size=None,
    region_branch=None, region_quorum=None, region_deadline_s=None,
    server_step=None,
) -> QuorumRoundOutcome:
    """Topology-first aggregation when ``mode`` is ``"ring"`` or
    ``"hierarchy"``: a straggler or dead party aborts the topology
    round on every controller (poison cascade + commit pass), and the
    SAME round re-aggregates over the coordinator topology with the
    quorum cutoff — the straggler is excluded there instead of failing
    the round, and a dead quorum coordinator reaches the driver's
    ``roster_successor`` failover arm."""
    from rayfed_tpu.proxy import recv_on_runtime

    me = runtime.party
    down = _round_key(session, stream, round_index)

    def _announce_after_topology(result) -> QuorumRoundOutcome:
        """Roster transition after a successful ring/hierarchy round:
        neither topology's result broadcast carries announcements, so a
        tiny announce frame rides after every such round (usually
        ``{"a": None}``)."""
        members = list(active)
        announce = None
        welcomes: list = []
        if me == coordinator:
            try:
                if announce_fn is not None:
                    announce, welcomes = announce_fn(members)
            except BaseException as exc:
                # Peers are about to park on the announce key — they
                # must hear the coordinator-side failure (e.g. a
                # no-successor coordinator fed.leave) now, not at
                # backstop.
                _poison_round_key(
                    runtime, [p for p in active if p != me],
                    f"{down}.ann", down, exc,
                )
                raise
            chaos.fire(
                "announce", party=me, round=round_index, epoch=epoch
            )
            refs = runtime.send_proxy.send_many(
                [p for p in active if p != me],
                {"a": announce}, f"{down}.ann", down,
                round_tag=round_index, epoch_tag=epoch,
            )
            for p, ref in refs.items():
                if not ref.resolve(timeout=backstop):
                    logger.warning(
                        "round %d: announce to %s failed",
                        round_index, p,
                    )
        else:
            try:
                ann = recv_on_runtime(
                    runtime, coordinator, f"{down}.ann", down
                ).resolve(timeout=backstop)
            except BaseException as exc:
                # Uniform failure type: a coordinator dying between
                # topology assembly and its announce must reach the
                # driver's failover arm like any other
                # coordinator-death, not as a bare RemoteError.
                raise QuorumRoundError(
                    f"round {round_index}: announce from coordinator "
                    f"{coordinator!r} failed: {exc!r}"
                ) from exc
            announce = ann.get("a")
        return QuorumRoundOutcome(result, members, announce, welcomes)

    if mode == "ring" and len(active) > 1:
        from rayfed_tpu.fl.ring import RING_STATS, RingRoundError, ring_aggregate

        try:
            objs = [updates[p] for p in sorted(updates)]
            result = ring_aggregate(
                objs,
                None if w_map is None
                else [w_map[p] for p in sorted(updates)],
                stream=f"{stream}/ring",
                # The server step consumes the exact f32 assembly (see
                # below); plain rounds keep the wire dtype.
                out_dtype=(
                    "float32" if server_step is not None else None
                ),
                chunk_elems=ring_chunk_elems,
                seq_ids=(f"{down}.rs", f"{down}.ag", f"{down}.c",
                         f"{down}.rl", f"{down}.nm"),
                round_tag=round_index,
                timeout=deadline_s if deadline_s is not None else backstop,
                expect_parties=active,
                timings=timings,
                # Compressed-domain quorum ring (ROADMAP item 1c, the
                # last topology exclusion lifted): the PR 12 quantized
                # stripe machinery — chunk-grid match guard, quantized
                # gather hop, RoundCodec EF discipline — needs no
                # quorum-specific teaching, and the RingRoundError
                # fallback below re-codes the SAME (uncommitted)
                # residual through the flat quorum path's shared codec.
                quant=quant,
                quant_ref=quant_ref,
                quant_scope=quant_scope,
            )
            if server_step is not None:
                # The ring has no downlink — every controller already
                # holds the byte-identical assembled aggregate, so each
                # applies the SAME deterministic f32 step locally and
                # all byte-agree on the post-step model (fl.server_opt).
                result = server_step(result)
            return _announce_after_topology(result)
        except RingRoundError as exc:
            logger.warning(
                "round %d: ring aborted (%s); re-aggregating the same "
                "round over the coordinator topology with quorum %d "
                "cutoff", round_index, exc, quorum,
            )
            RING_STATS["fallback_rounds"] += 1
            stream = f"{stream}.fb"
    if mode == "hierarchy" and len(active) > 1 and quant is not None:
        # Bootstrap rounds (no grid yet) fall straight through to the
        # flat quorum path — hierarchy is compressed-domain only.
        from rayfed_tpu.fl.hierarchy import (
            HIER_STATS,
            HierarchyRoundError,
            hierarchy_aggregate,
        )

        try:
            objs = [updates[p] for p in sorted(updates)]
            result = hierarchy_aggregate(
                objs,
                None if w_map is None
                else [w_map[p] for p in sorted(updates)],
                region_size=int(region_size),
                region_branch=region_branch,
                # Per-region quorum: a slow or partially-dead region
                # contributes its deadline-gated arrived subset instead
                # of aborting the tree — the flat-quorum fallback below
                # becomes the exception, not the straggler path.
                region_quorum=region_quorum,
                region_deadline_s=region_deadline_s,
                stream=f"{stream}/hier",
                quant=quant, quant_ref=quant_ref,
                quant_scope=quant_scope,
                quant_downlink=True,
                seq_ids=tuple(
                    f"{down}.h{i}" for i in range(6)
                ),
                round_tag=round_index,
                epoch=epoch,
                timeout=(
                    deadline_s if deadline_s is not None else backstop
                ),
                timings=timings,
                server_step=server_step,
            )
            return _announce_after_topology(result)
        except HierarchyRoundError as exc:
            # A dead region coordinator (or root) aborts the hierarchy
            # on every controller; the flat quorum re-run excludes the
            # corpse via the deadline-gated cutoff, the announcement
            # drops it from the roster, and a dead QUORUM coordinator
            # reaches the existing roster_successor failover arm.
            logger.warning(
                "round %d: hierarchy aborted (%s); re-aggregating the "
                "same round over the coordinator topology with quorum "
                "%d cutoff", round_index, exc, quorum,
            )
            HIER_STATS["fallback_rounds"] += 1
            stream = f"{stream}.fb"
    return quorum_aggregate(
        runtime, updates, w_map, session=session, round_index=round_index,
        quorum=quorum, deadline_s=deadline_s, coordinator=coordinator,
        stream=stream, epoch=epoch, announce_fn=announce_fn,
        backstop=backstop, timings=timings, quant=quant,
        quant_ref=quant_ref, quant_scope=quant_scope, secagg=secagg,
        server_step=server_step,
    )


def _restore_quorum_snapshot(checkpointer, params, roster, log,
                             sopt=None, sopt_descr=None):
    """Resume a quorum run from its latest snapshot: returns
    ``(start_round, session, params)`` — with the roster epoch/members
    applied, the member log replayed into ``log`` and the server-opt
    state (when the run carries one) loaded into ``sopt`` — or ``None``
    when the checkpointer holds nothing yet.  The caller re-derives the
    coordinator from the restored roster.  The snapshot's ``server_opt``
    stamp must match ``sopt_descr`` (loud refusal either direction —
    fl.server_opt.check_snapshot_server_opt)."""
    latest = checkpointer.latest_round()
    if latest is None:
        return None
    from rayfed_tpu.fl.compression import PackedTree, decompress, pack_tree

    tmpl = decompress(params) if isinstance(params, PackedTree) else params
    # "ckpt_meta", not "meta": checkpoint metadata lives on local disk —
    # it is NOT frame metadata, whose literal keys fedlint FED006 polices.
    ckpt_meta = checkpointer.load_metadata(latest)
    if "quorum_session" not in ckpt_meta:
        raise QuorumRoundError(
            f"checkpoint round {latest} was not written by a "
            f"quorum run (no roster epoch / rendezvous session in its "
            f"metadata) — a classic-loop checkpoint directory cannot "
            f"resume a quorum run"
        )
    if sopt_descr is not None:
        from rayfed_tpu.fl.server_opt import check_snapshot_server_opt

        check_snapshot_server_opt(
            ckpt_meta.get("server_opt"), sopt_descr
        )
    target = {"params": tmpl}
    if sopt is not None:
        import jax.numpy as _jnp

        target["server_state"] = sopt.opt.init(
            pack_tree(tmpl, _jnp.float32).buf
        )
    restored_round, snap = checkpointer.restore(
        round_num=latest, target=target
    )
    if sopt is not None:
        sopt.load_state(snap["server_state"])
    roster.apply(int(ckpt_meta["epoch"]), list(ckpt_meta["members"]))
    del log[:]
    log.extend(dict(e) for e in (ckpt_meta.get("member_log") or []))
    logger.info(
        "resuming quorum run at round %d (roster epoch %s, members %s)",
        restored_round, ckpt_meta["epoch"], ckpt_meta["members"],
    )
    return (int(restored_round), str(ckpt_meta["quorum_session"]),
            snap["params"])


def _normalize_server_opt_descr(descr) -> Dict[str, Any]:
    out: Dict[str, Any] = {"kind": str(descr.get("kind", "none"))}
    if "hyper" in descr:
        out["hyper"] = [float(h) for h in descr["hyper"]]
    return out


def _apply_ticket_server_opt(transport, join_ticket, sopt,
                             sopt_descr) -> None:
    """Validate and apply a welcome's server-opt spec + state handle.

    Every mismatch is LOUD, naming both sides: a joiner entering a
    FedAC run as plain FedAvg (or with different hyperparameters, or
    without the state) would silently reset the optimizer trajectory
    for the whole run the first time it holds the coordinator lease.
    """
    t_descr = join_ticket.get("server_opt")
    mine = _normalize_server_opt_descr(sopt_descr)
    if t_descr is not None:
        theirs = _normalize_server_opt_descr(t_descr)
        if theirs != mine:
            raise QuorumRoundError(
                f"server_opt mismatch between this joiner and the run "
                f"it is entering: the welcome was stamped {theirs}, "
                f"this run_fedavg_rounds call is configured {mine} — "
                f"pass the matching server_opt"
            )
    elif sopt is not None:
        raise QuorumRoundError(
            f"this run is configured with server_opt={mine} but the "
            f"welcome carries no server_opt stamp (a coordinator from "
            f"before welcomes carried optimizer state?) — the joiner "
            f"cannot resync the trajectory; restart the run or drop "
            f"server_opt"
        )
    if sopt is None:
        return
    state_handle = join_ticket.get("server_state")
    if state_handle is None:
        raise QuorumRoundError(
            "the welcome stamps a packed server_opt but carries no "
            "server_state handle — cannot resync the optimizer "
            "trajectory"
        )
    from rayfed_tpu.objects import maybe_resolve_handle

    state = maybe_resolve_handle(transport, state_handle)
    sopt.load_state(state)


def _send_welcomes(runtime, welcomes, roster, current, next_round,
                   session, backstop, coordinator: str,
                   quant_delta=None, server_opt_descr=None,
                   server_state=None) -> None:
    """Coordinator: hand each joiner everything it needs to enter the
    loop at the next round — round index, session, the current roster
    epoch, the CURRENT coordinator (post-handover, so a rejoiner never
    anchors at a departed party), the current global model, and (for
    compressed-domain runs) the grid reference delta the next round's
    shared grid derives from.  Best-effort: a joiner that died again
    simply re-requests later.  Direct transport send — see
    quorum_aggregate on why membership control traffic skips the
    cleanup send-watchdog.

    **Handle-passing (object plane)**: when the transport carries an
    object plane, the welcome names the model by content fingerprint
    (``"model"``: a blob handle whose holders are the coordinator plus
    every current member — each publishes the round broadcast into its
    plane, see the round loop) instead of inlining ``"params"``.  The
    joiner pulls from any live holder; a WARM joiner (its cache still
    holds the current model, e.g. a graceful leave/rejoin inside one
    round) transfers ~zero payload bytes.  ``server_opt_descr`` /
    ``server_state`` (packed server-opt runs): the welcome carries the
    optimizer spec plus a handle to the replicated state, so a joiner
    resyncs the trajectory through the object plane instead of being a
    loud exclusion (ROADMAP item 4 follow-on).
    """
    from rayfed_tpu.objects import canonical_host

    epoch, members = roster.snapshot()
    plane = getattr(runtime.transport, "objects", None)
    shared: Dict[str, Any] = {}
    if plane is not None:
        # Content-addressed dedup: the round loop already published
        # exactly these canonical bytes, so the store keeps ONE copy
        # (this re-derives the fingerprint, which refreshes the entry).
        fp, n = plane.publish(canonical_host(current))
        shared["model"] = plane.handle_for(fp, n, extra_holders=members)
    else:
        shared["params"] = current
    if server_opt_descr is not None:
        shared["server_opt"] = dict(server_opt_descr)
    if server_state is not None:
        if plane is None:
            raise QuorumRoundError(
                "a server_opt run's welcome needs the object plane to "
                "carry the optimizer state; this transport has none"
            )
        sfp, sn = plane.publish(canonical_host(server_state))
        shared["server_state"] = plane.handle_for(sfp, sn)
    for party, nonce in welcomes:
        payload = {
            "round": int(next_round),
            "session": session,
            "epoch": int(epoch),
            "members": list(members),
            "coordinator": coordinator,
            **shared,
        }
        if quant_delta is not None:
            payload["qd"] = quant_delta
        ref = runtime.send_proxy.send(
            party, payload, f"roster.welcome.{party}.{nonce}", "roster",
        )
        if not ref.resolve(timeout=backstop):
            logger.warning(
                "welcome to rejoining party %s failed; it will have to "
                "re-request", party,
            )


def join_cluster(
    coordinator: Optional[str] = None, timeout: Optional[float] = None
) -> Dict[str, Any]:
    """(Re)join an in-progress quorum run — the ``fed.join()`` protocol.

    Sends a join request to the coordinator's membership inbox, then
    parks until the coordinator's next round boundary sends back the
    **welcome**: ``{"round", "session", "epoch", "members",
    "coordinator", "params"}``.  The roster epoch from the welcome is
    applied to this runtime's roster before returning, so epoch-tagged
    frames line up immediately.  Pass the returned ticket to
    ``run_fedavg_rounds(join_ticket=...)`` to enter the loop at the
    right round with the current global model — no other party restarts
    anything; the ticket's ``coordinator`` re-anchors a joiner that
    missed a failover or handover.

    ``coordinator`` must name the run's CURRENT lease holder (requests
    land in a per-party inbox only the acting coordinator drains).
    After a failover, that is the announced successor, not the pinned
    party — a rejoining crashed coordinator learns it from operators or
    retries successors in sorted-ring order.
    """
    from rayfed_tpu.proxy import recv_on_runtime
    from rayfed_tpu.runtime import get_runtime

    runtime = get_runtime()
    me = runtime.party
    coord = (
        coordinator if coordinator is not None
        else min(runtime.cluster_config.parties)
    )
    if coord == me:
        raise ValueError(
            "the coordinator cannot join its own run; pass the "
            "coordinator the run is anchored at"
        )
    nonce = uuid.uuid4().hex
    ref = runtime.send_proxy.send(
        coord, {"op": "join", "party": me, "nonce": nonce},
        f"roster.req.{me}.{nonce}", "roster",
    )
    backstop = (
        timeout if timeout is not None
        else runtime.job_config.recv_backstop_s
    )
    if not ref.resolve(timeout=backstop):
        raise QuorumRoundError(
            f"join request to coordinator {coord!r} could not be "
            f"delivered"
        )
    welcome = recv_on_runtime(
        runtime, coord, f"roster.welcome.{me}.{nonce}", "roster"
    ).resolve(timeout=backstop)
    if "model" in welcome and "params" not in welcome:
        # Handle-passing welcome (object plane): resolve the model by
        # content fingerprint — a warm rejoiner (cache still holds the
        # current model) transfers ~zero payload bytes; a cold one
        # pulls from the coordinator or any named member, with dead-
        # holder failover.  The decoded bytes are IDENTICAL to what an
        # eager-push welcome would have delivered (same wire codec).
        from rayfed_tpu.objects import maybe_resolve_handle

        welcome["params"] = maybe_resolve_handle(
            runtime.transport, welcome["model"], timeout=backstop
        )
    runtime.transport.roster.apply(welcome["epoch"], welcome["members"])
    logger.info(
        "[%s] joined at round %d (roster epoch %d, members %s)",
        me, welcome["round"], welcome["epoch"], welcome["members"],
    )
    return welcome


def request_leave() -> None:
    """Graceful departure — the ``fed.leave()`` half of elastic
    membership.  Sets the roster's leave flag; the quorum round driver
    picks it up at the next round boundary, tells the coordinator, and
    this party exits its round loop once the announced roster drops it
    (so it still participates in the round in flight).  On the
    COORDINATOR this triggers a graceful handover: it completes the
    in-flight round and its announcement names the successor that
    anchors the next one — only when no successor is alive does the
    run fail loudly."""
    from rayfed_tpu.runtime import get_runtime

    get_runtime().transport.roster.request_leave()
