"""Split / vertical federated learning: activations forward, grads back.

BASELINE.md config #5: encoder@alice → head@bob.  Per step:

1. encoder party runs its half, *pushes* activations to the head party
   (owner-initiated, per the framework's push perimeter);
2. head party computes loss + gradient w.r.t. activations, updates its
   head params, pushes the activation gradient back;
3. encoder party closes the backward (recompute-in-jit) and updates.

Both halves keep params on their own devices between steps (actor
state); only [B, D] activations and their gradients cross the silo
boundary each step — this is the "activation push GB/s" path the
benchmark measures.

Two stepping modes:

- :meth:`SplitTrainer.step` — one batch, strictly serialized
  (fwd → push → head → push → bwd).  Latency per step is the full
  round trip; simple semantics.
- :meth:`SplitTrainer.step_pipelined` — GPipe-style microbatching
  *across the silo boundary*: all K encoder forwards are issued
  back-to-back (activation pushes stream while the next microbatch
  computes), head steps run as activations land, activation-gradients
  stream back, and both halves **accumulate** gradients, applying one
  update at the end — numerically the same step as one big batch, but
  the wire and both parties' compute overlap instead of taking turns.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence

import jax


class _GradAccum:
    """Shared accumulate-then-apply state for both split halves.

    Holds the running gradient sum and a pair of jitted helpers; the
    final update applies ``lr * mean(grads)`` once (GPipe semantics —
    identical to one step on the concatenated batch).
    """

    def __init__(self, lr: float):
        self._acc = None
        self._count = 0

        def _add(acc, grads):
            return jax.tree_util.tree_map(jax.numpy.add, acc, grads)

        def _apply(params, acc, count):
            return jax.tree_util.tree_map(
                lambda p, a: p - lr * a / count, params, acc
            )

        self._add = jax.jit(_add, donate_argnums=(0,))
        self._apply_jit = jax.jit(_apply, donate_argnums=(1,))

    def add(self, grads) -> None:
        self._acc = grads if self._acc is None else self._add(self._acc, grads)
        self._count += 1

    def apply(self, params):
        """Returns updated params, or ``None`` when nothing accumulated."""
        if self._acc is None:
            return None
        params = self._apply_jit(params, self._acc, float(self._count))
        self._acc = None
        self._count = 0
        return params


class _SplitHalf:
    """Shared actor plumbing: params + accumulator + apply/get."""

    _params: Any
    _accum: _GradAccum

    def apply_update(self):
        updated = self._accum.apply(self._params)
        if updated is None:
            return False
        self._params = updated
        return True

    def get_params(self):
        return self._params


class _EncoderActor(_SplitHalf):
    """Party-local encoder half: jitted forward + jitted recompute-backward.

    Both halves of the step compile exactly once.  The backward
    rematerializes the forward pass inside jit rather than holding a
    Python VJP closure across steps — an un-jitted ``jax.vjp`` would
    retrace the encoder every step (the round-1 0.01 GB/s bottleneck),
    while recompute-in-jit costs one fused extra forward on the MXU.

    Supports many microbatches in flight: each ``forward`` saves its
    input under a microbatch id; ``backward`` produces that microbatch's
    param grads and accumulates them; ``apply_update`` applies the mean
    accumulated gradient once (GPipe-style accumulate-then-apply).
    """

    def __init__(
        self, params: Any, apply_fn: Callable, lr: float, wire_dtype=None
    ):
        self._params = params
        self._saved: Dict[int, Any] = {}
        self._accum = _GradAccum(lr)

        def _fwd(params, x):
            h = apply_fn(params, x)
            return h.astype(wire_dtype) if wire_dtype is not None else h

        def _grads(params, x, g):
            out, vjp = jax.vjp(lambda p: apply_fn(p, x), params)
            (grads,) = vjp(g.astype(out.dtype))
            return grads

        self._fwd = jax.jit(_fwd)
        self._grads = jax.jit(_grads)

    def forward(self, x, microbatch: int = 0):
        self._saved[microbatch] = x
        return self._fwd(self._params, x)

    def backward(self, g, microbatch: int = 0):
        x = self._saved.pop(microbatch, None)
        if x is None:
            raise RuntimeError(
                f"backward for microbatch {microbatch} before its forward"
            )
        self._accum.add(self._grads(self._params, x, g))
        return True


class _HeadActor(_SplitHalf):
    """Party-local head half: loss + grads for both head and activations."""

    def __init__(
        self,
        params: Any,
        apply_fn: Callable,
        loss_fn: Callable,
        lr: float,
        wire_dtype=None,
    ):
        self._params = params
        self._accum = _GradAccum(lr)

        def _grads(params, h, y):
            # Wire-compressed activations compute in f32; the activation
            # gradient goes back to the wire in the compressed dtype.
            hc = h.astype(jax.numpy.float32) if wire_dtype is not None else h

            def f(params, h):
                return loss_fn(apply_fn(params, h), y)

            loss, (g_params, g_h) = jax.value_and_grad(f, argnums=(0, 1))(params, hc)
            if wire_dtype is not None:
                g_h = g_h.astype(wire_dtype)
            return g_params, g_h, loss

        self._grads = jax.jit(_grads)

    def step(self, h, y):
        """Grads + immediate update (the serialized one-batch path)."""
        g_h, loss = self.step_accum(h, y)
        self.apply_update()
        return g_h, loss

    def step_accum(self, h, y):
        """Like :meth:`step` but accumulates the head grad instead of
        applying it (microbatch pipelining)."""
        g_params, g_h, loss = self._grads(self._params, h, y)
        self._accum.add(g_params)
        return g_h, loss


class SplitTrainer:
    """Wire a split model across two parties over the fed API.

    Call from the shared (multi-controller) program *after* ``fed.init``.
    ``encoder_apply(params, x) -> activations``;
    ``head_apply(params, h) -> logits``; ``loss_fn(logits, y) -> scalar``.

    ``wire_dtype`` (e.g. ``jnp.bfloat16``): cast activations and their
    gradients to this dtype for the cross-silo hop — half the wire bytes
    per step; the head upcasts to f32 for its compute.  Standard split-FL
    activation compression; leave ``None`` for exact f32 exchange.
    """

    def __init__(
        self,
        *,
        encoder_party: str,
        head_party: str,
        encoder_params: Any,
        encoder_apply: Callable,
        head_params: Any,
        head_apply: Callable,
        loss_fn: Callable,
        lr: float = 0.1,
        wire_dtype=None,
    ):
        import rayfed_tpu as fed

        self._fed = fed
        self._encoder = (
            fed.remote(_EncoderActor)
            .party(encoder_party)
            .remote(encoder_params, encoder_apply, lr, wire_dtype)
        )
        self._head = (
            fed.remote(_HeadActor)
            .party(head_party)
            .remote(head_params, head_apply, loss_fn, lr, wire_dtype)
        )

    def step(self, x_obj, y_obj):
        """One split step; ``x_obj`` owned by encoder party, ``y_obj`` by
        head party.  Returns the loss as a FedObject owned by the head
        party (``fed.get`` it on any party)."""
        h = self._encoder.forward.remote(x_obj)
        g_h, loss = self._head.step.options(num_returns=2).remote(h, y_obj)
        self._encoder.backward.remote(g_h)
        self._encoder.apply_update.remote()
        return loss

    def step_pipelined(
        self, x_objs: Sequence[Any], y_objs: Sequence[Any]
    ) -> List[Any]:
        """One *accumulated* split step over K microbatches with
        transfer/compute overlap.

        All K forwards are issued before any backward, so the encoder
        party streams K activation pushes back-to-back while the head
        party consumes them; activation-gradients stream back the same
        way.  Both parties accumulate their param grads and apply a
        single mean update at the end — the same mathematical step as
        one batch of size ``sum(len(x))``, at pipeline throughput.

        Returns the per-microbatch losses (FedObjects owned by the head
        party).
        """
        if len(x_objs) != len(y_objs):
            raise ValueError("need one y per x microbatch")
        hs = [
            self._encoder.forward.remote(x, mb)
            for mb, x in enumerate(x_objs)
        ]
        losses = []
        g_hs = []
        for h, y in zip(hs, y_objs):
            g_h, loss = self._head.step_accum.options(num_returns=2).remote(h, y)
            g_hs.append(g_h)
            losses.append(loss)
        for mb, g_h in enumerate(g_hs):
            self._encoder.backward.remote(g_h, mb)
        self._encoder.apply_update.remote()
        self._head.apply_update.remote()
        return losses

    def encoder_params(self):
        return self._encoder.get_params.remote()

    def head_params(self):
        return self._head.get_params.remote()
