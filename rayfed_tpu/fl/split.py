"""Split / vertical federated learning: activations forward, grads back.

BASELINE.md config #5: encoder@alice → head@bob.  Per step:

1. encoder party runs its half, *pushes* activations to the head party
   (owner-initiated, per the framework's push perimeter);
2. head party computes loss + gradient w.r.t. activations, updates its
   head params, pushes the activation gradient back;
3. encoder party closes its saved VJP and updates encoder params.

Both halves keep params on their own devices between steps (actor
state); only [B, D] activations and their gradients cross the silo
boundary each step — this is the "activation push GB/s" path the
benchmark measures.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax


class _EncoderActor:
    """Party-local encoder half: forward + deferred backward via VJP."""

    def __init__(self, params: Any, apply_fn: Callable, lr: float):
        self._params = params
        self._apply = apply_fn
        self._lr = lr
        self._vjp = None

    def forward(self, x):
        out, vjp = jax.vjp(lambda p: self._apply(p, x), self._params)
        self._vjp = vjp
        return out

    def backward(self, g):
        if self._vjp is None:
            raise RuntimeError("backward called before forward")
        (grads,) = self._vjp(g)
        self._params = jax.tree_util.tree_map(
            lambda p, gr: p - self._lr * gr, self._params, grads
        )
        self._vjp = None
        return True

    def get_params(self):
        return self._params


class _HeadActor:
    """Party-local head half: loss + grads for both head and activations."""

    def __init__(self, params: Any, apply_fn: Callable, loss_fn: Callable, lr: float):
        self._params = params
        self._apply = apply_fn
        self._loss = loss_fn
        self._lr = lr

        def _step(params, h, y):
            def f(params, h):
                return self._loss(self._apply(params, h), y)

            loss, (g_params, g_h) = jax.value_and_grad(f, argnums=(0, 1))(params, h)
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, g_params
            )
            return new_params, g_h, loss

        self._step = jax.jit(_step)

    def step(self, h, y):
        self._params, g_h, loss = self._step(self._params, h, y)
        return g_h, loss

    def get_params(self):
        return self._params


class SplitTrainer:
    """Wire a split model across two parties over the fed API.

    Call from the shared (multi-controller) program *after* ``fed.init``.
    ``encoder_apply(params, x) -> activations``;
    ``head_apply(params, h) -> logits``; ``loss_fn(logits, y) -> scalar``.
    """

    def __init__(
        self,
        *,
        encoder_party: str,
        head_party: str,
        encoder_params: Any,
        encoder_apply: Callable,
        head_params: Any,
        head_apply: Callable,
        loss_fn: Callable,
        lr: float = 0.1,
    ):
        import rayfed_tpu as fed

        self._fed = fed
        self._encoder = (
            fed.remote(_EncoderActor)
            .party(encoder_party)
            .remote(encoder_params, encoder_apply, lr)
        )
        self._head = (
            fed.remote(_HeadActor)
            .party(head_party)
            .remote(head_params, head_apply, loss_fn, lr)
        )

    def step(self, x_obj, y_obj):
        """One split step; ``x_obj`` owned by encoder party, ``y_obj`` by
        head party.  Returns the loss as a FedObject owned by the head
        party (``fed.get`` it on any party)."""
        h = self._encoder.forward.remote(x_obj)
        g_h, loss = self._head.step.options(num_returns=2).remote(h, y_obj)
        self._encoder.backward.remote(g_h)
        return loss

    def encoder_params(self):
        return self._encoder.get_params.remote()

    def head_params(self):
        return self._head.get_params.remote()
