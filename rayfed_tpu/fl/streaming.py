"""Streaming on-device aggregation of PackedTree contributions.

The classic FedAvg receive path serializes: wait for every party's
complete payload → decode N full trees → one monolithic reduce.  At
model scale that parks O(parties × model) bytes on the coordinator and
leaves the devices idle while the wire drains.  Here aggregation is
fused into the receive path (THC-style, arXiv:2302.08545): the transport
surfaces payload bytes **as they land** (``TransportManager.recv_stream``
→ ``TransportServer`` chunk sinks), and a :class:`StreamingAggregator`
casts + accumulates each arriving chunk of the packed wire buffer into a
**donated on-device f32 accumulator** while later chunks are still on
the wire — wire time and decode+reduce time overlap, and the reduce
itself never materializes a list of full trees.  (Delta streams trade
memory for wire on top of this: the transport keeps each peer's last
full payload as the diff base — see the transport docs.)

Determinism contract: floating-point addition is not associative, so the
aggregator applies chunks in **party order per block** — party ``i``'s
block ``b`` is folded in only after parties ``0..i-1`` folded theirs.
Arrival order then only affects scheduling, never the result: the
streamed aggregate is bit-identical to the one-shot fused reduce
(:func:`rayfed_tpu.fl.fedavg.packed_weighted_sum`), which performs the
same zero-init → per-party multiply-add chain → final divide + cast.

Non-float (passthrough) leaves are reduced at finalize time with the
same per-leaf semantics as :func:`~rayfed_tpu.fl.fedavg.tree_average`
(the payloads are retained as zero-copy views, so decoding their
skeletons is cheap) — streamed and one-shot aggregation agree on the
whole tree, not just the packed buffer.

``streaming_aggregate`` is the multi-controller entry point: every party
calls it at the same program point with the same arguments (like
:func:`rayfed_tpu.fl.fedavg.aggregate`); contributions flow to the
coordinator on named delta streams (only changed chunks cross the wire
round-over-round) and the result is broadcast back.
"""

from __future__ import annotations

import functools
import json
import logging
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

# Elements folded per accumulate dispatch.  2M elements = one 4 MB
# bf16 wire chunk — matching the transport's chunk size keeps roughly
# one dispatch per arriving chunk.
DEFAULT_CHUNK_ELEMS = 1 << 21

# A sink only wakes the aggregator worker after this many new bytes
# (or on completion) — per-64KB-read notifies would thrash the lock.
_NOTIFY_BYTES = 512 * 1024


@functools.lru_cache(maxsize=None)
def _accum_kernel(chunk_elems: int, acc_dtype: str, wire_dtype: str):
    """One donated-accumulator multiply-add step: acc[off:off+C] += w*x.

    The donated accumulator means no second O(model) buffer per step;
    offsets are traced (one compile per (chunk size, dtypes), not per
    offset).  The per-element op chain — convert, multiply by the traced
    weight, add — is EXACTLY the chain ``packed_weighted_sum`` compiles,
    which is what makes streamed and one-shot aggregation bit-identical.
    """
    import jax
    import jax.numpy as jnp

    acc_dt = jnp.dtype(acc_dtype)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _apply(acc, chunk, off, w):
        seg = jax.lax.dynamic_slice(acc, (off,), (chunk_elems,))
        return jax.lax.dynamic_update_slice(
            acc, seg + w * chunk.astype(acc_dt), (off,)
        )

    return _apply


# Finalize (divide + cast) is shared with the one-shot path and the
# ring stripe owners: rayfed_tpu.fl.fedavg.finalize_packed_stripe is
# the single producer of the output bytes.


class _Stream:
    """Receive state of one contribution."""

    __slots__ = (
        "payload", "avail_bytes", "complete", "local_tree", "elems_array",
        "data_start", "data_nbytes", "dtype", "applied_blocks",
        "t_complete", "notified_bytes", "manifest", "error",
    )

    def __init__(self) -> None:
        self.payload: Optional[memoryview] = None
        self.avail_bytes = 0
        self.complete = False
        self.local_tree = None  # coordinator's own PackedTree
        self.elems_array: Optional[np.ndarray] = None  # local fast path
        self.data_start = -1  # byte offset of the packed buffer
        self.data_nbytes = -1
        self.dtype: Optional[np.dtype] = None
        self.applied_blocks = 0
        self.t_complete = 0.0
        self.notified_bytes = 0
        self.manifest: Optional[Dict[str, Any]] = None  # parsed payload manifest
        # Quorum mode only: this stream's own failure (dead source,
        # verification failure) — recorded instead of failing the whole
        # aggregation, as long as the quorum stays reachable.
        self.error: Optional[BaseException] = None


class _StreamSink:
    """Transport-facing adapter: thread-safe, throttled notifies."""

    __slots__ = ("_agg", "_index")

    def __init__(self, agg: "StreamingAggregator", index: int) -> None:
        self._agg = agg
        self._index = index

    def on_bytes(self, view: memoryview, total: int) -> None:
        self._agg._on_bytes(self._index, view, total)

    def on_complete(self, payload) -> None:
        self._agg._on_complete(self._index, payload)

    def on_error(self, err: Any) -> None:
        self._agg._on_error(self._index, err)

    def on_frame_abort(self, corrupt: bool = False) -> None:
        self._agg._on_frame_abort(self._index, corrupt)


class StreamingAggregator:
    """Fold N PackedTree contributions into one as their bytes arrive.

    Usage (coordinator side)::

        agg = StreamingAggregator(n_sources=len(parties), weights=w)
        for i, party in enumerate(parties):
            transport.recv_stream(party, up_id, down_id, agg.sink(i))
        agg.add_local(my_index, my_packed_tree)   # no wire hop for self
        averaged = agg.result(timeout=60)         # PackedTree, wire dtype

    The REDUCE itself holds O(model + chunk): one f32 accumulator, no
    list of decoded per-leaf trees.  Wire-payload residency is separate:
    in-flight payload buffers live until their frame completes, and when
    contributions ride delta streams the transport additionally caches
    each peer's last full payload (bounded LRU) as the diff base — that
    is a deliberate memory-for-wire trade, O(streams × wire payload),
    accounted to the transport, not this reducer.
    """

    def __init__(
        self,
        n_sources: int,
        weights: Optional[Sequence[float]] = None,
        allowed: Optional[Dict[str, Any]] = None,
        chunk_elems: int = DEFAULT_CHUNK_ELEMS,
        out_dtype: Any = None,
        quorum: Optional[int] = None,
        labels: Optional[Sequence[str]] = None,
        quant: Optional[Any] = None,
        quant_ref: Optional[Any] = None,
        masked: bool = False,
        mask_recovery: Optional[Any] = None,
        presummed: Optional[str] = None,
        party: Optional[str] = None,
    ) -> None:
        if n_sources < 1:
            raise ValueError("streaming aggregation needs >= 1 source")
        # Acting party for flight-recorder spans (agg.fold/finalize,
        # quorum.cutoff).  In-process multi-party runs share ONE
        # process-global recorder, so an unstamped record would be
        # served by EVERY manager's trace window and the merged
        # timeline would duplicate it under each party's clock offset.
        self._party = None if party is None else str(party)
        if quorum is not None and not 1 <= int(quorum) <= n_sources:
            raise ValueError(
                f"quorum must be in [1, {n_sources}], got {quorum}"
            )
        if labels is not None and len(labels) != n_sources:
            raise ValueError(
                f"{len(labels)} labels for {n_sources} sources"
            )
        if weights is not None:
            from rayfed_tpu.fl.fedavg import _check_weights

            if len(weights) != n_sources:
                raise ValueError(
                    f"{len(weights)} weights for {n_sources} sources"
                )
            self._weights = [float(w) for w in weights]
            self._total_w = _check_weights(self._weights)
        else:
            self._weights = [1.0] * n_sources
            self._total_w = float(n_sources)
        # Original arg (None vs explicit): the passthrough reduce must
        # take the same code path as packed_weighted_sum's.
        self._weights_arg = (
            None if weights is None else list(self._weights)
        )
        self._allowed = allowed
        # Output dtype of the aggregate (None = the wire dtype; f32 in
        # compressed-domain mode — integer codes make no sense as an
        # output).  Keep f32 when the result feeds a server optimizer or
        # error-feedback loop — re-quantizing the mean to an aggressive
        # wire dtype is exactly the loss no residual compensates.
        self._out_dtype = None if out_dtype is None else np.dtype(out_dtype)
        self._chunk_elems = int(chunk_elems)
        # Compressed-domain (shared-grid) mode: arriving integer codes
        # fold into a donated i32 accumulator (widening multiply-add —
        # exact, associative) and the ONE fused rescale happens at
        # finalize (fedavg.finalize_packed_quantized).  ``quant`` is the
        # round's QuantGrid; every contribution's grid fingerprint is
        # checked against it before its bytes are trusted.
        self._quant = quant
        self._int_weights: Optional[List[int]] = None
        # Delta-coded rounds: the shared reference buffer (flat f32;
        # every controller holds it bit-identically) the finalize adds
        # back after the single fused rescale.  A StripeAggregator gets
        # its stripe-compacted slice.
        self._quant_ref = None
        # Subclasses (StripeAggregator) fold a block SUBSET of the grid;
        # the base class folds the full buffer and cross-checks the
        # grid's total element count + per-payload grid descriptors.
        self._quant_full = True
        if quant is not None:
            if quant.mode == "delta":
                if quant_ref is None:
                    raise ValueError(
                        "a mode='delta' grid needs quant_ref= (the "
                        "round's shared reference buffer)"
                    )
                self._quant_ref = np.asarray(quant_ref).reshape(-1)
            elif quant_ref is not None:
                raise ValueError(
                    "quant_ref only applies to mode='delta' grids"
                )
            from rayfed_tpu.fl.fedavg import quant_weights

            if self._chunk_elems != int(quant.chunk_elems):
                raise ValueError(
                    f"fold grid ({self._chunk_elems} elems/block) must "
                    f"match the quantization grid "
                    f"({quant.chunk_elems}) — both ARE the canonical "
                    f"packed_block_grid chunking"
                )
            iw, itotal = quant_weights(weights, n_sources)
            quant.check_weight_headroom(itotal)
            self._int_weights = iw
            # Integer totals are exactly representable in f32 up to the
            # headroom bound, so the float bookkeeping stays exact.
            self._weights = [float(w) for w in iw]
            self._total_w = float(itotal)
        # Secure aggregation (fl.secagg): contributions arrive as
        # MASKED i32 codes — ``w_i·q_i + net pairwise mask`` — and fold
        # at UNIT weight through the unchanged integer kernel (the
        # party already folded its own weight in; weighted pairwise
        # masks could not cancel).  The float weight bookkeeping above
        # stays the TRUE example counts: the quorum cutoff's Σw reweight
        # and the finalize's zero-point term need them, and both see
        # exactly the unmasked round's numbers — which is what keeps
        # masked and unmasked rounds byte-identical.  ``mask_recovery``
        # (quorum rounds): called on the worker with the member labels
        # BEFORE finalize; returns the dropout rounds' orphaned-mask
        # correction (uint32, fl.secagg.mask_correction) or None.
        self._masked = bool(masked)
        self._mask_recovery = mask_recovery
        if self._masked and quant is None:
            raise ValueError(
                "masked aggregation requires quant= (the round's shared "
                "grid) — masks live in the integer domain"
            )
        if mask_recovery is not None and not self._masked:
            raise ValueError("mask_recovery only applies with masked=True")
        # Hierarchical aggregation (fl.hierarchy): sources are REGION
        # PARTIAL SUMS ``Σ_{p∈region} w_p·q_p`` (RegionSumTree) rather
        # than per-party codes — the weights are already folded in, so
        # each source folds at UNIT weight through the unchanged
        # integer kernel (integer adds are exact + associative, which
        # is what makes hierarchical == flat byte-identical).  The
        # ``weights`` passed here are the per-region integer TOTALS,
        # so Σw (the finalize divisor and zero-point term) is the
        # whole roster's weight — exactly the flat fold's.
        # ``presummed`` names the partial-sum wire dtype (int16/int32,
        # fl.hierarchy.partial_sum_dtype — the narrowest integer that
        # holds qabs_max·W exactly).
        self._presummed = None if presummed is None else str(presummed)
        if self._presummed is not None:
            if quant is None:
                raise ValueError(
                    "presummed aggregation requires quant= (the round's "
                    "shared grid) — partial sums live in its integer "
                    "domain"
                )
            if self._masked:
                raise ValueError(
                    "presummed and masked are mutually exclusive (a "
                    "region partial sum is already an unmaskable fold)"
                )
            if np.dtype(self._presummed).kind != "i":
                raise ValueError(
                    f"presummed= names the partial-sum integer wire "
                    f"dtype, got {self._presummed!r}"
                )
        self._n = n_sources
        self._streams = [_Stream() for _ in range(n_sources)]
        # Quorum (k-of-n) mode: the first k completed contributions may
        # be aggregated without the rest once the deadline passes (or
        # the rest provably cannot arrive).  None = classic all-of-n.
        self._quorum = None if quorum is None else int(quorum)
        self._labels = (
            [str(x) for x in labels]
            if labels is not None
            else [f"source {i}" for i in range(n_sources)]
        )
        # Sorted indices of the contributions actually aggregated; None
        # until a cutoff excludes someone (the all-of-n hot path never
        # touches this).
        self._participating: Optional[List[int]] = None
        self._deadline_at: Optional[float] = None  # monotonic cutoff time
        # Set by transport threads that need the fold rolled back (a
        # corrupt mid-fold stream under quorum); consumed by the worker,
        # the only thread allowed to touch the accumulator.
        self._needs_reset = False
        self._cond = threading.Condition()
        self._acc = None
        # True when the integer fold runs as plain numpy slice-adds
        # instead of per-block jit calls (decided in _init_acc).
        self._np_fold = False
        self._total_elems = -1
        self._nblocks = -1
        self._wire_dtype: Optional[np.dtype] = None
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._done = False
        self._worker: Optional[threading.Thread] = None
        # Timing for the overlap metric.
        self._t_first_byte = 0.0
        self._t_all_complete = 0.0
        self._t_done = 0.0
        self._busy_s = 0.0
        self.stats: Dict[str, float] = {}

    # -- source attachment ----------------------------------------------------

    def sink(self, index: int) -> _StreamSink:
        """The chunk sink for source ``index`` (hand to recv_stream)."""
        self._ensure_worker()
        return _StreamSink(self, index)

    def add_local(self, index: int, packed_tree: Any) -> None:
        """Feed the coordinator's own contribution (no wire hop)."""
        from rayfed_tpu.fl.compression import PackedTree
        from rayfed_tpu.fl.quantize import QuantizedPackedTree

        if not isinstance(packed_tree, PackedTree):
            self.fail(
                TypeError(
                    "streaming aggregation consumes PackedTree "
                    f"contributions, got {type(packed_tree).__name__} — "
                    "produce updates with fl.compress(tree, packed=True)"
                )
            )
            return
        if self._quant is not None:
            if not isinstance(packed_tree, QuantizedPackedTree):
                self.fail(
                    TypeError(
                        "compressed-domain aggregation consumes "
                        "QuantizedPackedTree contributions — quantize "
                        "onto the round grid first (fl.quantize)"
                    )
                )
                return
            from rayfed_tpu.fl.secagg import MaskedCodeTree

            if self._masked != isinstance(packed_tree, MaskedCodeTree):
                self.fail(
                    TypeError(
                        "masked fold got an unmasked contribution"
                        if self._masked else
                        "got a MaskedCodeTree but this aggregator is "
                        "not masked — construct it with masked=True "
                        "(fl.secagg) or send plain quantized codes"
                    )
                )
                return
            from rayfed_tpu.fl.hierarchy import RegionSumTree

            if (self._presummed is not None) != isinstance(
                packed_tree, RegionSumTree
            ):
                self.fail(
                    TypeError(
                        "presummed fold got a per-party contribution "
                        "(expected a RegionSumTree partial sum)"
                        if self._presummed is not None else
                        "got a RegionSumTree but this aggregator is "
                        "not presummed — construct it with presummed= "
                        "(fl.hierarchy) or send per-party codes"
                    )
                )
                return
            if packed_tree.gmeta != self._quant.meta():
                self.fail(
                    ValueError(
                        f"local contribution {index} was coded on a "
                        f"different grid (fp={packed_tree.gmeta.fp:#010x}"
                        f" vs {self._quant.fingerprint():#010x})"
                    )
                )
                return
        elif isinstance(packed_tree, QuantizedPackedTree):
            self.fail(
                TypeError(
                    "got a QuantizedPackedTree but no quant= grid — "
                    "construct the aggregator with the round's "
                    "QuantGrid to fold in the compressed domain"
                )
            )
            return
        self._attach_local(index, np.asarray(packed_tree.buf).reshape(-1),
                           tree=packed_tree)

    def _attach_local(self, index: int, arr: np.ndarray, tree=None) -> None:
        """Bind a wire-hop-free contribution (a host element array)."""
        self._ensure_worker()
        now = time.perf_counter()
        with self._cond:
            s = self._streams[index]
            s.local_tree = tree
            s.elems_array = arr
            s.dtype = arr.dtype
            s.data_start = 0
            s.data_nbytes = arr.nbytes
            s.avail_bytes = arr.nbytes
            s.complete = True
            s.t_complete = now
            if not self._t_first_byte:
                self._t_first_byte = now
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = exc
            self._cond.notify_all()

    # -- sink callbacks (transport threads) -----------------------------------

    def _on_bytes(self, index: int, view: memoryview, total: int) -> None:
        s = self._streams[index]
        # Arrival contract (both transport paths): ``view`` is the
        # frame's full payload buffer and ``total`` the CONTIGUOUS
        # bytes available from offset 0.  Single-socket streams grow
        # ``total`` as the socket drains; multi-rail stripe frames
        # (wire v4) feed the growing contiguous VERIFIED-chunk prefix,
        # so ``total`` may jump by several chunks at once and never
        # covers unverified or out-of-order bytes — either way the
        # fold below only ever consumes a true prefix of the payload.
        # ALL state writes happen under the lock — a lockless extent
        # update could race a frame abort's reset and carry a dead
        # frame's byte count onto the retry's fresh buffer.  Only the
        # worker WAKE is throttled (the lock itself is ~100ns; the
        # notify storm is what would thrash).
        with self._cond:
            if s.complete:
                return
            if s.payload is not None and s.payload.obj is not view.obj:
                # A retry frame with a fresh buffer: drop the stale
                # binding (already-applied blocks stay — a retry resends
                # the identical payload, so they remain a valid prefix).
                self._reset_frame(s)
            if s.payload is None:
                s.payload = view
                if not self._t_first_byte:
                    self._t_first_byte = time.perf_counter()
                s.avail_bytes = total
            else:
                s.avail_bytes = max(s.avail_bytes, total)
            if total - s.notified_bytes >= _NOTIFY_BYTES:
                s.notified_bytes = total
                self._cond.notify_all()

    def _on_complete(self, index: int, payload) -> None:
        now = time.perf_counter()
        with self._cond:
            s = self._streams[index]
            if s.error is not None:
                # A stream that failed earlier (corrupt mid-fold, a
                # transient death) just delivered CLEAN bytes — the
                # sender's retry or the party's revival won.  Clear the
                # failure so the stream rejoins the fold pool: leaving
                # it marked would stall the ordered fold chain at this
                # index forever while the cutoff counts it complete.
                # (Any poisoned partial folds were already queued for
                # rollback when the error was recorded.)
                logger.info(
                    "contribution from %s recovered (clean retry after "
                    "%s)", self._labels[index], s.error,
                )
                s.error = None
            # Delta frames (and mailbox replays) deliver a payload
            # object the incremental view never saw — rebind.
            s.payload = memoryview(payload)
            s.avail_bytes = len(s.payload)
            s.complete = True
            s.t_complete = now
            if not self._t_first_byte:
                self._t_first_byte = now
            self._cond.notify_all()

    def _on_error(self, index: int, err: Any) -> None:
        from rayfed_tpu.exceptions import RemoteError

        if isinstance(err, BaseException):
            exc: BaseException = err
        else:
            try:
                exc = RemoteError.from_wire(err)
            except Exception:
                exc = RuntimeError(f"stream {index} failed: {err!r}")
        if self._quorum is None:
            self.fail(exc)
            return
        # Quorum mode: one dead/failed contribution is survivable — mark
        # the stream failed and let the cutoff logic aggregate the rest.
        # Deliberately NO eager "quorum unreachable" verdict here: a
        # stream error can be transient (a corrupt frame whose sender
        # retries cleanly, a blip the monitor un-declares) and
        # _on_complete clears it — the give-up decision belongs to the
        # deadline (see _maybe_cutoff_locked), which is when stragglers
        # have provably had their chance.
        with self._cond:
            s = self._streams[index]
            if s.complete or s.error is not None:
                return
            s.error = exc
            logger.warning(
                "contribution from %s failed (%s); continuing toward "
                "quorum %d/%d", self._labels[index], exc, self._quorum,
                self._n,
            )
            self._cond.notify_all()

    @staticmethod
    def _reset_frame(s: _Stream) -> None:
        """Forget a dead frame's buffer; keep the applied-block prefix
        (a sender retry re-sends the identical payload bytes)."""
        s.payload = None
        s.avail_bytes = 0
        s.notified_bytes = 0
        s.data_start = -1
        s.data_nbytes = -1
        s.dtype = None

    def _on_frame_abort(self, index: int, corrupt: bool) -> None:
        """The in-flight frame died (connection drop) or failed
        verification.  A clean drop just resets the frame state and
        waits for the sender's retry; a CORRUPT frame whose bytes were
        already folded cannot be rolled back out of the donated
        accumulator — fail the aggregation loudly rather than let a
        retry land on top of poisoned partial sums."""
        with self._cond:
            s = self._streams[index]
            if s.complete:
                return
            if corrupt and s.applied_blocks > 0:
                if self._quorum is not None:
                    # Quorum mode can afford the rollback the donated
                    # accumulator can't: zero it, forget every applied
                    # block, mark the stream failed — the worker refolds
                    # the healthy contributions from their retained
                    # payloads (a reset also happens at any cutoff, so
                    # this adds no new machinery).
                    s.error = RuntimeError(
                        f"contribution from {self._labels[index]} failed "
                        f"verification mid-fold; excluded and refolding"
                    )
                    self._reset_frame(s)
                    # The WORKER performs the actual rollback (it is the
                    # only accumulator mutator — a reset from this
                    # transport thread could race a fold in flight).
                    self._needs_reset = True
                else:
                    self._error = RuntimeError(
                        f"contribution {index} failed verification after "
                        f"{s.applied_blocks} of its blocks were already "
                        f"aggregated — the donated accumulator cannot be "
                        f"rolled back; re-run the round"
                    )
            else:
                self._reset_frame(s)
            self._cond.notify_all()

    def _reset_fold_locked(self) -> None:
        """Zero the accumulator and forget all applied blocks (cutoff /
        quorum rollback).  The retained payloads and local arrays are
        the refold sources — pure local compute, no re-wire."""
        if self._acc is not None:
            if self._np_fold:
                self._acc = np.zeros(
                    self._nblocks * self._chunk_elems, np.int32
                )
            else:
                import jax.numpy as jnp

                self._acc = jnp.zeros(
                    self._nblocks * self._chunk_elems,
                    jnp.int32 if self._quant is not None else jnp.float32,
                )
        for s in self._streams:
            s.applied_blocks = 0

    def _maybe_cutoff_locked(self) -> None:
        """Quorum cutoff decision (worker loop, under the lock): once
        the deadline passes — or the stragglers provably cannot arrive —
        with at least ``quorum`` contributions complete, pin the
        participating set, reweight to its Σw, and refold.  The all-
        arrived case never reaches here with a subset, so quorum=n with
        no faults stays byte-identical to the classic path."""
        if self._quorum is None or self._participating is not None:
            return
        # Ready = complete AND healthy: a stream can be complete with a
        # still-standing error only transiently (a clean retry clears it
        # in _on_complete), but the cutoff must never pin a failed
        # stream into the participating set — its fold would stall the
        # chain forever.
        ready = [
            i for i, s in enumerate(self._streams)
            if s.complete and s.error is None
        ]
        if len(ready) == self._n:
            return  # everyone made it — nothing to cut
        failed = sum(1 for s in self._streams if s.error is not None)
        deadline_hit = (
            self._deadline_at is not None
            and time.monotonic() >= self._deadline_at
        )
        if len(ready) < self._quorum:
            # Quorum not met.  Give up only once the deadline has
            # passed AND even the still-pending healthy streams could
            # not fill it — failed streams get every chance to recover
            # (a clean retry clears the error) until then; without a
            # deadline the result() timeout is the bound, and its
            # PartyWaitTimeout names whoever never arrived.
            pending = self._n - len(ready) - failed
            if (
                deadline_hit
                and len(ready) + pending < self._quorum
                and self._error is None
            ):
                failed_names = [
                    self._labels[i]
                    for i, s in enumerate(self._streams)
                    if s.error is not None
                ]
                exc: BaseException = RuntimeError(
                    f"quorum {self._quorum}/{self._n} unreachable: only "
                    f"{len(ready)} contributions arrived by the round "
                    f"deadline and those from {failed_names} failed"
                )
                for i, s in enumerate(self._streams):
                    if s.error is not None:
                        exc.__cause__ = s.error
                        break
                self._error = exc
                self._cond.notify_all()
            return
        if not deadline_hit and not (
            failed and len(ready) + failed == self._n
        ):
            return
        self._participating = ready  # sorted by construction
        excluded = [
            self._labels[i] for i in range(self._n) if i not in set(ready)
        ]
        logger.warning(
            "quorum cutoff: aggregating %d/%d contributions "
            "(excluded: %s); reweighting to the arrived sum",
            len(ready), self._n, excluded,
        )
        from rayfed_tpu import telemetry

        telemetry.event(
            "quorum.cutoff",
            party=self._party,
            detail={
                "members": [self._labels[i] for i in ready],
                "excluded": excluded,
            },
        )
        if self._weights_arg is not None:
            from rayfed_tpu.fl.fedavg import _check_weights

            self._total_w = _check_weights(
                [self._weights[i] for i in ready]
            )
        else:
            self._total_w = float(len(ready))
        # Partial folds may include excluded streams' blocks (the fold
        # is per-arrival) — restart from zero over the participating set
        # in party order, which is exactly packed_weighted_sum over the
        # subset.
        self._reset_fold_locked()

    # -- result ---------------------------------------------------------------

    def result(self, timeout: Optional[float] = None,
               deadline_s: Optional[float] = None):
        """Block until every contribution streamed in; the aggregate as a
        :class:`~rayfed_tpu.fl.compression.PackedTree` in the wire dtype
        (``unpack``/``decompress`` restores the compute-dtype tree).

        ``deadline_s`` (quorum mode only): seconds from THIS call after
        which the wait stops for stragglers — once at least ``quorum``
        contributions are complete, the worker cuts the round over to
        the arrived set (reweighted to its Σw) instead of waiting out
        ``timeout``.  Cutoff granularity is the worker's wake interval
        (≤ 0.5 s past the deadline)."""
        if deadline_s is not None and self._quorum is None:
            raise ValueError("deadline_s needs quorum= at construction")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            if deadline_s is not None and self._deadline_at is None:
                self._deadline_at = time.monotonic() + float(deadline_s)
                self._cond.notify_all()  # worker re-times its waits
            while not self._done and self._error is None:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        from rayfed_tpu.exceptions import PartyWaitTimeout

                        self._error = PartyWaitTimeout(
                            f"streaming aggregation timed out after "
                            f"{timeout}s",
                            missing_parties=[
                                self._labels[i]
                                for i, s in enumerate(self._streams)
                                if not s.complete
                            ],
                        )
                        self._cond.notify_all()
                        break
                self._cond.wait(timeout=remaining)
            if self._error is not None:
                raise self._error
            return self._result

    @property
    def quorum_members(self) -> List[int]:
        """Sorted indices of the contributions the aggregate includes
        (all of them unless a quorum cutoff excluded stragglers).
        Meaningful once :meth:`result` returned."""
        with self._cond:
            if self._participating is not None:
                return list(self._participating)
            return list(range(self._n))

    @property
    def agg_overlap_frac(self) -> float:
        """Fraction of aggregation busy time hidden under the wire."""
        return self.stats.get("agg_overlap_frac", 0.0)

    # -- worker ---------------------------------------------------------------

    def _ensure_worker(self) -> None:
        with self._cond:
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._run,
                    name="rayfed-stream-agg",
                    daemon=True,
                )
                self._worker.start()

    def _parse_layout(self, s: _Stream) -> bool:
        """Locate the packed buffer inside the payload (needs only the
        manifest + skeleton-length prefix, i.e. the first chunk)."""
        if s.data_start >= 0:
            return True
        if s.payload is None or s.avail_bytes < 4:
            return False
        mv = s.payload
        (mlen,) = struct.unpack(">I", bytes(mv[:4]))
        if s.avail_bytes < 4 + mlen:
            return False
        manifest = json.loads(bytes(mv[4 : 4 + mlen]))
        s.manifest = manifest  # sideband consumers (StripeAggregator)
        leaves = manifest["leaves"]
        if not leaves or leaves[0]["k"] not in ("nd", "nds"):
            raise ValueError(
                "streaming aggregation expects a PackedTree payload "
                "(leaf 0 must be the packed wire buffer) — produce "
                "updates with fl.compress(tree, packed=True)"
            )
        spec = leaves[0]
        start = 4 + mlen + manifest["skel"]
        if spec["k"] == "nd":
            nbytes = spec["n"]
        else:
            nbytes = sum(e["n"] for e in spec["shards"])
        s.data_start = start
        s.data_nbytes = nbytes
        s.dtype = np.dtype(spec["dtype"])
        return True

    def _init_acc(self, s: _Stream) -> None:
        import jax.numpy as jnp

        itemsize = s.dtype.itemsize
        if s.data_nbytes % itemsize:
            raise ValueError("packed buffer not a whole element count")
        self._total_elems = s.data_nbytes // itemsize
        if self._total_elems >= 2**31:
            # Accumulator offsets ride int32 (jax's default index dtype
            # with x64 disabled) — beyond this a fold would silently
            # land at a wrapped offset.  Shard the model across several
            # packed trees before streaming at that scale.
            raise ValueError(
                f"packed buffer has {self._total_elems} elements — "
                f"streaming aggregation supports < 2**31 elements per "
                f"buffer; split the tree into multiple packed buffers"
            )
        self._wire_dtype = s.dtype
        if self._quant is not None:
            # Masked rounds widen the grid codes to i32 (the mod-2³²
            # ring the pairwise masks live in — fl.secagg); presummed
            # (hierarchy) rounds carry region partial sums at the
            # narrowest exact integer width; plain quantized rounds
            # carry the grid's own integer width.
            from rayfed_tpu.fl.secagg import MASKED_WIRE_DTYPE

            if self._masked:
                want_dt = MASKED_WIRE_DTYPE
            elif self._presummed is not None:
                want_dt = self._presummed
            else:
                want_dt = self._quant.wire_dtype
            if s.dtype != np.dtype(want_dt):
                mode_name = (
                    "masked" if self._masked
                    else "presummed" if self._presummed is not None
                    else "plain"
                )
                raise ValueError(
                    f"compressed-domain contribution carries "
                    f"{s.dtype} codes, this round folds {want_dt} "
                    f"({mode_name} mode) — "
                    f"sender and receiver disagree on the round shape"
                )
            if (
                self._quant_full
                and self._total_elems != self._quant.total_elems
            ):
                raise ValueError(
                    f"contribution has {self._total_elems} codes, the "
                    f"round grid covers {self._quant.total_elems} — "
                    f"all parties must quantize the identical packed "
                    f"layout"
                )
        # THE canonical grid — shared with the ring stripe schedule so
        # the fold blocks and the stripe blocks are the same blocks.
        from rayfed_tpu.fl.fedavg import packed_block_grid

        self._nblocks = packed_block_grid(
            self._total_elems, self._chunk_elems
        )
        # CPU integer folds skip jit: a per-block jit dispatch costs
        # ~100µs on the CPU backend — with N virtual parties each
        # folding a region's stripes (the hierarchy bench) that
        # dispatch tax alone dominated the round wall.  i32 adds are
        # exact and order-independent, so numpy slice-adds produce the
        # identical accumulator bit for bit (the keystone byte-identity
        # invariant holds by arithmetic, not by sharing the kernel).
        # The float path stays on jit unconditionally — XLA may fuse
        # multiply-add with different rounding than numpy's two-step —
        # and masked rounds keep the device accumulator their mod-2³²
        # correction kernel consumes.
        import jax

        self._np_fold = (
            self._quant is not None
            and not self._masked
            and jax.default_backend() == "cpu"
        )
        if self._np_fold:
            self._acc = np.zeros(
                self._nblocks * self._chunk_elems, np.int32
            )
        else:
            self._acc = jnp.zeros(
                self._nblocks * self._chunk_elems,
                jnp.int32 if self._quant is not None else jnp.float32,
            )

    def _avail_blocks(self, s: _Stream) -> int:
        if s.complete:
            return self._nblocks
        if s.data_start < 0 or s.dtype is None:
            return 0
        avail_elems = max(
            0, (min(s.avail_bytes, s.data_start + s.data_nbytes)
                - s.data_start) // s.dtype.itemsize
        )
        return min(self._nblocks, avail_elems // self._chunk_elems)

    def _chunk_np(self, src: tuple, block: int) -> np.ndarray:
        """The block's wire elements, zero-padded at the buffer tail.

        ``src`` is an under-the-lock snapshot of the stream's
        ``(elems_array, payload, dtype, data_start)``: a concurrent
        frame abort may null the live stream fields mid-fold, but the
        snapshot's bytes are a stable valid prefix of the logical
        payload (a sender retry resends identical bytes)."""
        elems_array, payload, dtype, data_start = src
        ce = self._chunk_elems
        first = block * ce
        count = min(ce, self._total_elems - first)
        if elems_array is not None:
            arr = elems_array[first : first + count]
        else:
            arr = np.frombuffer(
                payload,
                dtype=dtype,
                count=count,
                offset=data_start + first * dtype.itemsize,
            )
        if count < ce:
            pad = np.zeros(ce, dtype)
            pad[:count] = arr
            arr = pad
        return arr

    def _run(self) -> None:
        try:
            self._run_inner()
        # fedlint: disable=FED004 — transferred, not swallowed: fail(e) poisons every result waiter; this is the aggregator's dedicated worker thread, not the driver
        except BaseException as e:  # pragma: no cover - defensive
            logger.exception("streaming aggregator worker failed")
            self.fail(e)

    def _run_inner(self) -> None:
        kernel = None
        while True:
            with self._cond:
                if self._error is not None:
                    return
                if self._needs_reset:
                    self._needs_reset = False
                    self._reset_fold_locked()
                self._maybe_cutoff_locked()
                # The fold set: all streams, or the pinned quorum subset
                # after a cutoff (excluded stragglers are ignored even
                # if their bytes keep arriving).
                order = (
                    self._participating
                    if self._participating is not None
                    else list(range(self._n))
                )
                # Snapshot availability; validate layouts lazily.
                work: List[tuple] = []
                try:
                    for i in order:
                        s = self._streams[i]
                        if s.error is not None:
                            continue
                        if s.dtype is None and not self._parse_layout(s):
                            continue
                        if self._acc is None:
                            self._init_acc(s)
                        if (
                            s.data_nbytes
                            != self._total_elems * self._wire_dtype.itemsize
                            or s.dtype != self._wire_dtype
                        ):
                            raise ValueError(
                                f"contribution {i} layout mismatch: "
                                f"{s.data_nbytes}B {s.dtype} vs "
                                f"{self._total_elems} elems of "
                                f"{self._wire_dtype} — all parties must "
                                f"pack the same tree structure"
                            )
                except Exception as e:
                    self._error = e
                    self._cond.notify_all()
                    return
                if self._acc is not None:
                    # Party-order-per-block schedule: stream i may fold
                    # block b only once every EARLIER fold-set stream
                    # folded theirs — the result is then independent of
                    # arrival order (and, after a cutoff, identical to
                    # packed_weighted_sum over the participating subset).
                    # The chunk source is snapshotted HERE, under the
                    # lock (see _chunk_np).
                    prev: Optional[int] = None
                    for i in order:
                        s = self._streams[i]
                        if s.error is not None:
                            # Pre-cutoff: a failed stream stalls its
                            # successors until the cutoff excludes it
                            # (partial sums must not skip a party that
                            # the cutoff might still... never include).
                            break
                        limit = (
                            self._streams[prev].applied_blocks
                            if prev is not None else self._nblocks
                        )
                        target = min(self._avail_blocks(s), limit)
                        if target > s.applied_blocks:
                            work.append((
                                i, s.applied_blocks, target,
                                (s.elems_array, s.payload, s.dtype,
                                 s.data_start),
                            ))
                        prev = i
                all_complete = all(
                    self._streams[i].complete for i in order
                ) and (self._participating is not None
                       or not any(s.error is not None
                                  for s in self._streams))
                if not work:
                    if all_complete and self._acc is not None and all(
                        self._streams[i].applied_blocks == self._nblocks
                        for i in order
                    ):
                        break  # everything folded — finalize below
                    wait_s = 0.5
                    if (
                        self._deadline_at is not None
                        and self._participating is None
                    ):
                        wait_s = min(
                            wait_s,
                            max(0.05,
                                self._deadline_at - time.monotonic()),
                        )
                    self._cond.wait(timeout=wait_s)
                    continue
                if all_complete and not self._t_all_complete:
                    self._t_all_complete = max(
                        self._streams[i].t_complete for i in order
                    )
            # Apply outside the lock (sinks keep landing bytes meanwhile).
            if kernel is None and not self._np_fold:
                if self._quant is not None:
                    # The integer-accumulate path: widening i32
                    # multiply-add of the codes (fl.fedavg, beside the
                    # one-shot packed_quantized_sum chain it matches
                    # exactly — integer adds are order-independent).
                    from rayfed_tpu.fl.fedavg import (
                        quantized_accum_kernel,
                    )

                    kernel = quantized_accum_kernel(
                        self._chunk_elems, str(self._wire_dtype)
                    )
                else:
                    kernel = _accum_kernel(
                        self._chunk_elems, "float32", str(self._wire_dtype)
                    )
            for i, lo, hi, src in work:
                s = self._streams[i]
                if self._masked or self._presummed is not None:
                    # The party folded its own weight into the masked
                    # codes (pairwise masks only cancel at unit fold
                    # weight — fl.secagg), and a region partial sum
                    # already carries Σ w_p·q_p (fl.hierarchy) — both
                    # fold at unit weight.
                    w = np.int32(1)
                elif self._int_weights is not None:
                    w = np.int32(self._int_weights[i])
                else:
                    w = np.float32(self._weights[i])
                t0 = time.perf_counter()
                if self._np_fold:
                    ce = self._chunk_elems
                    wi = np.int32(w)
                    for b in range(lo, hi):
                        off = b * ce
                        self._acc[off:off + ce] += (
                            wi * self._chunk_np(src, b).astype(np.int32)
                        )
                else:
                    for b in range(lo, hi):
                        self._acc = kernel(
                            self._acc,
                            self._chunk_np(src, b),
                            np.int32(b * self._chunk_elems),
                            w,
                        )
                self._busy_s += time.perf_counter() - t0
                with self._cond:
                    s.applied_blocks = hi

        t0 = time.perf_counter()
        t0_wall = time.time()
        result = self._finalize()
        fin_s = time.perf_counter() - t0
        self._busy_s += fin_s
        self._t_done = time.perf_counter()
        if not self._t_all_complete:
            self._t_all_complete = self._t_done
        tail_s = max(0.0, self._t_done - self._t_all_complete)
        busy = max(self._busy_s, 1e-9)
        from rayfed_tpu import telemetry as _telemetry

        _tr = _telemetry.active()
        if _tr is not None:
            # The fold window (first byte → every block folded) and the
            # single finalize, as spans.  Wall anchors derive from the
            # perf-counter marks relative to now.
            now_p, now_w = time.perf_counter(), time.time()
            if self._t_first_byte:
                _tr.emit(
                    "agg.fold",
                    party=self._party,
                    t_start=now_w - (now_p - self._t_first_byte),
                    dur_s=max(0.0, self._t_all_complete
                              - self._t_first_byte),
                    detail={
                        "busy_ms": round(self._busy_s * 1e3, 3),
                        "parties": len(self._streams),
                    },
                )
            _tr.emit(
                "agg.finalize", party=self._party,
                t_start=t0_wall, dur_s=fin_s,
                detail={
                    "excluded": (
                        0 if self._participating is None
                        else self._n - len(self._participating)
                    ),
                },
            )
        self.stats = {
            "agg_busy_s": self._busy_s,
            "agg_tail_s": tail_s,
            "agg_wire_s": max(
                0.0, self._t_all_complete - self._t_first_byte
            ),
            "agg_overlap_frac": min(1.0, max(0.0, 1.0 - tail_s / busy)),
            "quorum_excluded": (
                0 if self._participating is None
                else self._n - len(self._participating)
            ),
            # Which sources were cut with a STANDING error (dead party,
            # verification failure) vs merely late: a coordinator-
            # failover re-establishment expects exactly the dead
            # coordinator here — anything else in the list is a second
            # fault worth an operator's eyes.
            "quorum_failed_sources": [
                self._labels[i]
                for i, s in enumerate(self._streams)
                if s.error is not None
            ],
        }
        with self._cond:
            self._result = result
            self._done = True
            self._cond.notify_all()

    def _finalize(self):
        """Divide + cast once, rebuild the PackedTree around the
        aggregated buffer (spec/passthrough from one template
        contribution — they are structural, identical across parties).
        Runs on the worker after every block folded; overridden by
        :class:`StripeAggregator` to emit a bare stripe buffer."""
        from rayfed_tpu.fl.compression import PackedTree, PackSpec

        members = (
            self._participating
            if self._participating is not None
            else list(range(self._n))
        )
        if self._quant is not None:
            # ONE fused rescale of the i32 code sums; every wire
            # payload's grid descriptor is verified against the round
            # grid first — wrong-grid codes must never rescale.
            from rayfed_tpu.fl.fedavg import finalize_packed_quantized

            self._verify_quant_members(members)
            if self._masked and self._mask_recovery is not None:
                # Dropout mask recovery (quorum rounds): the hook runs
                # the announce/reply round trip with the survivors and
                # returns the orphaned-mask correction — which must be
                # subtracted BEFORE the rescale (this worker is the
                # only accumulator mutator, so mid-round recovery can
                # only live here).  With no dropouts it still announces
                # the pinned member set (the survivors' receive
                # protocol is deterministic) and returns None.
                corr = self._mask_recovery(
                    [self._labels[i] for i in members]
                )
                if corr is not None:
                    from rayfed_tpu.fl.fedavg import (
                        masked_correction_kernel,
                    )

                    corr = np.asarray(corr, np.uint32).reshape(-1)
                    if corr.size != self._total_elems:
                        raise ValueError(
                            f"mask correction covers {corr.size} "
                            f"elements, round folds {self._total_elems}"
                        )
                    pad = self._nblocks * self._chunk_elems - corr.size
                    if pad:
                        corr = np.concatenate(
                            [corr, np.zeros(pad, np.uint32)]
                        )
                    self._acc = masked_correction_kernel()(
                        self._acc, corr
                    )
            out_dt = self._out_dtype or np.dtype(np.float32)
            out_buf = finalize_packed_quantized(
                self._acc, self._quant.scales, self._quant.zps,
                self._total_w, self._total_elems, self._chunk_elems,
                out_dt, ref=self._quant_ref,
            )
        else:
            from rayfed_tpu.fl.fedavg import finalize_packed_stripe

            out_dt = self._out_dtype or self._wire_dtype
            out_buf = finalize_packed_stripe(
                self._acc, self._total_w, self._total_elems, out_dt
            )
        out_buf.block_until_ready()
        template = self._template_tree()
        passthrough = template.passthrough
        if passthrough:
            # Non-float leaves get the same per-leaf averaging the
            # one-shot path applies (every payload is still retained as
            # a zero-copy view, so decoding the skeletons is cheap).
            # After a quorum cutoff only the participating trees reduce,
            # with the matching weight subset.
            from rayfed_tpu.fl.fedavg import _reduce_passthrough

            passthrough = _reduce_passthrough(
                [self._tree_of(self._streams[i]).passthrough
                 for i in members],
                None if self._weights_arg is None
                else [self._weights[i] for i in members],
                self._total_w,
            )
        spec = template.spec
        if str(out_dt) != spec.wire_dtype:
            spec = PackSpec(spec.entries, spec.treedef, np.dtype(out_dt).name)
        return PackedTree(out_buf, passthrough, spec)

    def _verify_quant_members(self, members) -> None:
        """Grid agreement check before the rescale: every member
        payload (retained as a zero-copy view — decode is cheap) must
        be a QuantizedPackedTree coded on exactly the round grid.
        Local contributions were checked at ``add_local``."""
        from rayfed_tpu.fl.hierarchy import RegionSumTree
        from rayfed_tpu.fl.quantize import QuantizedPackedTree
        from rayfed_tpu.fl.secagg import MaskedCodeTree

        want = self._quant.meta()
        for i in members:
            s = self._streams[i]
            if s.local_tree is not None:
                continue
            tree = self._tree_of(s)
            if not isinstance(tree, QuantizedPackedTree):
                raise TypeError(
                    f"contribution from {self._labels[i]} is not a "
                    f"QuantizedPackedTree — all parties must quantize "
                    f"onto the round's shared grid"
                )
            if self._masked != isinstance(tree, MaskedCodeTree):
                raise TypeError(
                    f"contribution from {self._labels[i]} is "
                    f"{'unmasked' if self._masked else 'masked'} but "
                    f"this round folds "
                    f"{'masked' if self._masked else 'plain'} codes — "
                    f"all parties must agree on secure_agg for the round"
                )
            if (self._presummed is not None) != isinstance(
                tree, RegionSumTree
            ):
                raise TypeError(
                    f"contribution from {self._labels[i]} is "
                    f"{'a per-party code tree' if self._presummed is not None else 'a RegionSumTree partial sum'}"
                    f" but this fold is "
                    f"{'presummed' if self._presummed is not None else 'per-party'}"
                    f" — hierarchy levels must agree on the round shape"
                )
            if tree.gmeta != want:
                raise ValueError(
                    f"contribution from {self._labels[i]} was coded on "
                    f"a different grid (fp={tree.gmeta.fp:#010x} vs "
                    f"{want.fp:#010x}) — aborting before the rescale; "
                    f"re-run the round on one grid"
                )

    def _tree_of(self, s: _Stream):
        from rayfed_tpu.fl.compression import PackedTree
        from rayfed_tpu.transport import wire as wire_mod

        if s.local_tree is not None:
            return s.local_tree
        tree = wire_mod.decode_payload(
            s.payload, allowed=self._allowed, zero_copy=True
        )
        if not isinstance(tree, PackedTree):
            raise TypeError(
                "streaming aggregation consumes PackedTree payloads, got "
                f"{type(tree).__name__}"
            )
        return tree

    def _template_tree(self):
        members = (
            self._participating
            if self._participating is not None
            else list(range(self._n))
        )
        for i in members:
            if self._streams[i].local_tree is not None:
                return self._streams[i].local_tree
        return self._tree_of(self._streams[members[0]])


class StripeAggregator(StreamingAggregator):
    """Fold one *stripe* of the packed chunk grid as its bytes arrive.

    The ring topology (:mod:`rayfed_tpu.fl.ring`) stripes the packed
    buffer's chunk grid across the sorted party ring; each stripe owner
    runs one of these over the compacted stripe payloads its peers send
    (leaf 0 of each payload is the stripe's chunks back to back, in
    ascending block order).  Everything else — the thread-safe sinks,
    the frame-abort semantics, and crucially the **party-order-per-
    block fold schedule** — is inherited from
    :class:`StreamingAggregator`, and the finalize is the shared
    :func:`rayfed_tpu.fl.fedavg.finalize_packed_stripe`.  Because both
    the fold chain and the divide+cast are elementwise, the stripe
    result is byte-identical to the corresponding element range of the
    whole-buffer aggregate: assembling the N stripes reproduces
    ``packed_weighted_sum`` exactly.

    ``expect_elems``: the stripe's element count, known to the owner
    from the canonical schedule — a mis-wired payload fails fast with a
    layout error instead of folding into the wrong offsets.
    ``meta_check``: called with the payload's ``rsm`` manifest string
    (its last — ``py`` — leaf) BEFORE any of that stream's blocks fold;
    the ring passes its schedule cross-check here, so two parties
    disagreeing on the chunk grid abort loudly instead of folding
    equal-sized-but-differently-composed stripes into wrong offsets.
    """

    def __init__(
        self,
        n_sources: int,
        weights: Optional[Sequence[float]] = None,
        allowed: Optional[Dict[str, Any]] = None,
        chunk_elems: int = DEFAULT_CHUNK_ELEMS,
        out_dtype: Any = None,
        expect_elems: Optional[int] = None,
        label: str = "stripe",
        meta_check: Optional[Any] = None,
        quant: Optional[Any] = None,
        quant_blocks: Optional[Sequence[int]] = None,
        quant_ref: Optional[Any] = None,
        party: Optional[str] = None,
    ) -> None:
        super().__init__(
            n_sources, weights=weights, allowed=allowed,
            chunk_elems=chunk_elems, out_dtype=out_dtype,
            party=party,
            quant=quant,
            # The stripe's compacted slice of the shared reference (the
            # base-class size check against the FULL grid is skipped
            # via _quant_full below).
            quant_ref=quant_ref,
        )
        self._expect_elems = (
            None if expect_elems is None else int(expect_elems)
        )
        self._label = label
        self._meta_check = meta_check
        # Compressed-domain stripes: the stripe's GLOBAL block indices
        # (ascending, the compaction order) select this owner's
        # scale/zero-point rows out of the round grid for its finalize.
        # Stripe payloads are bare code arrays (grid agreement is the
        # ring's rsm cross-check, not a per-payload descriptor), so the
        # base class's full-buffer checks are skipped.
        self._quant_full = False
        if quant is not None and quant_blocks is None:
            raise ValueError(
                f"{label}: compressed-domain stripes need quant_blocks "
                f"(the stripe's global block indices)"
            )
        self._quant_blocks = (
            None if quant_blocks is None
            else [int(b) for b in quant_blocks]
        )

    def _parse_layout(self, s: _Stream) -> bool:
        already = s.data_start >= 0
        if not super()._parse_layout(s):
            return False
        if self._meta_check is not None and not already and s.manifest is not None:
            # Wire payloads only (the owner's own stripe needs no
            # manifest; s.manifest is the base parse's — one decode per
            # stream); runs once, before any of its blocks fold.
            last = s.manifest["leaves"][-1]
            if last.get("k") != "py" or not isinstance(last.get("v"), str):
                raise ValueError(
                    f"{self._label}: stripe payload is missing its "
                    f"'rsm' manifest leaf"
                )
            self._meta_check(last["v"])
        return True

    def add_local(self, index: int, stripe: Any) -> None:
        """Feed the owner's own stripe (a 1-D wire-dtype host array)."""
        arr = np.asarray(stripe).reshape(-1)
        if (
            self._expect_elems is not None
            and arr.size != self._expect_elems
        ):
            self.fail(
                ValueError(
                    f"{self._label}: local stripe has {arr.size} "
                    f"elements, schedule expects {self._expect_elems}"
                )
            )
            return
        if (
            self._quant is not None
            and arr.dtype != np.dtype(self._quant.wire_dtype)
        ):
            self.fail(
                ValueError(
                    f"{self._label}: local stripe is {arr.dtype}, the "
                    f"round grid codes {self._quant.wire_dtype}"
                )
            )
            return
        self._attach_local(index, arr)

    def _init_acc(self, s: _Stream) -> None:
        super()._init_acc(s)
        if (
            self._expect_elems is not None
            and self._total_elems != self._expect_elems
        ):
            raise ValueError(
                f"{self._label}: contribution carries "
                f"{self._total_elems} elements, schedule expects "
                f"{self._expect_elems} — ring peers disagree on the "
                f"stripe layout"
            )

    def payload_value(self, index: int) -> Any:
        """Decode the full payload of source ``index`` (the stripe dict
        with its sideband fields) — retained as a zero-copy view, so
        this is cheap.  None for the owner's own (local) source."""
        from rayfed_tpu.transport import wire as wire_mod

        s = self._streams[index]
        if s.payload is None:
            return None
        return wire_mod.decode_payload(
            s.payload, allowed=self._allowed, zero_copy=True
        )

    def _finalize(self):
        """Bare stripe buffer in the output dtype (host array): the
        assembly step scatters it back onto the chunk grid."""
        if self._quant is not None:
            # The stripe's rows of the round grid: stripe block i of
            # the compacted payload IS global block quant_blocks[i], so
            # the per-row rescale is elementwise-identical to the
            # whole-buffer finalize at those element positions — the
            # keystone of ring/coordinator byte-identity, now in the
            # compressed domain.
            from rayfed_tpu.fl.fedavg import finalize_packed_quantized

            if len(self._quant_blocks) != self._nblocks:
                raise ValueError(
                    f"{self._label}: {self._nblocks} folded blocks vs "
                    f"{len(self._quant_blocks)} scheduled quant blocks"
                )
            scales, zps = self._quant.rows(self._quant_blocks)
            out_dt = self._out_dtype or np.dtype(np.float32)
            out_buf = finalize_packed_quantized(
                self._acc, scales, zps, self._total_w,
                self._total_elems, self._chunk_elems, out_dt,
                ref=self._quant_ref,
            )
        else:
            from rayfed_tpu.fl.fedavg import finalize_packed_stripe

            out_dt = self._out_dtype or self._wire_dtype
            out_buf = finalize_packed_stripe(
                self._acc, self._total_w, self._total_elems, out_dt
            )
        out_buf.block_until_ready()
        return np.asarray(out_buf)


# Seq ids one streaming_aggregate call consumes — callers pre-allocating
# ids for an off-main-thread call (fl.overlap's comms lane) draw exactly
# this many from runtime.next_seq_id() in program order.
STREAM_AGG_SEQ_IDS = 2


def streaming_aggregate(
    fed_objects: Sequence[Any],
    weights: Optional[Sequence[float]] = None,
    *,
    coordinator: Optional[str] = None,
    stream: str = "sagg",
    timeout: Optional[float] = None,
    out_dtype: Any = None,
    seq_ids: Optional[Sequence[int]] = None,
    round_tag: Optional[int] = None,
    timings: Optional[Dict[str, float]] = None,
    quant: Optional[Any] = None,
    quant_ref: Optional[Any] = None,
    quant_scope: Optional[str] = None,
    quant_downlink: bool = False,
    secagg: Optional[Any] = None,
    server_step: Optional[Any] = None,
) -> Any:
    """FedAvg round over the streaming + delta-cache pipeline.

    Drop-in for ``fl.aggregate(...)`` in coordinator topology when the
    contributions are PackedTrees: every party calls it at the same
    program point with the same arguments.  Owners push their update to
    the coordinator on a per-party **delta stream** (round-over-round
    unchanged chunks never cross the wire); the coordinator folds each
    arriving chunk into a donated on-device accumulator while later
    chunks are in flight, and broadcasts the aggregate (also on a delta
    stream).  Returns the averaged PackedTree on every party.

    ``stream`` names the delta-cache scope — keep it constant across
    rounds of the same training loop so the caches hit.

    ``seq_ids``: :data:`STREAM_AGG_SEQ_IDS` pre-allocated rendezvous ids
    ``(contrib_id, result_id)``.  Default (None) allocates them here —
    correct whenever the call runs on the thread driving the fed
    program.  A call dispatched to a background lane (the pipelined
    round engine, :mod:`rayfed_tpu.fl.overlap`) MUST pass ids drawn on
    the main thread instead: an off-thread ``next_seq_id`` would
    interleave nondeterministically with the main thread's task ids and
    desync the controllers' rendezvous streams.

    ``round_tag`` stamps every frame of the round (contributions and
    broadcast) with the round index (``wire.ROUND_TAG_KEY``).

    ``timings`` (optional dict) receives ``push_s`` (this party's
    contribution pushes ACKed, 0.0 on the coordinator — its own
    contribution never crosses the wire) and ``agg_s`` (wall time of the
    whole call).

    ``quant``: the round's shared :class:`~rayfed_tpu.fl.quantize.
    QuantGrid` — aggregate **in the compressed domain**: each party's
    contribution is quantized onto the grid before the push (already-
    quantized contributions pass through after a fingerprint check),
    frames carry the grid descriptor (``wire.QUANT_GRID_KEY``), the
    coordinator folds the integer codes into a donated i32 accumulator
    and rescales ONCE at finalize.  ``quant_ref``: the round's shared
    reference buffer (PackedTree or flat f32 buffer, bit-identical on
    every controller — the round's starting model) for ``mode="delta"``
    grids: parties code ``update − ref`` and the finalize adds ``ref``
    back.  ``out_dtype`` defaults to f32 in this mode.  ``quant_scope``
    keys the per-process error-feedback residual
    (:func:`rayfed_tpu.fl.quantize.compressor`) — None quantizes
    statelessly (no EF; parity tests).  ``quant_downlink``
    re-quantizes the broadcast onto a FRESH grid derived from the
    aggregate (carried in the payload, no negotiation needed) so the
    downlink bytes drop too; every party — coordinator included —
    returns the identical dequantized tree.

    ``server_step`` (:mod:`rayfed_tpu.fl.server_opt`): a finalize-side
    hook the COORDINATOR applies to the exact finalized aggregate
    before the result broadcast — the broadcast (and, with
    ``quant_downlink``, the re-quantized downlink, whose fresh grid is
    therefore ranged by the POST-step delta) carries the post-step
    model, so every controller returns the stepped bytes and advances
    its replicated optimizer state from them.  A step failure aborts
    the round on every controller (peers' parked broadcast is
    poisoned) — never a silent pre-step broadcast.

    Multi-host parties: only the party LEADER process runs the
    cross-party wire, so streaming aggregation works on the leader and
    raises ``NotImplementedError`` on non-leader coordinator processes
    — use :func:`rayfed_tpu.fl.aggregate` for multi-host coordinators.
    """
    from rayfed_tpu.fed_object import FedObject
    from rayfed_tpu.proxy import recv_on_runtime, send_on_runtime
    from rayfed_tpu.runtime import get_runtime

    runtime = get_runtime()
    objs = list(fed_objects)
    if not objs:
        raise ValueError("streaming_aggregate needs at least one object")
    if weights is not None and len(weights) != len(objs):
        raise ValueError(
            f"{len(weights)} weights for {len(objs)} objects"
        )
    for obj in objs:
        if not isinstance(obj, FedObject):
            raise TypeError(
                "streaming_aggregate consumes FedObjects (party-owned "
                f"contributions), got {type(obj).__name__}"
            )
    if quant_downlink and quant is None:
        raise ValueError("quant_downlink requires quant= (the grid)")
    if secagg is not None and quant is None:
        raise ValueError(
            "secagg= requires quant= — masks live in the shared-grid "
            "integer domain (fl.secagg)"
        )
    if server_step is not None and secagg is not None:
        raise ValueError(
            "server_step does not compose with masked (secure_agg) "
            "rounds yet — the recovery window has not been exercised "
            "with a post-finalize step (loud exclusion, see "
            "fl.server_opt)"
        )
    # The sender-side codec discipline (grid check + EF two-phase
    # commit), shared verbatim with ring/quorum; a no-op when quant is
    # None.  ``secagg`` (a fl.secagg.RoundMasker) swaps in the masked
    # codec: same discipline, plus the fused weight-and-mask step — the
    # coordinator then folds at unit weight and the masks cancel
    # bit-exactly (no dropout recovery here: the all-of-n path fails
    # the round on any loss, so no masks can orphan).
    from rayfed_tpu.fl import quantize as qz

    if quant is not None and out_dtype is None:
        # Integer codes make no sense as an output dtype — the
        # compressed-domain aggregate materializes in f32.
        out_dtype = np.float32
    if secagg is not None:
        from rayfed_tpu.fl.secagg import MaskedRoundCodec

        codec = MaskedRoundCodec(quant, quant_ref, quant_scope, secagg)
    else:
        codec = qz.RoundCodec(quant, quant_ref, quant_scope)
    qref = codec.ref
    q_descriptor = codec.descriptor
    _to_wire = codec.to_wire
    _quant_commit = codec.commit
    _quant_rollback = codec.rollback

    # Allocated identically on every controller — the determinism
    # contract that keys the rendezvous.
    if seq_ids is None:
        contrib_id = runtime.next_seq_id()
        result_id = runtime.next_seq_id()
    else:
        contrib_id, result_id = seq_ids
    t_call0 = time.perf_counter()
    me = runtime.party
    coord = coordinator or objs[0].get_party()
    backstop = timeout if timeout is not None else runtime.job_config.recv_backstop_s
    parties = list(runtime.cluster_config.parties)

    if me != coord:
        own_seq = 0  # per-OWNER ordinal: stable under client sampling,
        # unlike the global position (which churns with the active set
        # and would rotate delta-stream names every round).
        push_done: List[float] = []
        for obj in objs:
            if obj.get_party() == me:
                local_ref = obj.get_local_ref()
                if quant is not None:
                    # Quantize on the resolving thread (the task-pool
                    # worker that produced the update) — one fused
                    # kernel, then the uint8 codes are what the delta
                    # cache diffs and the wire ships.
                    local_ref = local_ref.then(_to_wire)
                push_ref = send_on_runtime(
                    runtime, coord, local_ref,
                    obj.get_fed_task_id(), contrib_id,
                    # Masked codes are fresh uniform noise every round:
                    # a delta stream would hash every chunk and pin a
                    # model-sized base for zero hits — send plain.
                    stream=(
                        None if secagg is not None
                        else f"{stream}/up/{me}/{own_seq}"
                    ),
                    round_tag=round_tag,
                    quant_meta=q_descriptor,
                )
                if timings is not None:
                    push_ref.add_done_callback(
                        lambda _r: push_done.append(time.perf_counter())
                    )
                own_seq += 1
        ref = recv_on_runtime(runtime, coord, result_id, result_id)
        try:
            result = ref.resolve(timeout=backstop)
        except BaseException:
            _quant_rollback()
            raise
        _quant_commit()
        if quant is not None and isinstance(
            result, qz.QuantizedPackedTree
        ):
            # Quantized downlink: decode with the grid the payload
            # itself carries — bit-identical to the coordinator's own
            # return value (same codes, same rescale, same shared ref).
            result = result.dequantize(
                np.dtype(out_dtype),
                ref=qref if result.gmeta.mode == "delta" else None,
            )
        if timings is not None:
            # The result broadcast only lands after the coordinator
            # folded every contribution, so the ACK timestamps are
            # complete by now.
            timings["push_s"] = (
                max(push_done) - t_call0 if push_done else 0.0
            )
            timings["agg_s"] = time.perf_counter() - t_call0
        return result

    agg = StreamingAggregator(
        len(objs),
        weights=weights,
        allowed=runtime.cluster_config.serializing_allowed_list,
        out_dtype=out_dtype,
        party=me,
        quant=quant,
        quant_ref=qref,
        masked=secagg is not None,
        # The fold grid IS the quantization grid (both are the
        # canonical packed_block_grid chunking).
        chunk_elems=(
            quant.chunk_elems if quant is not None else DEFAULT_CHUNK_ELEMS
        ),
    )
    pending_cancels: List[tuple] = []
    sink_entries: List[tuple] = []
    for i, obj in enumerate(objs):
        if obj.get_party() == me:
            local_ref = obj.get_local_ref()

            def _feed(ref, i=i):
                exc = ref.exception()
                if exc is not None:
                    agg.fail(exc)
                else:
                    try:
                        agg.add_local(i, _to_wire(ref.resolve()))
                    # fedlint: disable=FED004 — transferred, not swallowed: fail(e) poisons every result waiter; this callback runs on the resolving task-pool thread, not the driver
                    except BaseException as e:
                        agg.fail(e)

            local_ref.add_done_callback(_feed)
        else:
            sink_entries.append(
                (obj.get_party(), obj.get_fed_task_id(), contrib_id,
                 agg.sink(i))
            )
            pending_cancels.append((obj.get_fed_task_id(), contrib_id))
    if sink_entries:
        # One loop hop registers every contribution sink (and enrolls
        # their source parties with the health monitor's fail-fast).
        runtime.transport.recv_stream_many(sink_entries)
    others = [p for p in parties if p != me]
    try:
        result = agg.result(timeout=backstop)
        if server_step is not None:
            # The server-optimization step consumes the EXACT finalized
            # f32 aggregate (fl.server_opt); inside the try so a step
            # failure poisons the peers' parked broadcast like any
            # other coordinator-side failure.
            result = server_step(result)
    except BaseException as exc:
        _quant_rollback()
        for up, down in pending_cancels:
            runtime.transport.cancel_stream(up, down)
        # Fail-fast parity with aggregate(): the peers are parked on the
        # result broadcast — poison that key so their recv raises the
        # coordinator's error now, not after the hour-long backstop.
        poison = getattr(runtime.transport, "_send_poison", None)
        if poison is not None:
            for p in others:
                try:
                    poison(p, result_id, result_id, exc)
                except Exception:  # pragma: no cover - best effort
                    logger.exception(
                        "failed to poison streaming result for %s", p
                    )
        raise
    from rayfed_tpu.proxy import send_many_on_runtime

    _quant_commit()
    wire_result = result
    down_descriptor = None
    if quant_downlink:
        # Re-quantize the aggregate for the broadcast on a FRESH grid
        # derived from the aggregate itself (qz.quantize_downlink —
        # shared with quorum_aggregate so the two downlinks stay
        # byte-identical); the coordinator returns the DEQUANTIZED
        # codes, so every controller holds the identical bytes.
        wire_result, result, down_descriptor = qz.quantize_downlink(
            result, quant, qref, quant_scope, out_dtype=out_dtype
        )
    if others:
        send_many_on_runtime(
            runtime, others, wire_result, result_id, result_id,
            stream=f"{stream}/down", round_tag=round_tag,
            quant_meta=down_descriptor,
        )
    if timings is not None:
        timings["push_s"] = 0.0  # own contribution never hits the wire
        timings["agg_s"] = time.perf_counter() - t_call0
    return result
