"""High-level federated training driver: the round loop as one call.

The reference leaves the round loop to user code (its canonical shape
is the hand-rolled loop in its ``tests/test_fed_get.py:47-82``); here
the loop is a first-class driver that composes the framework's pieces —
coordinator aggregation with pipelined (lazy) rounds, FedOpt server
optimizers, bf16 wire compression, and per-party checkpoint/resume —
while preserving the multi-controller contract: every party calls
:func:`run_fedavg_rounds` at the same program point with the same
arguments and walks the identical seq-id sequence.

Checkpoint/resume: with a ``checkpointer``, each party snapshots
``(round, params, server-opt state)`` every ``checkpoint_every`` rounds
and the NEXT call resumes from the latest complete snapshot — restart
all parties and the loop continues where it left off (deterministic
seq-ids re-align the rendezvous, SURVEY §5.4's resume story).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Optional, Sequence

from rayfed_tpu.fl.compression import ErrorFeedback, compress, decompress
from rayfed_tpu.fl.fedavg import aggregate
from rayfed_tpu.fl.fedopt import ServerOptimizer

logger = logging.getLogger(__name__)

# Headroom factor for compressed-domain uplink grids (wire_quant) —
# shared with the quorum driver loop so both derive bit-identical grids
# (see fl.quantize.QUANT_DELTA_EXPAND for the rationale).
from rayfed_tpu.fl.quantize import QUANT_DELTA_EXPAND as _QUANT_DELTA_EXPAND


def sample_parties(
    parties: Sequence[str], sample: int, sample_seed: int, round_index: int
) -> list:
    """The per-round participation draw, shared by every controller.

    Draws from the **sorted** party list: the population order must be
    canonical, not dict insertion order — two controllers that built
    their ``trainers`` mapping in different orders would otherwise draw
    DIFFERENT subsets from the identical seed (``rng.sample`` picks by
    index), desyncing the seq-id streams into a hang.  The result is
    sorted too, so coordinator choice is order-stable.
    """
    import random as _random

    rng = _random.Random(int(sample_seed) * 1_000_003 + round_index)
    return sorted(rng.sample(sorted(parties), int(sample)))


def validate_round_config(
    trainers: dict,
    *,
    rounds: int = 1,
    server_opt: Optional[Any] = None,
    weights: Optional[Sequence[float]] = None,
    compress_wire: bool = False,
    packed_wire: bool = False,
    checkpointer: Any = None,
    checkpoint_every: int = 0,
    sample: Optional[int] = None,
    aggregator: Optional[Callable[[Sequence[Any]], Any]] = None,
    streaming_agg: bool = False,
    error_feedback: bool = False,
    wire_quant: Optional[Any] = None,
    mode: str = "coordinator",
    coordinator: Optional[str] = None,
    overlap: bool = False,
    ring_chunk_elems: Optional[int] = None,
    region_size: Optional[int] = None,
    region_branch: Optional[int] = None,
    region_quorum: Optional[int] = None,
    region_deadline_s: Optional[float] = None,
    quorum: Optional[int] = None,
    round_deadline_s: Optional[float] = None,
    join_ticket: Optional[dict] = None,
    round_log: Optional[list] = None,
    secure_agg: bool = False,
) -> dict:
    """Validate one round-loop configuration WITHOUT running it.

    The single producer of every feature-composition verdict
    :func:`run_fedavg_rounds` enforces: each feature pair either
    passes here (and is exercised bit-exactly by a test or bench gate)
    or raises a LOUD ``ValueError`` naming the clash — never a silent
    fallback.  Extracted so the composition-matrix test
    (``tests/test_composition_matrix.py``) can drive the full pairwise
    grid in-process, with no runtime or party subprocesses.

    Returns the normalized bits the driver needs downstream:
    ``{"wire_quant": <dtype name or None>, "checkpoint_every": <int>,
    "server_opt_kind": "none"|"fedopt"|"packed"}``.
    """
    from rayfed_tpu.fl.server_opt import PackedServerOpt

    packed_opt = (
        server_opt if isinstance(server_opt, PackedServerOpt) else None
    )
    legacy_opt = (
        server_opt
        if (server_opt is not None and packed_opt is None)
        else None
    )
    if legacy_opt is not None and not isinstance(
        legacy_opt, ServerOptimizer
    ):
        raise ValueError(
            f"server_opt must be a fl.server_opt.PackedServerOpt "
            f"(packed-domain momentum/FedAC — composes with "
            f"wire_quant/quorum/ring/hierarchy) or a legacy "
            f"fl.fedopt.ServerOptimizer, got "
            f"{type(server_opt).__name__}"
        )
    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    if checkpoint_every and checkpointer is None:
        raise ValueError("checkpoint_every set without a checkpointer")
    if checkpoint_every < 0:
        raise ValueError(f"checkpoint_every must be >= 0, got {checkpoint_every}")
    if checkpointer is not None and not checkpoint_every:
        # A checkpointer with checkpoint_every=0 would resume but never
        # save — snapshot every round rather than silently never.
        checkpoint_every = 1
    if aggregator is not None and weights is not None:
        raise ValueError(
            "aggregator and weights are mutually exclusive (a custom "
            "reducer defines its own weighting)"
        )
    if sample is not None and not 1 <= int(sample) <= len(trainers):
        raise ValueError(
            f"sample must be in [1, {len(trainers)}], got {sample}"
        )
    if sample is not None and weights is not None:
        raise ValueError(
            "sample and weights are mutually exclusive (a weight "
            "sequence cannot align with a changing per-round subset)"
        )
    _qname = None
    if wire_quant is not None:
        import numpy as _np

        _qname = _np.dtype(wire_quant).name
        if _qname not in ("uint8", "int8"):
            raise ValueError(
                f"wire_quant must be an 8-bit integer dtype (uint8/"
                f"int8), got {_qname!r}"
            )
        if not (compress_wire and packed_wire):
            raise ValueError(
                "wire_quant requires compress_wire=True and "
                "packed_wire=True (the quantized unit is the packed "
                "wire buffer)"
            )
        if (
            not streaming_agg
            and mode not in ("ring", "hierarchy")
            and quorum is None
        ):
            raise ValueError(
                "wire_quant requires streaming_agg=True, mode='ring', "
                "mode='hierarchy' or quorum= — the compressed-domain "
                "fold lives in the streaming/striped aggregators "
                "(fl.quantize)"
            )
        incompat_q = {
            "error_feedback": error_feedback,  # quant carries its OWN
            "aggregator": aggregator is not None,
            # PACKED server optimizers (fl.server_opt) compose: the
            # step runs on the exact finalized f32 beside the single
            # rescale.  Only the legacy per-leaf tree optimizers are
            # excluded here.  overlap=True composes too (the unified
            # staleness recurrence, fl.overlap): the DGA-corrected
            # contribution's delta against the round's shared broadcast
            # reference is exactly the party's local displacement, so
            # delta-grid coding commutes with the correction.
            "server_opt": legacy_opt is not None,
        }
        bad_q = [k for k, v in incompat_q.items() if v]
        if bad_q:
            raise ValueError(
                f"wire_quant is incompatible with {bad_q}: the "
                f"grid codec carries its own error feedback, the "
                f"other paths have not been taught the quantized round "
                f"shape, and a legacy fedopt.ServerOptimizer runs "
                f"per-leaf tree arithmetic — use the packed "
                f"fl.server_opt optimizers with wire_quant"
            )
    if secure_agg:
        if wire_quant is None:
            raise ValueError(
                "secure_agg requires wire_quant — pairwise masks live "
                "in the shared-grid integer domain (fl.secagg); pass "
                "e.g. wire_quant='uint8'"
            )
        if mode == "ring":
            raise ValueError(
                "secure_agg runs the streaming/quorum coordinator "
                "topology — mode='ring' is a loud exclusion (stripe "
                "owners would each see a maskable subset)"
            )
        if sample is not None and sample != len(trainers):
            raise ValueError(
                "secure_agg and sample are mutually exclusive: the "
                "mask peer set is the round's full active roster"
            )
    if streaming_agg and not (compress_wire and packed_wire):
        raise ValueError(
            "streaming_agg requires compress_wire=True and "
            "packed_wire=True (the streamed unit is the packed wire "
            "buffer)"
        )
    if streaming_agg and aggregator is not None:
        raise ValueError(
            "streaming_agg and aggregator are mutually exclusive (a "
            "custom reducer needs the raw per-party values)"
        )
    if error_feedback and not (compress_wire and packed_wire):
        raise ValueError(
            "error_feedback requires compress_wire=True and "
            "packed_wire=True (the residual is carried on the packed "
            "wire buffer)"
        )
    if mode not in ("coordinator", "ring", "hierarchy"):
        raise ValueError(
            f"unknown mode {mode!r}: expected 'coordinator', 'ring' or "
            f"'hierarchy'"
        )
    if mode == "hierarchy":
        if wire_quant is None:
            raise ValueError(
                "mode='hierarchy' requires wire_quant: hierarchical "
                "aggregation is compressed-domain ONLY (float partial "
                "sums would re-associate a non-associative fold and "
                "silently break hierarchical == flat byte-identity) — "
                "pass e.g. wire_quant='uint8'"
            )
        if region_size is None or int(region_size) < 1:
            raise ValueError(
                "mode='hierarchy' requires region_size= (the "
                "deterministic partition width of the sorted roster), "
                f"got {region_size!r}"
            )
        if streaming_agg:
            raise ValueError(
                "mode='hierarchy' and streaming_agg are mutually "
                "exclusive: the hierarchy replaces the flat hub "
                "topology streaming_agg folds on (its fallback path "
                "streams on its own) — drop streaming_agg"
            )
        if sample is not None and sample != len(trainers):
            raise ValueError(
                "mode='hierarchy' requires full participation: "
                "sampling churns the region partition every round, "
                "re-striping every region ring — use "
                "mode='coordinator' for sampled rounds"
            )
        if secure_agg:
            raise ValueError(
                "mode='hierarchy' and secure_agg are mutually "
                "exclusive: pairwise masks only cancel over the FULL "
                "party set, so a region's partial sum would be "
                "un-finalizable ring noise — loud exclusion, never "
                "silent garbage"
            )
        if aggregator is not None:
            raise ValueError(
                "mode='hierarchy' and aggregator are mutually "
                "exclusive (a custom reducer needs the raw per-party "
                "values at one place)"
            )
    if region_size is not None and mode != "hierarchy":
        raise ValueError(
            "region_size only applies to mode='hierarchy' (it sets "
            "the deterministic region partition width)"
        )
    if region_branch is not None:
        if mode != "hierarchy":
            raise ValueError(
                "region_branch only applies to mode='hierarchy' (it "
                "sets the interior tree degree of the derived "
                "multi-level hierarchy)"
            )
        if int(region_branch) < 2:
            raise ValueError(
                f"region_branch must be >= 2 (a 1-ary interior level "
                f"folds nothing), got {region_branch!r}"
            )
    if region_quorum is not None:
        if mode != "hierarchy":
            raise ValueError(
                "region_quorum only applies to mode='hierarchy' (it "
                "sets the per-region minimum arrived count for the "
                "deadline-gated region cutoff)"
            )
        if int(region_quorum) < 1:
            raise ValueError(
                f"region_quorum must be >= 1 (the minimum arrived "
                f"member count per region), got {region_quorum!r}"
            )
    if region_deadline_s is not None:
        if region_quorum is None:
            raise ValueError(
                "region_deadline_s needs region_quorum= (the "
                "per-region minimum arrived count the deadline gates)"
            )
        if float(region_deadline_s) <= 0:
            raise ValueError(
                f"region_deadline_s must be positive, got "
                f"{region_deadline_s!r}"
            )
    if mode == "ring":
        if not (compress_wire and packed_wire):
            raise ValueError(
                "mode='ring' requires compress_wire=True and "
                "packed_wire=True (the striped unit is the packed wire "
                "buffer)"
            )
        if aggregator is not None:
            raise ValueError(
                "mode='ring' and aggregator are mutually exclusive (a "
                "custom reducer needs the raw per-party values at one "
                "place)"
            )
        if sample is not None and sample != len(trainers):
            raise ValueError(
                "mode='ring' requires full participation: sampling "
                "churns ring membership, re-striping the chunk grid "
                "and thrashing the per-peer delta caches every round — "
                "use mode='coordinator' for sampled rounds"
            )
        if streaming_agg:
            raise ValueError(
                "mode='ring' and streaming_agg are mutually exclusive: "
                "the ring replaces the hub topology streaming_agg "
                "folds on (the ring's fallback path streams on its "
                "own) — drop streaming_agg or use mode='coordinator'"
            )
    if coordinator is not None and coordinator not in trainers:
        raise ValueError(
            f"coordinator {coordinator!r} is not a training party "
            f"({sorted(trainers)})"
        )
    if ring_chunk_elems is not None and mode not in ("ring", "hierarchy"):
        raise ValueError(
            "ring_chunk_elems only applies to mode='ring' or "
            "mode='hierarchy' (it sets the stripe/chunk grid "
            "granularity)"
        )
    if quorum is not None:
        if not 1 <= int(quorum) <= len(trainers):
            raise ValueError(
                f"quorum must be in [1, {len(trainers)}], got {quorum}"
            )
        if not (compress_wire and packed_wire):
            raise ValueError(
                "quorum requires compress_wire=True and packed_wire=True "
                "(the quorum cutoff and the DGA late fold run on the "
                "packed wire buffer)"
            )
        incompat = {
            # Packed server optimizers compose with quorum (the
            # cutoff's subset refold reweights the step's effective
            # Σw, and the replicated state survives coordinator
            # failover) — only the legacy tree optimizers need the
            # fixed-roster classic loop.
            "server_opt": legacy_opt is not None,
            "aggregator": aggregator is not None,
            "sample": sample is not None and sample != len(trainers),
            "error_feedback": error_feedback,
            "overlap": overlap,
        }
        bad = [k for k, v in incompat.items() if v]
        if bad:
            raise ValueError(
                f"quorum is incompatible with {bad}: each needs the "
                "exact fixed-roster synchronous round boundary that "
                "k-of-n cutoffs and elastic membership give up (packed "
                "fl.server_opt optimizers DO compose with quorum)"
            )
    if round_deadline_s is not None:
        if quorum is None:
            raise ValueError(
                "round_deadline_s only applies with quorum= (it is the "
                "straggler cutoff of k-of-n rounds)"
            )
        if not round_deadline_s > 0:
            raise ValueError(
                f"round_deadline_s must be > 0, got {round_deadline_s}"
            )
    if join_ticket is not None and quorum is None:
        raise ValueError(
            "join_ticket only applies with quorum= (elastic membership "
            "rides the quorum round protocol)"
        )
    if round_log is not None and quorum is None:
        raise ValueError(
            "round_log only applies with quorum= (the classic loop has "
            "a fixed roster — there is nothing to log)"
        )
    if overlap:
        if not (compress_wire and packed_wire):
            raise ValueError(
                "overlap=True requires compress_wire=True and "
                "packed_wire=True (the overlapped aggregation unit is "
                "the packed wire buffer, and the DGA correction runs on "
                "it)"
            )
        if mode == "hierarchy":
            raise ValueError(
                "overlap=True is incompatible with mode='hierarchy' — "
                "the pipelined engine drives the coordinator/ring "
                "collectives from its comms lane; the hierarchy's "
                "region-cutoff/regroup protocol has no lane-callable "
                "collective yet (loud exclusion, never a silent flat "
                "fallback)"
            )
        if secure_agg:
            raise ValueError(
                "overlap=True is incompatible with secure_agg — "
                "pairwise masks are keyed by a synchronous (session, "
                "stream, round) tuple over the round's full roster; "
                "the pipelined lane's in-flight round would need a "
                "mask-recovery window that has never been exercised "
                "under overlap (loud exclusion)"
            )
        incompat = {
            # PACKED server optimizers compose via the unified
            # staleness recurrence (fl.overlap): the correction anchors
            # on the post-step broadcast, so the step consumes the mean
            # one-round-stale local displacement as its pseudo-gradient.
            # Only the legacy per-leaf tree optimizers still need the
            # materialized synchronous boundary.
            "server_opt": legacy_opt is not None,
            "aggregator": aggregator is not None,
            "sample": sample is not None and sample != len(trainers),
            "error_feedback": error_feedback,
            "checkpointer": checkpointer is not None,
        }
        bad = [k for k, v in incompat.items() if v]
        if bad:
            raise ValueError(
                f"overlap=True is incompatible with {bad}: each needs "
                "the exact synchronous round boundary (the overlapped "
                "aggregate lands one round late, under the next round's "
                "compute)"
            )


    if packed_opt is not None:
        if not (compress_wire and packed_wire):
            raise ValueError(
                "a packed server_opt (fl.server_opt) requires "
                "compress_wire=True and packed_wire=True — the fused "
                "step runs over the packed wire buffer"
            )
        incompat_s = {
            # The outgoing-wire EF residual corrects the model the
            # DRIVER pushes; under a server step the broadcast already
            # IS the stepped model — pair aggressive wire dtypes with
            # wire_quant (whose grid codec carries its own EF) instead.
            "error_feedback": error_feedback,
            # A custom reducer's output is not the weighted mean the
            # pseudo-gradient step assumes (and need not be packed).
            "aggregator": aggregator is not None,
            # The masked recovery window has not been exercised with a
            # post-finalize step — loud exclusion, never silently
            # unstepped or unmasked.
            "secure_agg": secure_agg,
            # A changing per-round subset is fine for the MEAN but the
            # legacy tree path is the one with sampling history; the
            # packed step has no sampled-round test yet.
            "sample": sample is not None and sample != len(trainers),
            # join_ticket COMPOSES since the object plane landed:
            # welcomes carry the server-opt spec + a content handle to
            # the replicated state, and the joiner resyncs through the
            # pull path (loud spec-mismatch guard in fl.quorum).
        }
        bad_s = [k for k, v in incompat_s.items() if v]
        if bad_s:
            raise ValueError(
                f"packed server_opt is incompatible with {bad_s} — "
                f"loud exclusion (see fl.server_opt's composition "
                f"notes)"
            )
    return {
        "wire_quant": _qname if wire_quant is not None else None,
        "checkpoint_every": checkpoint_every,
        "server_opt_kind": (
            "none" if server_opt is None
            else "packed" if packed_opt is not None
            else "fedopt"
        ),
    }


def run_fedavg_rounds(
    trainers: dict,
    params: Any,
    rounds: int,
    *,
    server_opt: Optional[ServerOptimizer] = None,
    weights: Optional[Sequence[float]] = None,
    compress_wire: bool = False,
    packed_wire: bool = False,
    checkpointer: Any = None,
    checkpoint_every: int = 0,
    on_round: Optional[Callable[[int, Any], None]] = None,
    sample: Optional[int] = None,
    sample_seed: int = 0,
    aggregator: Optional[Callable[[Sequence[Any]], Any]] = None,
    streaming_agg: bool = False,
    error_feedback: bool = False,
    wire_dtype: Any = None,
    wire_quant: Optional[Any] = None,
    mode: str = "coordinator",
    coordinator: Optional[str] = None,
    overlap: bool = False,
    timings: Optional[list] = None,
    ring_chunk_elems: Optional[int] = None,
    region_size: Optional[int] = None,
    region_branch: Optional[int] = None,
    region_quorum: Optional[int] = None,
    region_deadline_s: Optional[float] = None,
    quorum: Optional[int] = None,
    round_deadline_s: Optional[float] = None,
    join_ticket: Optional[dict] = None,
    round_log: Optional[list] = None,
    secure_agg: bool = False,
) -> Any:
    """Run ``rounds`` FedAvg rounds over party-pinned trainer actors.

    ``trainers``: ``{party: actor}`` where ``actor.train(params)``
    returns the party's updated tree (each party's actor runs only on
    its own silo).  Every controller passes the identical arguments.

    - ``server_opt``: apply a server optimizer to the round aggregate
      (plain replacement when ``None``).  A
      :class:`rayfed_tpu.fl.server_opt.PackedServerOpt` (``fl.fedac(λ,
      γ, β)`` / ``fl.server_momentum(lr, momentum)``) runs as ONE
      fused kernel over the packed wire buffers at the single
      finalize, cutting ROUNDS-to-target (FedAC), and composes with
      ``wire_quant``, ``streaming_agg``, ``quorum`` (the cutoff's
      subset refold reweights the step's effective Σw; the replicated
      state survives coordinator failover), ``mode="ring"`` (every
      controller steps the byte-identical assembly locally),
      ``mode="hierarchy"`` (the root steps once; the tree broadcast
      carries the post-step model) and ``overlap=True`` (the unified
      staleness recurrence: the DGA correction anchors on the
      post-step broadcast, so the step consumes the mean
      one-round-stale local displacement — see fl.overlap); requires
      ``compress_wire`` + ``packed_wire``; composes with
      ``join_ticket`` (welcomes carry the spec + a content handle to
      the replicated state, resolved through the object plane); loudly
      excluded with ``secure_agg``/``error_feedback``/``aggregator``/
      ``sample`` — see :mod:`rayfed_tpu.fl.server_opt` and
      ``docs/source/server_optimization.rst``.  A legacy
      :mod:`rayfed_tpu.fl.fedopt` ``ServerOptimizer`` keeps the
      per-leaf tree path (coordinator/ring topologies, no
      wire_quant/quorum).  Checkpoints stamp the server-opt config and
      carry its state; restoring across differing configs is refused
      loudly.
    - ``compress_wire``: halves the push bytes.  Trainer contract:
      ``train`` must call :func:`~rayfed_tpu.fl.decompress` on its
      argument (a no-op on full-precision input) and return
      ``compress(updated)`` — in pipelined rounds the averaged bf16
      tree flows straight back into ``train``; the driver decompresses
      only what it returns or feeds the server optimizer.
    - ``packed_wire``: with ``compress_wire``, use the packed single-
      buffer wire form (:class:`~rayfed_tpu.fl.PackedTree`): one fused
      cast kernel instead of per-leaf casts, one contiguous wire buffer
      instead of one per leaf.  ``decompress`` on the trainer side
      accepts either form transparently; trainers returning
      ``compress(updated, packed=True)`` keep the fast path end-to-end.
    - ``checkpointer``: a :class:`rayfed_tpu.checkpoint.FedCheckpointer`;
      resume happens automatically from its latest complete round.  If
      ``checkpoint_every`` is left at 0, it defaults to 1 (every round)
      — a checkpointer that resumes but never saves is a misconfig.
    - ``on_round(i, params)``: called after each materialized round.
    - ``sample``: partial participation — each round trains only a
      deterministic pseudo-random subset of ``sample`` parties (seeded
      by ``(sample_seed, round)``, so every controller draws the
      IDENTICAL subset and the seq-id streams stay aligned).
    - ``aggregator(values) -> tree``: replace the weighted mean with a
      custom reducer over the round's fetched contributions — e.g.
      :func:`rayfed_tpu.fl.tree_median`, ``functools.partial(
      fl.tree_trimmed_mean, trim=1)``, or a Krum selection.
      Materializes every round (the reducer needs raw values) and is
      mutually exclusive with ``weights``.
    - ``streaming_agg``: aggregate each round with
      :func:`rayfed_tpu.fl.streaming.streaming_aggregate` instead of
      the one-shot fetch+reduce: the coordinator folds each arriving
      contribution chunk into a donated on-device accumulator while
      later chunks are on the wire, and contributions/broadcasts ride
      per-peer **delta streams** (unchanged chunks never re-cross the
      wire).  Requires ``compress_wire`` + ``packed_wire`` (the
      streamed unit is the packed buffer) and materializes every round;
      bit-identical to the one-shot path.
    - ``error_feedback``: carry the wire quantization error of the
      outgoing (driver→trainer) compressed model into the next round
      (:class:`rayfed_tpu.fl.ErrorFeedback`) — keeps aggressive wire
      dtypes convergent.  Requires ``compress_wire`` + ``packed_wire``
      (the residual is carried on the packed buffer) and materializes
      every round (the driver must hold the round's tree to correct
      it).  Trainer-side updates compress inside the trainer's own
      ``train``; give each trainer its own ErrorFeedback instance for
      full bidirectional feedback.
    - ``wire_dtype``: the compressed wire dtype for the driver's
      outgoing pushes (default bf16).  Pair an aggressive choice (e.g.
      ``jnp.float8_e4m3fn``) with ``error_feedback=True``.
    - ``wire_quant``: aggregate **in the compressed domain** (``"uint8"``
      / ``"int8"``; see :mod:`rayfed_tpu.fl.quantize` and
      ``docs/source/compressed_aggregation.rst``).  Each round every
      controller derives the identical shared per-block grid from the
      previous round's observed aggregate delta, contributions are
      coded as ``update − shared model`` on that grid (with a carried
      error-feedback residual — the grid codec's OWN EF, which is why
      ``error_feedback=True`` is mutually exclusive) and the
      aggregators fold the integer codes with ONE fused rescale (+
      reference add) at finalize — roughly half the bf16 wire bytes
      AND half the fold's HBM traffic.  The first round has no
      observed delta and runs unquantized (bootstrap).  Requires
      ``compress_wire`` + ``packed_wire`` and ``streaming_agg=True``,
      ``mode="ring"`` or ``quorum=`` (quantized quorum rounds run the
      coordinator topology; ``quorum`` + ``mode="ring"`` +
      ``wire_quant`` is a loud exclusion); on the streaming and quorum
      paths the result broadcast is re-quantized too (fresh grid,
      carried in the payload), and quantized-quorum rounds are
      byte-identical to quantized-streaming rounds.  Integral
      non-negative ``weights`` only (example counts).
    - ``secure_agg``: **secure aggregation**
      (:mod:`rayfed_tpu.fl.secagg`; ``docs/source/
      secure_aggregation.rst``) — each party's quantized contribution
      is masked with pairwise masks derived from the transport's HELLO
      key agreement, so the coordinator (and any single eavesdropped
      payload) learns only the SUM of the round's updates, at zero
      extra wire bytes for the masks themselves (they are generated
      from agreed seeds, never transmitted; the masked codes widen to
      i32 on the wire).  The masked round's aggregate is BYTE-identical
      to the unmasked round's.  Requires ``wire_quant`` (masks live on
      the shared integer grid) with the streaming or quorum paths
      (``mode="ring"`` and ``sample`` are loud exclusions); composes
      with ``quorum`` — a mid-round dropout triggers pairwise mask
      recovery over the survivors, and coordinator failover re-runs
      recovery on the successor's stream.  The bootstrap round (no
      grid yet) runs unquantized AND unmasked.
    - ``mode``: the aggregation wire topology.  ``"coordinator"`` (the
      default) funnels contributions through one party (hub-and-spoke;
      with ``streaming_agg`` they fold as they arrive).  ``"ring"``
      replaces the hub with a chunk-striped **reduce-scatter +
      all-gather** over the sorted party ring
      (:func:`rayfed_tpu.fl.ring.ring_aggregate`): per-party traffic is
      ``~2·|model|`` independent of party count, and the result is
      byte-identical to the coordinator path.  Requires
      ``compress_wire`` + ``packed_wire`` (the striped unit is the
      packed buffer); full participation only (``sample`` churns ring
      membership, which would re-stripe the grid and thrash every delta
      cache — use the coordinator topology for sampled rounds); custom
      ``aggregator`` reducers need the raw values and stay
      coordinator-only.  When a ring round aborts mid-flight (peer
      death, poisoned hop), EVERY controller sees the abort (poison
      cascade + commit ring) and the driver re-aggregates the same
      round's updates over the coordinator topology — the round's
      training work is never lost.  ``"hierarchy"`` scales past what
      one flat structure can carry (:mod:`rayfed_tpu.fl.hierarchy`):
      the sorted roster partitions deterministically into regions of
      ``region_size``, each region runs the chunk-striped ring
      reduce-scatter internally, region coordinators stream integer
      partial sums up to a root, and ONE fused rescale finalizes —
      per-party traffic stays ~2·|model| and no node at any level
      sees O(N) ingress, with the aggregate BYTE-identical to the
      flat compressed-domain fold (integer adds are exact and
      associative).  Requires ``wire_quant`` (hierarchical float sums
      are a loud exclusion) and ``region_size``; the bootstrap round
      (no grid yet) runs the flat streaming path; a mid-round abort
      falls back to flat streaming (classic loop) or the quorum
      coordinator path (``quorum=``) for the SAME round, in lockstep.
    - ``region_size``: the deterministic partition width of
      ``mode="hierarchy"`` (regions are contiguous slices of the
      sorted roster — every controller derives the identical partition
      from the identical roster epoch, no negotiation).
    - ``region_branch``: interior tree degree of ``mode="hierarchy"``
      (>= 2).  When the region count exceeds the branch, the tree
      recurses: region coordinators group ``region_branch`` at a time
      under interior nodes, level by level, until one root remains —
      the regrouped integer folds stay byte-identical to the flat sum
      at any depth.  Default: one interior level (the 2-level tree).
    - ``region_quorum`` / ``region_deadline_s``: per-region quorum
      cutoffs for ``mode="hierarchy"``.  Once ``region_quorum``
      members of a region have delivered and ``region_deadline_s``
      has elapsed, the region coordinator folds the arrived subset
      and moves on — the root reweights to the true arrived Σw, so a
      straggling region delays only itself, not the tree, and the
      abort-and-flatten fallback is reserved for structural failures.
    - ``coordinator``: which party anchors coordinator-mode rounds and
      ring fallbacks (default: the canonically-first — ``min`` — party).
      Exposed mainly for tests and for deployments whose first party is
      bandwidth-poor; keep it STABLE across a training run, because
      every delta-stream cache is keyed by destination and a moving
      coordinator re-seeds full payloads on every peer it moves to.
      Under ``quorum=`` this names the INITIAL lease holder only:
      coordinator death or a coordinator ``fed.leave()`` rotates the
      lease to the deterministic successor (see
      :mod:`rayfed_tpu.fl.quorum`).

    - ``overlap``: double-buffer the rounds
      (:class:`rayfed_tpu.fl.overlap.PipelinedRoundRunner`): round *k*'s
      push + aggregation runs on a dedicated comms lane WHILE round
      *k+1* trains from each party's locally-updated model, and the
      late aggregate is folded in with the DGA correction
      ``w ← agg_k + (w_local − w_local_at_send)`` — per-round wall drops
      to ``max(compute, comms)`` at the cost of one round of bounded
      staleness (``overlap=False`` keeps today's exact synchronous
      semantics).  Requires ``compress_wire`` + ``packed_wire``;
      composes with ``mode="coordinator"`` (streaming aggregation),
      ``mode="ring"`` (with the same-round coordinator fallback on ring
      aborts), ``wire_quant`` and packed ``server_opt`` (the unified
      staleness recurrence — see :mod:`rayfed_tpu.fl.overlap`);
      mutually exclusive with legacy ``server_opt``, ``aggregator``,
      ``sample``, ``error_feedback``, checkpointing, ``secure_agg``,
      ``quorum`` and ``mode="hierarchy"`` (each needs the exact
      synchronous round boundary or a lane-callable collective).
    - ``timings``: optional list receiving one ``{"local_s", "push_s",
      "agg_s", "hidden_s"}`` dict per round (seconds; also logged at
      debug level).  ``hidden_s`` is the share of the round's comms wall
      that ran under local compute — 0 on the synchronous path by
      construction.  Requesting timings materializes every round (the
      lazy pipelined path has no per-round boundary to time).
    - ``ring_chunk_elems``: override the ring topology's stripe-grid
      granularity (``mode="ring"`` only; every controller must pass the
      same value — tests use it to stripe small models).

    - ``quorum``: **k-of-n rounds** — the round aggregates as soon as at
      least ``quorum`` contributions arrived once ``round_deadline_s``
      passes (or the stragglers provably cannot arrive), reweighted to
      the arrived Σw; a straggler's missed contribution folds into its
      NEXT round via the DGA correction instead of being dropped, and
      the live roster (``fed.join``/``fed.leave``/monitor-declared
      death) advances by coordinator announcement at round boundaries —
      see :mod:`rayfed_tpu.fl.quorum`.  The coordinator itself is a
      rotating crash-tolerant lease: on monitor-declared coordinator
      death every survivor fails over to the deterministic successor
      (next alive party on the sorted roster ring) and re-establishes
      the same round there, and a coordinator ``fed.leave()`` hands the
      lease over gracefully in its final announcement.  Requires
      ``compress_wire`` + ``packed_wire``; with ``quorum=len(trainers)``
      and no faults the result is byte-identical to the streaming path.
      Composes with ``mode="ring"`` (a ring abort re-aggregates the
      round over the coordinator topology with the quorum cutoff) and
      with ``checkpointer`` (snapshots carry round, roster epoch,
      member log, session and params; restore re-derives the
      coordinator from the restored roster).  Incompatible with
      ``server_opt``/``aggregator``/``sample``/``error_feedback``/
      ``overlap`` (each needs the exact fixed-roster synchronous
      boundary).
    - ``round_deadline_s``: the straggler cutoff for quorum rounds (and
      the per-wait deadline of quorum-mode ring rounds).  Without it a
      quorum round only cuts over when missing parties are DECLARED
      dead by the health monitor.
    - ``join_ticket``: the welcome dict returned by ``fed.join()`` — a
      (re)joining controller enters the in-progress quorum run at the
      welcome's round with the welcome's params; all other arguments
      must match the running controllers'.

    Without a server optimizer the rounds **pipeline**: the averaged
    model flows into the next round as a lazy ``FedObject`` (no
    ``fed.get`` barrier) and only the final round materializes.  A
    server optimizer (or ``on_round``/checkpointing) materializes every
    round — the server step is driver-side tree arithmetic.

    Returns the final global params (identical on every controller).
    """
    cfg = validate_round_config(
        trainers,
        rounds=rounds,
        server_opt=server_opt,
        weights=weights,
        compress_wire=compress_wire,
        packed_wire=packed_wire,
        checkpointer=checkpointer,
        checkpoint_every=checkpoint_every,
        sample=sample,
        aggregator=aggregator,
        streaming_agg=streaming_agg,
        error_feedback=error_feedback,
        wire_quant=wire_quant,
        mode=mode,
        coordinator=coordinator,
        overlap=overlap,
        ring_chunk_elems=ring_chunk_elems,
        region_size=region_size,
        region_branch=region_branch,
        region_quorum=region_quorum,
        region_deadline_s=region_deadline_s,
        quorum=quorum,
        round_deadline_s=round_deadline_s,
        join_ticket=join_ticket,
        round_log=round_log,
        secure_agg=secure_agg,
    )
    checkpoint_every = cfg["checkpoint_every"]
    _qname = cfg["wire_quant"]
    import numpy as _np

    # validate_round_config already classified server_opt — dispatch on
    # ITS verdict so the driver can never disagree with validation.
    packed_opt = (
        server_opt if cfg["server_opt_kind"] == "packed" else None
    )
    legacy_opt = (
        server_opt if cfg["server_opt_kind"] == "fedopt" else None
    )

    from rayfed_tpu.fed_object import FedObject
    from rayfed_tpu.fl.server_opt import (
        PackedServerOptimizer,
        check_snapshot_server_opt,
        describe_server_opt,
    )

    state = legacy_opt.init(params) if legacy_opt is not None else None
    sopt = PackedServerOptimizer(packed_opt) if packed_opt is not None else None
    # The checkpoint stamp for THIS run's server-opt config — every
    # snapshot carries it, and a restore across differing configs is
    # refused loudly (a silent momentum reset changes the trajectory
    # without failing anything).
    sopt_descr = describe_server_opt(server_opt)
    start_round = 0

    # Quorum rounds own their resume story (roster epoch + member log +
    # session ride the snapshot; see fl/quorum.py) — the classic
    # params/server-state restore below would strip all of that.
    if (
        checkpointer is not None
        and quorum is None
        and checkpointer.latest_round() is not None
    ):
        check_snapshot_server_opt(
            checkpointer.load_metadata().get("server_opt"), sopt_descr
        )
        target = {"params": params}
        if state is not None:
            target["server_state"] = state
        if sopt is not None:
            import jax.numpy as _sjnp

            from rayfed_tpu.fl.compression import pack_tree as _pt

            target["server_state"] = packed_opt.init(
                _pt(params, _sjnp.float32).buf
            )
        restored_round, snap = checkpointer.restore(target=target)
        params = snap["params"]
        if state is not None:
            state = snap["server_state"]
        if sopt is not None:
            sopt.load_state(snap["server_state"])
        start_round = restored_round
        if start_round >= rounds:
            return params

    # Pipelined mode only when nothing needs the materialized value
    # each round.
    pipeline = (
        server_opt is None
        and on_round is None
        and not checkpoint_every
        and aggregator is None  # a reducer needs the raw values
        and not streaming_agg  # streaming materializes at the reducer
        and not error_feedback  # the residual needs the driver's tree
        and mode == "coordinator"  # ring assembles (materializes) per round
        and timings is None  # per-round timing needs a round boundary
        and len(trainers) > 1
    )
    # Coordinator pinned to the canonically-first party unless the
    # caller overrides it — and then kept for the WHOLE run.  The churn
    # rationale: every delta-stream cache (contributions up, broadcast
    # down, ring fallback) is keyed by its destination party, so a
    # coordinator that rotates — e.g. "first active party" under client
    # sampling — would re-point every stream each round, re-seeding
    # full payloads everywhere and retaining stale multi-MB bases on
    # every former coordinator.  Stability beats load-spreading here;
    # spreading the load is what mode="ring" is for.
    coord = coordinator if coordinator is not None else min(trainers)
    # ``wire_dtype`` (default bf16) is where error feedback earns its
    # keep: fp8 wire halves bf16's bytes again, and the carried
    # residual is what keeps it convergent.
    import jax.numpy as _jnp

    wire_dt = _jnp.bfloat16 if wire_dtype is None else wire_dtype

    if quorum is not None:
        # k-of-n rounds with elastic membership own their loop shape
        # (roster-driven active set, DGA late folds, round-index-derived
        # rendezvous keys) — see fl/quorum.py.
        from rayfed_tpu.fl.quorum import run_quorum_rounds

        return run_quorum_rounds(
            trainers, params, rounds,
            quorum=int(quorum),
            round_deadline_s=round_deadline_s,
            weights=weights,
            coordinator=coord,
            wire_dtype=wire_dt,
            mode=mode,
            ring_chunk_elems=ring_chunk_elems,
            on_round=on_round,
            timings=timings,
            join_ticket=join_ticket,
            round_log=round_log,
            checkpointer=checkpointer,
            checkpoint_every=checkpoint_every,
            wire_quant=_qname if wire_quant is not None else None,
            secure_agg=secure_agg,
            region_size=region_size,
            region_branch=region_branch,
            region_quorum=region_quorum,
            region_deadline_s=region_deadline_s,
            server_opt=packed_opt,
        )

    if overlap:
        # The pipelined engine owns its own loop shape (double-buffered
        # rounds + DGA correction + comms lane) — see fl/overlap.py.
        # wire_quant and the packed server optimizer ride along: the
        # unified staleness recurrence makes the DGA correction commute
        # with delta-grid coding and with the accelerated server step.
        from rayfed_tpu.fl.overlap import PipelinedRoundRunner

        runner = PipelinedRoundRunner(
            trainers,
            weights=weights,
            mode=mode,
            coordinator=coord,
            wire_dtype=wire_dt,
            on_round=on_round,
            ring_chunk_elems=ring_chunk_elems,
            wire_quant=_qname,
            server_opt=sopt,
        )
        return runner.run(params, rounds, timings=timings)

    ef = ErrorFeedback(wire_dt) if error_feedback else None

    parties = list(trainers)

    def round_parties(r: int):
        if sample is None or sample == len(parties):
            return parties
        # Deterministic per-round subset: every controller draws the
        # identical parties (same seed, same round) or the seq-id
        # streams desync — see sample_parties for the canonical-order
        # contract.
        return sample_parties(parties, int(sample), sample_seed, r)

    current: Any = params  # tree, or FedObject in pipelined rounds
    # Compressed-domain state: the previous round's observed aggregate
    # delta (shared — derived from broadcast values only), the range
    # reference for the next round's grid.  None until one round has
    # been observed, so the first round always runs unquantized.
    quant_prev_delta = None

    me = None
    sa_keys = None
    sa_session = None
    # Flight recorder (rayfed_tpu/telemetry.py): armed, every
    # materialized round emits driver-side spans carrying the SAME
    # round/epoch keys the transport stamps on frames, so the driver's
    # view and the wire's view join on one timeline.  The lazy pipelined
    # path stays untraced (no per-round boundary), exactly like
    # ``timings``.
    from rayfed_tpu import telemetry as _telemetry

    trace_rounds = _telemetry.armed() and not pipeline
    if timings is not None or trace_rounds:
        import time as _time
    if timings is not None or secure_agg or trace_rounds:
        from rayfed_tpu.runtime import get_runtime

        _rt = get_runtime()
        me = _rt.party
    if secure_agg:
        _transport = _rt.transport
        sa_keys = getattr(_transport, "secagg_keys", None)
        if sa_keys is None or not hasattr(
            _transport, "ensure_secagg_peer_keys"
        ):
            raise ValueError(
                "secure_agg needs the transport key-agreement plane "
                "(TransportManager.secagg_keys) — this transport has "
                "none"
            )
        # One HELLO ping per missing pair, before the first masked
        # round (fl.secagg / transport.secagg).
        _transport.ensure_secagg_peer_keys(parties)
        # Fresh mask-seed scope per run, drawn identically on every
        # controller: two runs in one process must never reuse a
        # (session, stream, round) seed — reused keystream over
        # different data is a two-time pad.
        sa_session = str(_rt.next_seq_id())

    for r in range(start_round, rounds):
        active = round_parties(r)
        # Wire form: a driver-held tree is compressed before the push
        # (with the carried error-feedback residual folded in, when
        # enabled); a lazy FedObject from a pipelined round is already
        # the trainers' own (compressed) wire form.
        if compress_wire and not isinstance(current, FedObject):
            outgoing = (
                ef.compress(current)
                if ef is not None
                else compress(
                    current, packed=packed_wire, wire_dtype=wire_dt
                )
            )
        else:
            outgoing = current
        rec = None
        if timings is not None or trace_rounds:
            # Per-round breakdown (satellite of the overlap work): the
            # synchronous path exposes local/push/agg walls with
            # hidden_s pinned at 0 — comms fully serialize behind
            # compute here, which is exactly what overlap=True removes.
            rec = {
                "local_s": 0.0, "push_s": 0.0, "agg_s": 0.0,
                "hidden_s": 0.0,
            }
            t_r0 = _time.perf_counter()
            t_r0_wall = _time.time()
        updates = [trainers[p].train.remote(outgoing) for p in active]
        if rec is not None and me in active:
            my_ref = updates[active.index(me)].get_local_ref()
            if my_ref is not None:
                my_ref.add_done_callback(
                    lambda _ref, rec=rec, t0=t_r0: rec.__setitem__(
                        "local_s", _time.perf_counter() - t0
                    )
                )
        if pipeline:
            last = r == rounds - 1
            current = aggregate(
                updates,
                weights,
                mode="coordinator",
                coordinator=coord,
                materialize=last,
            )
            if last and compress_wire:
                current = decompress(current)
            continue

        # aggregate() owns the wire topology for both the mean and a
        # custom reducer (coordinator-side reduce + broadcast at N>2) —
        # one place decides who talks to whom.  The streaming path rides
        # the same coordinator topology but folds contributions in as
        # their chunks arrive; the ring path replaces the hub with a
        # reduce-scatter + all-gather.  All three are bit-identical.
        #
        # With error feedback (or a server optimizer) the aggregate
        # must come back in f32: casting the mean to an aggressive
        # wire dtype here would re-quantize it with no residual to
        # compensate (the broadcast's delta cache still applies).
        agg_out_dtype = (
            "float32"
            if (error_feedback or server_opt is not None)
            else None
        )
        # Compressed-domain round: parties code their update as a DELTA
        # against the round's shared starting model (`current`, bit-
        # identical on every controller) on a grid derived from the
        # PREVIOUS round's observed aggregate delta — per-party deltas
        # live at that scale, so the 8-bit step resolves the signal,
        # not the ambient parameter range.  Every controller derives
        # the identical grid from the identical shared buffers (that IS
        # the negotiation; the fingerprint rides every quantized frame
        # and the aggregators verify it).  The FIRST round has no
        # observed delta yet and runs unquantized (bootstrap).
        round_grid = None
        round_ref = None
        if wire_quant is not None:
            from rayfed_tpu.fl import quantize as _qz
            from rayfed_tpu.fl.compression import pack_tree

            round_ref = _np.asarray(
                pack_tree(current, _jnp.float32).buf
            )
            if quant_prev_delta is not None:
                round_grid = _qz.make_round_grid(
                    quant_prev_delta, wire_dtype=_qname, mode="delta",
                    # The grid chunking must BE the fold/stripe
                    # chunking: a ring round with an overridden
                    # ring_chunk_elems quantizes on that same grid, or
                    # ring_aggregate's chunk-match guard would abort
                    # (and silently fall back) every quantized round.
                    chunk_elems=(
                        ring_chunk_elems
                        if mode in ("ring", "hierarchy") else None
                    ),
                    # Per-party deltas overshoot the aggregate delta
                    # (the mean averages them down) — give the grid
                    # headroom; what still clips rides the EF residual.
                    expand=_QUANT_DELTA_EXPAND,
                )
        # Packed server optimization (fl.server_opt): the round's
        # shared starting buffer anchors the step (applied at the
        # finalizing node for streaming/quorum/hierarchy, locally on
        # every controller for ring/classic — deterministic f32 on
        # byte-identical input either way) and the post-round state
        # resync every controller runs from the broadcast pair.
        step_fn = None
        x_srv = None
        if sopt is not None:
            if round_ref is not None:
                x_srv = round_ref
            else:
                from rayfed_tpu.fl.compression import pack_tree as _pt2

                x_srv = _np.asarray(_pt2(current, _jnp.float32).buf)
            sopt.ensure(x_srv)
            step_fn = sopt.step_fn(x_srv)
        # Secure aggregation: this party's round masker (pairwise
        # seeds toward every active peer at its own fold weight); the
        # keystream expansion prefetches on a background thread so it
        # overlaps training/the wire instead of the round's critical
        # path.  The bootstrap round (no grid) runs unmasked.
        round_masker = None
        if secure_agg and round_grid is not None and me in trainers:
            from rayfed_tpu.fl import secagg as _sa
            from rayfed_tpu.fl.fedavg import quant_weights

            _iw, _ = quant_weights(
                None if weights is None
                else [float(w) for w in weights],
                len(active),
            )
            round_masker = _sa.RoundMasker(
                sa_keys, me, [p for p in active if p != me],
                session=sa_session, stream="fedavg", round_index=r,
                weight=_iw[active.index(me)],
            )
            round_masker.prefetch(round_grid.total_elems)
        if mode == "hierarchy":
            from rayfed_tpu.fl.streaming import streaming_aggregate

            if round_grid is None:
                # Bootstrap round: no shared grid has been observed yet
                # and hierarchy is compressed-domain only — run the
                # flat streaming round (exactly the quantized loop's
                # own bootstrap), hierarchical from the next round.
                avg = streaming_aggregate(
                    updates, weights, stream="fedavg",
                    coordinator=coord, out_dtype=agg_out_dtype,
                    timings=rec,
                    server_step=step_fn,
                )
            else:
                from rayfed_tpu.fl.hierarchy import (
                    HIER_STATS,
                    HierarchyRoundError,
                    hierarchy_aggregate,
                )

                try:
                    avg = hierarchy_aggregate(
                        updates, weights,
                        region_size=int(region_size),
                        region_branch=region_branch,
                        region_quorum=region_quorum,
                        region_deadline_s=region_deadline_s,
                        stream="fedavg",
                        server_step=step_fn,
                        quant=round_grid, quant_ref=round_ref,
                        quant_scope="fedavg",
                        # Quantize the broadcast down the tree too —
                        # the downlink is the other half of the
                        # round's bytes (shared quantize_downlink
                        # producer).
                        quant_downlink=True,
                        round_tag=r, timings=rec,
                    )
                except HierarchyRoundError as e:
                    # The abort reached every controller (tree-shaped
                    # poison cascade + commit/release), so all of them
                    # take this branch in lockstep: re-aggregate the
                    # SAME round's updates over the flat streaming
                    # path — owners still hold them, and the shared
                    # RoundCodec re-quantizes with the SAME residual.
                    logger.warning(
                        "hierarchy round %d aborted (%s); falling back "
                        "to flat streaming aggregation at %r", r, e,
                        coord,
                    )
                    HIER_STATS["fallback_rounds"] += 1
                    avg = streaming_aggregate(
                        updates, weights, stream="fedavg",
                        coordinator=coord, timings=rec,
                        quant=round_grid, quant_ref=round_ref,
                        quant_scope="fedavg",
                        # The SAME step from the SAME state: the abort
                        # happened before any resync, so the flat
                        # re-run's step is bit-identical to the one the
                        # hierarchy root would have applied.
                        server_step=step_fn,
                    )
        elif mode == "ring":
            from rayfed_tpu.fl.ring import (
                RING_STATS,
                RingRoundError,
                ring_aggregate,
            )

            try:
                avg = ring_aggregate(
                    updates, weights, stream="fedavg",
                    out_dtype=agg_out_dtype,
                    chunk_elems=ring_chunk_elems, timings=rec,
                    quant=round_grid, quant_ref=round_ref,
                    quant_scope="fedavg",
                )
                if step_fn is not None:
                    # The ring has no downlink — every controller holds
                    # the byte-identical assembled aggregate, so each
                    # applies the same deterministic f32 step locally
                    # and all byte-agree on the post-step model.
                    avg = step_fn(avg)
            except RingRoundError as e:
                # The abort reached every controller (poison cascade +
                # commit ring), so all of them take this branch in
                # lockstep: re-aggregate the SAME round's updates over
                # the coordinator topology — owners still hold them, so
                # no training work is lost.
                from rayfed_tpu.fl.streaming import streaming_aggregate

                logger.warning(
                    "ring round %d aborted (%s); falling back to "
                    "coordinator aggregation at %r", r, e, coord,
                )
                RING_STATS["fallback_rounds"] += 1
                avg = streaming_aggregate(
                    updates, weights, stream="fedavg",
                    coordinator=coord, out_dtype=agg_out_dtype,
                    timings=rec,
                    # Same grid, same (uncommitted) residual: the
                    # fallback re-quantizes the identical codes the
                    # ring round would have folded.  Downlink stays
                    # plain — this is the recovery path, keep it
                    # simple.  The server step re-runs from the same
                    # (never-resynced) state at the coordinator.
                    quant=round_grid, quant_ref=round_ref,
                    quant_scope="fedavg",
                    server_step=step_fn,
                )
        elif streaming_agg:
            from rayfed_tpu.fl.streaming import streaming_aggregate

            avg = streaming_aggregate(
                updates, weights, stream="fedavg",
                coordinator=coord,
                out_dtype=agg_out_dtype,
                timings=rec,
                quant=round_grid, quant_ref=round_ref,
                quant_scope="fedavg",
                # Quantize the result broadcast too: the downlink is
                # the other half of the round's bytes.  Under a server
                # step the coordinator steps FIRST, so the downlink
                # recode's fresh grid is ranged by the post-step delta.
                quant_downlink=round_grid is not None,
                secagg=round_masker,
                server_step=step_fn,
            )
        else:
            t_a0 = _time.perf_counter() if rec is not None else 0.0
            avg = aggregate(
                updates, weights, reducer=aggregator, coordinator=coord
            )
            if step_fn is not None:
                # Every controller holds the byte-identical broadcast
                # mean; the deterministic f32 step keeps them agreeing.
                avg = step_fn(avg)
            if rec is not None:
                rec["agg_s"] = _time.perf_counter() - t_a0
        if sopt is not None:
            # Every controller advances its state replica from the
            # round's byte-agreed broadcast pair (the broadcast IS the
            # post-step model) — all replicas stay byte-identical with
            # zero extra wire bytes (fl.server_opt).
            sopt.resync(x_srv, _np.asarray(avg.buf))
        if wire_quant is not None:
            # What the grid must cover next round: how far the global
            # model just moved, per block.  Derived from broadcast
            # values only, so it is bit-identical on every controller
            # (under server_opt: the POST-step delta — the grid ranges
            # over the model movement the step actually realized).
            quant_prev_delta = (
                _np.asarray(avg.buf).astype(_np.float32) - round_ref
            )
        if compress_wire:
            avg = decompress(avg)
        if legacy_opt is not None:
            current, state = legacy_opt.apply(current, avg, state)
        else:
            current = avg
        if on_round is not None:
            on_round(r, current)
        if checkpoint_every and (r + 1) % checkpoint_every == 0:
            snap = {"params": current}
            if state is not None:
                snap["server_state"] = state
            if sopt is not None:
                snap["server_state"] = sopt.state
            checkpointer.save(
                r + 1, snap, metadata={"server_opt": sopt_descr}
            )
        if rec is not None:
            # The aggregation call blocks on this party's own training
            # output before any byte can move, so its measured walls
            # include the local wait — subtract it to report the comms-
            # only window (what overlap=True would hide).
            rec["push_s"] = max(0.0, rec["push_s"] - rec["local_s"])
            rec["agg_s"] = max(0.0, rec["agg_s"] - rec["local_s"])
            # Correlation stamp: the SAME keys the transport rides on
            # every frame (wire.ROUND_TAG_KEY / EPOCH_TAG_KEY), so a
            # timings row joins the wire's view of its round on one
            # timeline.  Classic fedavg has no roster epoch — None.
            rec["round"] = r
            rec["epoch"] = None
            rec["coordinator"] = coord
            if timings is not None:
                timings.append(rec)
            if trace_rounds:
                _telemetry.emit(
                    "driver.round", round=r, party=me, peer=coord,
                    t_start=t_r0_wall,
                    dur_s=_time.perf_counter() - t_r0,
                    detail={
                        k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in rec.items()
                    },
                )
            logger.debug(
                "round %d timings: local=%.3fs push=%.3fs agg=%.3fs "
                "hidden=%.3fs", r, rec["local_s"], rec["push_s"],
                rec["agg_s"], rec["hidden_s"],
            )

    return current
