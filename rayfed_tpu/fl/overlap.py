"""Pipelined federated rounds: hide cross-party aggregation under compute.

The synchronous round loop serializes its two expensive phases: every
party finishes its local steps, pushes its delta, then **idles** until
the aggregate comes back — per-round wall time is ``compute + comms``
even though the two use disjoint resources (devices vs the wire).  After
the codec (PR 1), the receive path (PR 2) and the topology (PR 3)
squeezed the comms term itself, the remaining cost is that
serialization.

This module removes it with **one round of bounded staleness**
(delayed-gradient averaging — Federated Accelerated SGD,
arXiv:2006.08950; transparent-overlap proxies, arXiv:2305.09593): after
computing its round-*k* model, each party hands the push + aggregation
of round *k* to a background **comms lane**
(:class:`rayfed_tpu.executor.CommsLane`) and immediately begins round
*k+1* local steps from its *locally updated* model.  When the round-*k*
aggregate lands, the party folds it in with the DGA correction::

    w  ←  agg_k + (w_local − w_local_at_send)

i.e. the delayed global average replaces the stale local base while the
local progress made meanwhile is preserved verbatim.  Writing
``Δ_{k+1,p}`` for party *p*'s round-*k+1* local progress, the global
model evolves as ``agg_{k+1} = agg_k + mean_p Δ_{k+1,p}`` — exactly the
synchronous FedAvg recurrence except that each ``Δ`` is computed from a
one-round-stale base.  Per-round wall time drops from
``compute + comms`` to ``max(compute, comms)`` (+ the cheap correction).

Multi-controller determinism: every controller runs the identical main-
thread program (train → correct → hand off), so the fed seq-id streams
stay aligned; the lane NEVER allocates seq ids — each round's
aggregation ids are drawn on the main thread in program order and passed
in (``seq_ids=``), because an off-thread ``next_seq_id`` would
interleave nondeterministically with task ids and desync the rendezvous.

Fault story: every in-flight round is tagged with its round index (the
frames carry ``wire.ROUND_TAG_KEY``), and a ring round whose
aggregation aborts is **re-aggregated — same round, same
contributions — over the coordinator topology** before the runner
moves on: the abort (:class:`~rayfed_tpu.fl.ring.RingRoundError`,
peer death included) surfaces on every controller (poison cascade +
commit ring), so all of them take the fallback in lockstep, mirroring
the synchronous driver's ring→coordinator contract.  Coordinator-mode
failures propagate loudly on every controller instead of falling back
(a rerun over the same topology with the same contributions would fail
identically) — either way a round is never silently skipped.

``run_fedavg_rounds(overlap=True)`` is the one-call entry point;
:class:`PipelinedRoundRunner` is the engine underneath for callers that
want to drive rounds themselves.

**The unified staleness recurrence** (ROADMAP item 1a, shipped here) is
what lets the correction compose with delta-grid coding
(``wire_quant``) and with the accelerated server step (``server_opt``)
— both were loud exclusions until the following two observations:

*Overlap x wire_quant.*  Write ``b_{k-1}`` for the round-(k−1)
broadcast (the value every controller byte-agrees on).  Round *k*'s
corrected contribution is ``c_p = b_{k-1} + (u_p − c_p^{prev})``, so
its delta against the round's shared reference — which IS ``b_{k-1}``,
exactly as in the synchronous quantized loop — is::

    c_p − b_{k-1}  =  u_p − c_p^{prev}

i.e. the party's *local displacement over one round of training*: the
same quantity whose scale the synchronous loop's delta grid is ranged
for (previous aggregate delta x ``QUANT_DELTA_EXPAND`` headroom).  The
DGA correction therefore **commutes with delta-grid coding**: quantize
the corrected contribution against the broadcast reference and you have
coded the raw displacement, bit for bit (``dga_correct`` computes in
f32 and casts once to the wire dtype, so no intermediate rounding
intrudes).  The runner derives the round grid from the previous
broadcast delta — the identical shared-buffer derivation as
``run_fedavg_rounds``'s classic loop — with round 0 unquantized
(bootstrap, nothing observed yet), and hands ``quant/quant_ref/
quant_scope`` to the very same collective codepaths
(``streaming_aggregate`` / ``ring_aggregate``), RoundCodec EF
discipline included.

*Overlap x server_opt.*  With a packed server step the broadcast is
``b_k = step(x_k, m_k)`` where ``m_k = mean_p c_p`` is the finalized
mean.  Anchor the correction on that post-step broadcast —
``c_p ← b_{k-1} + (u_p − c_p^{prev})``, literally the same
``dga_correct`` call — and take means::

    m_k − b_{k-1}  =  mean_p u_p − m_{k-1}

The step's pseudo-gradient ``x_k − m_k`` therefore consumes exactly the
**mean one-round-stale local displacement**: the accelerated recurrence
runs on delayed gradients (the delayed-gradient regime Federated
Accelerated SGD analyzes, arXiv:2006.08950) instead of silently
composing ``step(x, agg) + Δ`` as a naive pairing would.  Mechanically
the runner passes the finalize-side step hook into the collective (the
coordinator steps the exact finalized f32 once; ring rounds step the
byte-identical assembly locally on every controller) and resyncs the
replicated optimizer state from each landed broadcast pair — the same
state-without-a-state-broadcast contract as every synchronous topology
(fl.server_opt).  Both compositions are verified bit-exactly by
in-process replays in ``tests/test_overlap.py`` (see the composition
matrix rows).  (The QUORUM loop's straggler late fold — the same
``dga_correct`` call — composes the same way one level down: the missed
contribution reaches the optimizer one round late inside the NEXT
round's pseudo-gradient; see ``docs/source/server_optimization.rst``.)
This recurrence is also the prerequisite the buffered asynchronous
driver builds on — ``fl/async_rounds.py`` runs it at per-party
staleness instead of the uniform one-round lag.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)


@functools.lru_cache(maxsize=None)
def _dga_kernel(out_dtype_name: str):
    """One fused ``agg + (cur − base)`` over packed wire buffers.

    All three operands convert to f32 for the arithmetic (the wire dtype
    is usually bf16 — subtracting near-equal bf16 values directly would
    lose the low bits the correction exists to preserve) and the result
    casts back to the wire dtype in the same fused program.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _corr(agg, cur, base):
        return (
            agg.astype(jnp.float32)
            + cur.astype(jnp.float32)
            - base.astype(jnp.float32)
        ).astype(jnp.dtype(out_dtype_name))

    return _corr


def dga_correct(agg: Any, cur: Any, base: Any) -> Any:
    """``agg + (cur − base)`` on PackedTrees — the DGA staleness fix.

    ``agg`` is the delayed round aggregate, ``cur`` the party's current
    local model, ``base`` the local model at the time its contribution
    was sent (= what ``cur`` was trained from).  Runs as a party-local
    fed task inside the pipelined loop; exposed for tests and custom
    runners.  Non-float (passthrough) leaves get the same elementwise
    recurrence.
    """
    from rayfed_tpu.fl.compression import PackedTree

    for name, tree in (("agg", agg), ("cur", cur), ("base", base)):
        if not isinstance(tree, PackedTree):
            raise TypeError(
                f"dga_correct consumes PackedTrees; {name} is "
                f"{type(tree).__name__} — trainers must return "
                "fl.compress(updated, packed=True)"
            )
    if cur.spec != base.spec:
        raise ValueError(
            "dga_correct: cur/base pack specs differ — the trainer "
            "changed its tree structure mid-run"
        )
    if (
        agg.spec.entries != cur.spec.entries
        or agg.spec.treedef != cur.spec.treedef
    ):
        raise ValueError(
            "dga_correct: aggregate pack spec differs from the local "
            "model's — all parties must pack the identical structure"
        )
    buf = _dga_kernel(cur.spec.wire_dtype)(agg.buf, cur.buf, base.buf)
    passthrough = tuple(
        a + (c - b)
        for a, c, b in zip(agg.passthrough, cur.passthrough, base.passthrough)
    )
    return PackedTree(buf, passthrough, cur.spec)


class _InFlight:
    """One round's aggregation handed to the comms lane."""

    __slots__ = ("round_index", "ref", "rec")

    def __init__(self, round_index: int, ref: Any, rec: Dict[str, float]):
        self.round_index = round_index
        self.ref = ref
        self.rec = rec


class PipelinedRoundRunner:
    """Double-buffered FedAvg rounds: round *k*'s comms under round
    *k+1*'s compute.

    ``trainers``/``weights``/``mode``/``coordinator`` as in
    :func:`rayfed_tpu.fl.run_fedavg_rounds`; the trainer wire contract
    is the packed one (``train`` decompresses its argument and returns
    ``fl.compress(updated, packed=True)``).  ``mode="coordinator"``
    aggregates each round with
    :func:`~rayfed_tpu.fl.streaming.streaming_aggregate` (delta streams
    + on-the-wire folding); ``mode="ring"`` with
    :func:`~rayfed_tpu.fl.ring.ring_aggregate`, falling back to the
    coordinator topology for any round the ring aborts — both compose
    with the overlap because the lane only needs a blocking collective
    call with pre-allocated seq ids.

    ``wire_quant``: optional integer wire dtype name (``"uint8"`` /
    ``"uint16"``) — rounds run compressed-domain exactly like the
    synchronous quantized loop (delta grid derived from the previous
    broadcast delta, round 0 unquantized bootstrap, scoped
    error-feedback residual under ``stream``); the unified staleness
    recurrence (module docstring) is why the corrected contribution
    codes exactly.  ``server_opt``: optional packed server optimizer
    (:class:`~rayfed_tpu.fl.server_opt.PackedServerOptimizer`, or the
    bare packed spec, which gets wrapped) — the broadcast becomes the
    post-step model and the step consumes the mean one-round-stale
    local displacement as its pseudo-gradient.

    Every controller constructs the runner with identical arguments and
    calls :meth:`run` at the same program point (the usual
    multi-controller contract).
    """

    def __init__(
        self,
        trainers: Dict[str, Any],
        *,
        weights: Optional[Sequence[float]] = None,
        mode: str = "coordinator",
        coordinator: Optional[str] = None,
        wire_dtype: Any = None,
        stream: str = "fedavg",
        on_round: Optional[Callable[[int, Any], None]] = None,
        ring_chunk_elems: Optional[int] = None,
        wire_quant: Optional[str] = None,
        server_opt: Any = None,
    ) -> None:
        if not trainers:
            raise ValueError("PipelinedRoundRunner needs trainers")
        if mode not in ("coordinator", "ring"):
            raise ValueError(
                f"unknown mode {mode!r}: expected 'coordinator' or 'ring'"
            )
        if weights is not None and len(weights) != len(trainers):
            raise ValueError(
                f"{len(weights)} weights for {len(trainers)} trainers"
            )
        if coordinator is not None and coordinator not in trainers:
            raise ValueError(
                f"coordinator {coordinator!r} is not a training party "
                f"({sorted(trainers)})"
            )
        self._trainers = trainers
        self._weights = (
            None if weights is None else [float(w) for w in weights]
        )
        self._mode = mode
        self._coord = coordinator if coordinator is not None else min(trainers)
        import jax.numpy as jnp

        self._wire_dtype = jnp.bfloat16 if wire_dtype is None else wire_dtype
        self._stream = stream
        self._on_round = on_round
        self._ring_chunk_elems = ring_chunk_elems
        self._wire_quant = None if wire_quant is None else str(wire_quant)
        if server_opt is not None and not hasattr(server_opt, "step_fn"):
            # Convenience for direct-runner callers: accept the bare
            # packed spec and wrap it the way run_fedavg_rounds does.
            from rayfed_tpu.fl.server_opt import PackedServerOptimizer

            server_opt = PackedServerOptimizer(server_opt)
        self._sopt = server_opt
        # The local controller's party — set by run() (the runtime is
        # not required at construction time); stamps the flight
        # recorder's driver.round / overlap.hidden spans.
        self._me: Optional[str] = None

    # -- lane-side: one round's push + aggregate (+ fallback) ----------------

    def _aggregate_round(
        self,
        r: int,
        objs: List[Any],
        seq_ids: Sequence[int],
        fallback_ids: Sequence[int],
        rec: Dict[str, float],
        grid: Any = None,
        ref: Any = None,
        step_fn: Optional[Callable[[Any], Any]] = None,
    ) -> Any:
        from rayfed_tpu.fl.ring import RING_STATS, RingRoundError, ring_aggregate
        from rayfed_tpu.fl.streaming import streaming_aggregate

        # Under a server step the aggregate must come back f32 (the
        # step's pseudo-gradient lives below bf16 resolution); quant
        # rounds finalize f32 already.
        out_dtype = "float32" if step_fn is not None else None
        t0 = time.perf_counter()
        try:
            if self._mode != "ring":
                # No fallback on the coordinator topology: its failures
                # (poisoned contribution, dead peer) would fail a rerun
                # over the SAME topology with the SAME contributions
                # identically, and a coordinator-side timeout doesn't
                # reach the participants as a catchable error — a
                # fallback here would desync the controllers.  The
                # error surfaces loudly on every controller instead
                # (result poison); the round is never silently skipped.
                return streaming_aggregate(
                    objs, self._weights, stream=self._stream,
                    coordinator=self._coord, seq_ids=seq_ids,
                    round_tag=r, timings=rec,
                    out_dtype=out_dtype,
                    quant=grid, quant_ref=ref,
                    quant_scope=self._stream if grid is not None else None,
                    # Quantize the result broadcast too — the downlink
                    # is the other half of the round's bytes (same as
                    # the synchronous quantized loop).
                    quant_downlink=grid is not None,
                    server_step=step_fn,
                )
            try:
                agg = ring_aggregate(
                    objs, self._weights, stream=self._stream,
                    chunk_elems=self._ring_chunk_elems,
                    seq_ids=seq_ids, round_tag=r, timings=rec,
                    out_dtype=out_dtype,
                    quant=grid, quant_ref=ref,
                    quant_scope=self._stream if grid is not None else None,
                )
                if step_fn is not None:
                    # The ring has no downlink — every controller holds
                    # the byte-identical assembled aggregate, so each
                    # applies the same deterministic f32 step locally
                    # and all byte-agree on the post-step model.
                    agg = step_fn(agg)
                return agg
            except RingRoundError as exc:
                # The abort reached every controller (poison cascade +
                # commit ring — ring_aggregate's contract, peer death
                # included), so all of them take this branch in
                # lockstep: re-aggregate the SAME round's contributions
                # over the coordinator topology — the owners still hold
                # them, so no training work is lost and no round is
                # silently skipped.  Only a failed fallback propagates.
                # Mirrors the synchronous driver's ring→coordinator
                # contract.
                logger.warning(
                    "pipelined round %d ring aggregation failed (%s); "
                    "re-aggregating the same round synchronously over "
                    "the coordinator topology at %r", r, exc, self._coord,
                )
                RING_STATS["fallback_rounds"] += 1
                return streaming_aggregate(
                    objs, self._weights, stream=self._stream,
                    coordinator=self._coord, seq_ids=fallback_ids,
                    round_tag=r, timings=rec,
                    out_dtype=out_dtype,
                    # Same grid, same (uncommitted) residual: the
                    # fallback re-quantizes the identical codes the ring
                    # round would have folded.  Downlink stays plain —
                    # recovery path, keep it simple.  The server step
                    # re-runs from the same (never-resynced) state.
                    quant=grid, quant_ref=ref,
                    quant_scope=self._stream if grid is not None else None,
                    server_step=step_fn,
                )
        finally:
            # Raw lane window (fallback included).  The lane job BLOCKS
            # on this party's own contribution before any byte can move,
            # so the honest comms wall is computed in _collect from
            # [contribution ready → aggregate landed], not from here.
            rec["_lane_t0"] = t0
            rec["_lane_t1"] = time.perf_counter()

    # -- main-thread driver ---------------------------------------------------

    def _alloc_ids(self, runtime) -> tuple:
        """Draw the round's aggregation seq ids in main-thread program
        order — primary ids for the mode's collective, plus fallback ids
        for the same-round synchronous re-aggregation.  Allocated
        unconditionally (used or not) so every controller's counter
        advances identically."""
        from rayfed_tpu.fl.ring import RING_SEQ_IDS
        from rayfed_tpu.fl.streaming import STREAM_AGG_SEQ_IDS

        n = RING_SEQ_IDS if self._mode == "ring" else STREAM_AGG_SEQ_IDS
        primary = tuple(runtime.next_seq_id() for _ in range(n))
        fallback = tuple(
            runtime.next_seq_id() for _ in range(STREAM_AGG_SEQ_IDS)
        )
        return primary, fallback

    def _collect(
        self,
        inflight: _InFlight,
        backstop: float,
        next_u_done: Optional[Dict[str, Any]] = None,
    ) -> Any:
        """Block until the in-flight round's aggregate lands; rewrites
        the round's record with the HONEST comms wall.

        The lane job blocks on this party's own contribution before any
        byte can move, so the raw call walls (``push_s``/``agg_s`` as
        measured inside the collective) include local-compute wait.  The
        true comms window runs from [contribution ready → aggregate
        landed].  ``hidden_s`` is the share of that window spent under
        the NEXT round's local train (``next_u_done`` holds its
        completion timestamp; the train starts from the same event that
        opens the comms window — the corrected contribution — so the
        hidden stretch is [window start → min(window end, train end)]).
        The main thread's own blocked time is NOT the measure: training
        runs on the task pool, so in steady state the main thread sits
        in this wait for the whole round period whether or not comms
        overlapped anything.  Only one round of compute can hide a
        round's comms — round *k+2*'s train consumes the round-*k+1*
        correction, which consumes this very aggregate.
        """
        agg = inflight.ref.resolve(timeout=backstop)
        rec = inflight.rec
        t_round0 = rec.pop("_t0", None)
        lane_t0 = rec.pop("_lane_t0", None)
        lane_t1 = rec.pop("_lane_t1", None)
        start = None
        if lane_t0 is not None and lane_t1 is not None:
            # My contribution resolved before the aggregate could land,
            # so the local_s callback has fired by now.  The window can
            # also not open before the (serial) lane reached this job.
            ready = (
                t_round0 + rec["local_s"]
                if t_round0 is not None and rec["local_s"] > 0.0
                else lane_t0
            )
            start = max(ready, lane_t0)
            # The collective measured its walls from its OWN call start;
            # anchor them on the absolute lane end to stay correct even
            # when a fallback re-aggregation overwrote the record.
            t_call0 = lane_t1 - rec["agg_s"] if rec["agg_s"] > 0.0 else start
            rec["push_s"] = max(0.0, t_call0 + rec["push_s"] - start)
            rec["agg_s"] = max(0.0, lane_t1 - start)
            if next_u_done is not None:
                # A next-round train still running at this landing has
                # covered the whole window (it cannot have started
                # after ``start`` opened the window).
                done = next_u_done.get("t")
                end_hidden = (
                    lane_t1 if done is None else min(lane_t1, done)
                )
                rec["hidden_s"] = min(
                    max(0.0, end_hidden - start), rec["agg_s"]
                )
        logger.debug(
            "round %d timings: local=%.3fs push=%.3fs agg=%.3fs "
            "hidden=%.3fs",
            inflight.round_index, rec.get("local_s", 0.0),
            rec.get("push_s", 0.0), rec.get("agg_s", 0.0),
            rec["hidden_s"],
        )
        from rayfed_tpu import telemetry as _telemetry

        _tr = _telemetry.active()
        if _tr is not None and lane_t1 is not None:
            # The honest round record as a span, plus the overlap's
            # hidden-comms window — the stretch of round k's comms that
            # ran UNDER round k+1's train.  Wall anchors derive from
            # the perf-counter marks relative to now (the ring append
            # itself never blocks the lane).
            now_p, now_w = time.perf_counter(), time.time()
            anchor = t_round0 if t_round0 is not None else lane_t0
            _tr.emit(
                "driver.round", round=inflight.round_index,
                party=self._me, peer=self._coord,
                t_start=now_w - (now_p - anchor),
                dur_s=max(0.0, lane_t1 - anchor),
                detail={
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in rec.items()
                },
            )
            if start is not None and rec["hidden_s"] > 0.0:
                _tr.emit(
                    "overlap.hidden", round=inflight.round_index,
                    party=self._me,
                    t_start=now_w - (now_p - start),
                    dur_s=rec["hidden_s"],
                    detail={"agg_s": round(rec["agg_s"], 6)},
                )
        return agg

    def run(
        self,
        params: Any,
        rounds: int,
        *,
        timings: Optional[List[Dict[str, float]]] = None,
    ) -> Any:
        """Run ``rounds`` pipelined rounds from ``params``; returns the
        final global params (a decompressed tree, identical on every
        controller up to the one-round staleness semantics).

        ``timings``: optional list receiving one
        ``{"local_s", "push_s", "agg_s", "hidden_s"}`` dict per round
        (also logged at debug level as each round's aggregate lands).
        """
        if rounds < 1:
            raise ValueError(f"rounds must be >= 1, got {rounds}")
        import rayfed_tpu as fed
        from rayfed_tpu.executor import CommsLane
        from rayfed_tpu.fl.compression import compress, decompress
        from rayfed_tpu.runtime import get_runtime

        runtime = get_runtime()
        me = runtime.party
        self._me = me
        backstop = runtime.job_config.recv_backstop_s
        parties = list(self._trainers)
        outgoing = compress(params, packed=True, wire_dtype=self._wire_dtype)
        # Compressed-domain / server-opt round state (the unified
        # staleness recurrence, module docstring).  ``round_base`` is
        # the f32 reference every controller byte-agrees on for the
        # round about to be SUBMITTED (round 0: the f32 pack of the
        # init; later: the f32 view of the latest landed broadcast);
        # ``inflight_base`` anchors the IN-FLIGHT round's step/grid so
        # the optimizer resync and the next grid derivation use the
        # matching broadcast pair when that round lands.
        sopt = self._sopt
        use_quant = self._wire_quant is not None
        round_base = None
        inflight_base = None
        prev_delta = None
        if use_quant or sopt is not None:
            import jax.numpy as jnp
            import numpy as _np

            from rayfed_tpu.fl.compression import pack_tree

            round_base = _np.asarray(pack_tree(params, jnp.float32).buf)
        lane = CommsLane(
            name=f"rayfed-comms-{me}",
            bind_runtime_fn=runtime._bind_to_current_thread,
        )
        try:
            inputs: Dict[str, Any] = {p: outgoing for p in parties}
            prev_contribs: Optional[Dict[str, Any]] = None
            inflight: Optional[_InFlight] = None
            for r in range(rounds):
                rec: Dict[str, Any] = {
                    "local_s": 0.0, "push_s": 0.0, "agg_s": 0.0,
                    "hidden_s": 0.0,
                    # Correlation stamp (flight recorder): the same
                    # keys the transport rides on every frame, so this
                    # row joins the wire's view of its round.  The
                    # overlap runner has no roster epoch.
                    "round": r, "epoch": None, "coordinator": self._coord,
                }
                t_r0 = time.perf_counter()
                rec["_t0"] = t_r0  # popped by _collect
                # Round-r local steps — each party trains from its OWN
                # model (round 0: the shared init; later: its corrected
                # model), so launching costs no wire traffic and no
                # barrier.
                u = {
                    p: self._trainers[p].train.remote(inputs[p])
                    for p in parties
                }
                # Absolute end of MY round-r train — _collect uses it to
                # measure how much of round r-1's comms window this
                # train covered (hidden_s).
                u_done: Optional[Dict[str, Any]] = None
                if me in u:
                    u_ref = u[me].get_local_ref()
                    if u_ref is not None:
                        u_done = {"t": None}
                        u_ref.add_done_callback(
                            lambda _ref, d=u_done: d.__setitem__(
                                "t", time.perf_counter()
                            )
                        )
                if inflight is None:
                    contribs = u  # round 0: raw local models
                else:
                    # Round r-1's aggregate lands here — usually already
                    # done (it ran under round r-1→r compute); apply the
                    # DGA correction as a party-local fed task chained
                    # on the round-r train output.
                    agg_prev = self._collect(inflight, backstop, u_done)
                    if use_quant or sopt is not None:
                        new_base = _np.asarray(agg_prev.buf).astype(
                            _np.float32
                        )
                        if sopt is not None:
                            # Every controller advances its state
                            # replica from the landed round's
                            # byte-agreed broadcast pair — zero extra
                            # wire bytes (fl.server_opt).
                            sopt.resync(
                                inflight_base, _np.asarray(agg_prev.buf)
                            )
                        if use_quant:
                            # What the grid must cover next round: how
                            # far the global model just moved (under
                            # server_opt: the POST-step delta).
                            prev_delta = new_base - round_base
                        round_base = new_base
                    if self._on_round is not None:
                        self._on_round(
                            inflight.round_index, decompress(agg_prev)
                        )
                    contribs = {
                        p: fed.remote(dga_correct).party(p).remote(
                            agg_prev, u[p], prev_contribs[p]
                        )
                        for p in parties
                    }
                if me in contribs:
                    local_ref = contribs[me].get_local_ref()
                    if local_ref is not None:
                        local_ref.add_done_callback(
                            lambda _ref, rec=rec, t0=t_r0: rec.__setitem__(
                                "local_s", time.perf_counter() - t0
                            )
                        )
                # Round-r grid/step, derived from broadcast values only
                # (bit-identical on every controller).  The FIRST round
                # has no observed delta yet and runs unquantized
                # (bootstrap) — exactly the synchronous quantized loop.
                round_grid = None
                if use_quant and prev_delta is not None:
                    from rayfed_tpu.fl import quantize as _qz

                    round_grid = _qz.make_round_grid(
                        prev_delta, wire_dtype=self._wire_quant,
                        mode="delta",
                        # The grid chunking must BE the ring's stripe
                        # chunking, or ring_aggregate's chunk-match
                        # guard would abort (and fall back) every
                        # quantized round.
                        chunk_elems=(
                            self._ring_chunk_elems
                            if self._mode == "ring" else None
                        ),
                        # Per-party deltas overshoot the aggregate
                        # delta; what still clips rides the EF
                        # residual.
                        expand=_qz.QUANT_DELTA_EXPAND,
                    )
                step_fn = None
                if sopt is not None:
                    sopt.ensure(round_base)
                    step_fn = sopt.step_fn(round_base)
                inflight_base = round_base
                seq_ids, fallback_ids = self._alloc_ids(runtime)
                inflight = _InFlight(
                    r,
                    lane.submit(
                        self._aggregate_round, r, list(contribs.values()),
                        seq_ids, fallback_ids, rec,
                        round_grid,
                        round_base if use_quant else None,
                        step_fn,
                    ),
                    rec,
                )
                if timings is not None:
                    timings.append(rec)
                # Round r+1 trains from the corrected round-r model —
                # which IS the round-r contribution (the correction both
                # fixes the contribution and advances the local model).
                prev_contribs = contribs
                inputs = contribs
            final = self._collect(inflight, backstop)
            if self._on_round is not None:
                self._on_round(rounds - 1, decompress(final))
            return decompress(final)
        finally:
            lane.shutdown(wait=False)
