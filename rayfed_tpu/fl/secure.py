"""Secure aggregation: pairwise-masked sums that reveal only the total.

Cross-silo FL's canonical privacy primitive (Bonawitz et al., "Practical
Secure Aggregation", 2017): each party adds a random mask per peer —
``+mask(i,j)`` when ``i < j`` and ``−mask(i,j)`` when ``i > j`` — so
every mask appears exactly once positive and once negative across the
parties, and the *sum* of the masked updates equals the sum of the raw
updates while any single masked update is indistinguishable from noise.

Exactness: floating-point masking would leak through rounding (the
masks only cancel approximately), so updates are carried in **uint32
fixed-point with wraparound** — addition mod 2³² is associative, masks
cancel bit-exactly, and the only loss is the fixed-point quantization
chosen by ``frac_bits``.

Key material: ``pairwise_key`` derives the (i, j) seed from a shared
``group_key`` + the party-name pair + the round number.  How the group
key is provisioned is deployment policy (the reference leaves TLS certs
to the operator the same way, ``tool/generate_tls_certs.py``); in
production each pair would run a key exchange over the authenticated
mTLS channel and feed the result in here.

Usage (each party, same code — multi-controller):

    masked = mask_update(update, party="alice", parties=parties,
                         round_num=r, group_key=key, clip=8.0)
    # push `masked` like any update; then on the aggregate:
    total = unmask_sum(fed.get(masked_objs), clip=8.0)
    avg = jax.tree_util.tree_map(lambda t: t / len(parties), total)
"""

from __future__ import annotations

import hashlib
from typing import Any, Sequence

import jax
import jax.numpy as jnp

_MOD = 2**32


def pairwise_key(group_key: bytes, a: str, b: str, round_num: int) -> bytes:
    """256-bit seed for the (a, b) pair at one round — order-independent.

    The full digest feeds the mask XOF: truncating to a JAX PRNGKey
    would cap the keyspace at threefry's 64 bits, which an
    honest-but-curious aggregator could brute-force offline against a
    single masked update.
    """
    lo, hi = sorted((a, b))
    lo_b, hi_b = lo.encode(), hi.encode()
    # Length-prefixed components: a '|'-delimited preimage would let
    # names containing '|' collide across pairs (('a','b|c') vs
    # ('a|b','c')), handing one pair another pair's mask seed.
    return hashlib.sha256(
        b"rayfed-secagg|%d:%s|%d:%s|%d|"
        % (len(lo_b), lo_b, len(hi_b), hi_b, round_num)
        + group_key
    ).digest()


def _encode(tree: Any, clip: float, frac_bits: int) -> Any:
    """Float pytree → uint32 fixed-point (two's-complement wrap).

    Values are clipped to ±``clip`` first: fixed-point needs a known
    range, and secure aggregation deployments clip updates anyway (the
    mask hides magnitudes only within the ring).
    """
    scale = float(2**frac_bits)

    def enc(x):
        x = jnp.clip(x.astype(jnp.float32), -clip, clip)
        # int32 → uint32 astype is the two's-complement embedding into
        # the ring (wraps mod 2³²); clip·2^frac_bits < 2³¹ keeps the
        # int32 exact.  No int64 needed (x64 mode stays off).
        return jnp.round(x * scale).astype(jnp.int32).astype(jnp.uint32)

    return jax.tree_util.tree_map(enc, tree)


def _decode(tree: Any, frac_bits: int) -> Any:
    """uint32 fixed-point sum → float pytree.

    uint32 → int32 astype is the two's-complement read (values ≥ 2³¹
    become negative) — exact while |true sum| < 2³¹, which
    :func:`unmask_sum` guards.
    """
    scale = float(2**frac_bits)
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.int32).astype(jnp.float32) / scale, tree
    )


def _mask_for(seed: bytes, tree: Any) -> Any:
    """One uint32 mask per element, expanded from the 256-bit pair seed.

    SHAKE-256 as the XOF (domain-separated per leaf index) keeps the
    full seed entropy — unlike JAX's threefry PRNG, whose 64-bit key
    would be the scheme's effective security level.
    """
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    masks = []
    for i, leaf in enumerate(leaves):
        stream = hashlib.shake_256(
            seed + b"|leaf|%d" % i
        ).digest(4 * leaf.size)
        masks.append(
            jnp.asarray(
                np.frombuffer(stream, dtype=np.uint32).reshape(leaf.shape)
            )
        )
    return jax.tree_util.tree_unflatten(treedef, masks)


def mask_update(
    tree: Any,
    *,
    party: str,
    parties: Sequence[str],
    round_num: int,
    group_key: bytes,
    clip: float = 8.0,
    frac_bits: int = 16,
) -> Any:
    """Fixed-point-encode ``tree`` and add this party's pairwise masks.

    Returns a uint32 pytree safe to push: without the peers' masked
    updates it is uniformly random in the ring.  ``clip``/``frac_bits``
    must match across parties and in :func:`unmask_sum`.
    """
    if party not in parties:
        raise ValueError(f"party {party!r} not in {list(parties)!r}")
    out = _encode(tree, clip, frac_bits)
    for peer in parties:
        if peer == party:
            continue
        mask = _mask_for(pairwise_key(group_key, party, peer, round_num), out)
        sign = 1 if party < peer else -1
        out = jax.tree_util.tree_map(
            # uint32 arithmetic wraps mod 2^32 — exactly the ring we want.
            (lambda o, m: o + m) if sign > 0 else (lambda o, m: o - m),
            out,
            mask,
        )
    return out


def unmask_sum(
    masked_trees: Sequence[Any], *, frac_bits: int = 16, clip: float = 8.0
) -> Any:
    """Sum all parties' masked updates; masks cancel bit-exactly.

    Returns the float **sum** of the clipped updates (divide by the
    party count for the average).  ``clip`` bounds the representable
    sum: n·clip must stay below 2^(31−frac_bits) or the ring wraps.
    """
    n = len(masked_trees)
    if n == 0:
        raise ValueError("unmask_sum needs at least one masked update")
    if n * clip >= float(2 ** (31 - frac_bits)):
        raise ValueError(
            f"{n} parties at clip={clip} overflow the ring at "
            f"frac_bits={frac_bits}; lower frac_bits or clip"
        )
    total = masked_trees[0]
    for t in masked_trees[1:]:
        total = jax.tree_util.tree_map(lambda a, b: a + b, total, t)
    return _decode(total, frac_bits)
