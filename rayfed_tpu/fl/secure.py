"""DEPRECATED shim — the secure-aggregation subsystem moved to
:mod:`rayfed_tpu.fl.secagg`.

This module was the seed-era demo: whole-tree fixed-point masking with
an operator-provisioned group key, applied around ``fed.get``.  The
real subsystem now lives in :mod:`rayfed_tpu.fl.secagg` (masking in the
shared-grid integer domain, pairwise key agreement riding the transport
HELLO handshake, quorum-dropout mask recovery — wired through
``run_fedavg_rounds(secure_agg=True)``), and the in-process primitives
this module exported live there too:

- :func:`~rayfed_tpu.fl.secagg.pairwise_key`
- :func:`~rayfed_tpu.fl.secagg.mask_update`
- :func:`~rayfed_tpu.fl.secagg.unmask_sum`

Import them from ``rayfed_tpu.fl.secagg`` (or ``rayfed_tpu.fl``); this
shim re-exports them unchanged and will be removed.
"""

from __future__ import annotations

import warnings

from rayfed_tpu.fl.secagg import (  # noqa: F401
    mask_update,
    pairwise_key,
    unmask_sum,
)

warnings.warn(
    "rayfed_tpu.fl.secure is deprecated: the secure-aggregation "
    "subsystem lives in rayfed_tpu.fl.secagg (transport rounds: "
    "run_fedavg_rounds(secure_agg=True))",
    DeprecationWarning,
    stacklevel=2,
)
