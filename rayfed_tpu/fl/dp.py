"""Differential privacy for federated updates: clip + calibrated noise.

The DP-FedAvg primitive (McMahan et al., 2018): before an update leaves
its silo, (1) bound its global L2 norm to ``clip_norm`` — the
sensitivity of the aggregate to any one party — and (2) add Gaussian
noise scaled by ``noise_multiplier · clip_norm``.  Accounting (ε, δ
composition over rounds) is deployment policy and depends on the
sampling regime; this module provides the mechanism, applied
identically by every party to its own update before the push.

Composes with :mod:`rayfed_tpu.fl.secagg`: clip first (secure
aggregation needs bounded values anyway), noise, then mask — the server
only ever sees the noised sum.  The transport rounds
(``run_fedavg_rounds(secure_agg=True)``) mask in the shared-grid
integer domain, where headroom is the grid's own concern: the clipped
mass of an out-of-range noised update rides the error-feedback
residual, and the i32 overflow guard is
:meth:`~rayfed_tpu.fl.quantize.QuantGrid.check_weight_headroom`.  The
range discipline below applies to the IN-PROCESS fixed-point primitive
(:func:`rayfed_tpu.fl.secagg.mask_update`): its encode re-clips
per-coordinate at its ``clip`` (default ±8), and Gaussian noise with
σ = noise_multiplier · clip_norm can exceed that range and be
truncated, biasing the sum and weakening the stated DP mechanism.  Use
:func:`secure_clip_for` to pick a safe fixed-point range (it is
validated by :func:`check_secure_composition`, which :func:`privatize`
cannot run for you because it never sees the fixed-point clip).

All jit-compiled pytree arithmetic; noise is drawn on-device from a
party-held PRNG key.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp


@jax.jit
def global_norm(tree: Any) -> jax.Array:
    """Global L2 norm across every leaf of a pytree (f32)."""
    return jnp.sqrt(
        sum(
            jnp.sum(leaf.astype(jnp.float32) ** 2)
            for leaf in jax.tree_util.tree_leaves(tree)
        )
    )


@functools.partial(jax.jit, static_argnums=(1,))
def clip_by_global_norm(tree: Any, clip_norm: float) -> Tuple[Any, jax.Array]:
    """Scale ``tree`` so its global L2 norm is at most ``clip_norm``.

    Returns ``(clipped, original_norm)``; a tree already inside the ball
    is returned unscaled (standard DP-SGD clipping, not normalization).
    """
    norm = global_norm(tree)
    factor = jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))
    clipped = jax.tree_util.tree_map(
        lambda leaf: (leaf.astype(jnp.float32) * factor).astype(leaf.dtype),
        tree,
    )
    return clipped, norm


def secure_clip_for(
    *, clip_norm: float, noise_multiplier: float, tail_sds: float = 6.0
) -> float:
    """Fixed-point ``clip`` for ``fl.secagg.mask_update`` after ``privatize``.

    A privatized coordinate is bounded by ``clip_norm`` (global-L2
    clipping bounds every coordinate) plus Gaussian noise of
    σ = ``noise_multiplier · clip_norm``; ``tail_sds`` standard
    deviations of headroom (default 6 → per-coordinate truncation
    probability ~1e-9) keeps the fixed-point encode from re-clipping
    the noise and biasing the sum.
    """
    sigma = noise_multiplier * clip_norm
    return clip_norm + tail_sds * sigma


def check_secure_composition(
    *,
    clip_norm: float,
    noise_multiplier: float,
    secure_clip: float,
    tail_sds: float = 4.0,
) -> None:
    """Raise if ``mask_update(clip=secure_clip)`` would truncate DP noise.

    Call with the values you pass to :func:`privatize` and to
    ``fl.secagg.mask_update``; raises ``ValueError`` when the
    fixed-point range leaves fewer than ``tail_sds`` noise standard
    deviations of headroom above ``clip_norm``.
    """
    needed = clip_norm + tail_sds * noise_multiplier * clip_norm
    if secure_clip < needed:
        raise ValueError(
            f"secure-aggregation fixed-point clip {secure_clip} would "
            f"truncate DP noise (clip_norm={clip_norm}, "
            f"sigma={noise_multiplier * clip_norm:.4g}): need >= {needed:.4g} "
            f"({tail_sds} standard deviations of headroom); use "
            f"secure_clip_for(...) or raise mask_update's clip="
        )


def privatize(
    tree: Any,
    key: jax.Array,
    *,
    clip_norm: float,
    noise_multiplier: float,
) -> Any:
    """Clip to ``clip_norm`` and add N(0, (noise_multiplier·clip_norm)²).

    The standard deviation is per-coordinate: with every party clipped
    to the same sensitivity, the aggregate's noise matches the Gaussian
    mechanism at the chosen multiplier.  ``noise_multiplier=0`` is
    clipping only.
    """
    clipped, _ = clip_by_global_norm(tree, clip_norm)
    if noise_multiplier == 0.0:
        return clipped
    sigma = noise_multiplier * clip_norm
    leaves, treedef = jax.tree_util.tree_flatten(clipped)
    keys = jax.random.split(key, len(leaves))
    noised = [
        (
            leaf.astype(jnp.float32)
            + sigma * jax.random.normal(k, leaf.shape, jnp.float32)
        ).astype(leaf.dtype)
        for k, leaf in zip(keys, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)
