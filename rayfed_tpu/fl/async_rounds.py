"""Buffered asynchronous federated rounds with EXACT integer staleness
decay — the round barrier removed without giving up the bit-exactness
contracts the synchronous stack is built on.

The synchronous loop (fl.trainer / fl.streaming) admits one global
round clock: every party contributes to round ``r`` and the slowest
party sets the round's wall.  The quorum layer (fl.quorum) trims the
tail by CUTTING stragglers; this module keeps them.  Parties push a
staleness-tagged quantized delta whenever they finish local work; the
coordinator folds each arrival into a RUNNING donated-i32 code buffer
through the UNCHANGED :func:`fl.fedavg.quantized_accum_kernel` and
emits a new model **version** every K contributions (``buffer_k``) or
T seconds (``flush_s``) — FedBuff's buffered-async regime (Nguyen et
al., arXiv:2106.06639) run entirely in the compressed domain.

Exactness (why the buffer can fold arrivals in ANY order)
---------------------------------------------------------

A contribution coded on the version-``v`` grid arrives with staleness
``s = v_now − v``.  Staleness-decayed weighting is applied as an
INTEGER SHIFT::

    w_eff = w >> min(s, staleness_cap)

so the folded term stays ``w_eff · q`` with ``w_eff`` a non-negative
integer — exactly the contract of the i32 fold.  Integer adds commute
and associate with no rounding, hence for one version's contribution
set the running buffer holds ``Σ_p w_eff_p · q_p`` REGARDLESS of
arrival order, and the single fused rescale
(:func:`fl.fedavg.finalize_packed_quantized`) emits bytes identical to
a sorted-order refold of the same set through
:func:`fl.fedavg.packed_quantized_sum` at weights ``w_eff`` — the same
cutoff-refold contract the quorum layer pins one level up, now per
model version.  A multiplicative float decay (``w · α^s``) would break
both the exactness and the i32 overflow bound; the shift keeps the
headroom guard (:meth:`fl.quantize.QuantGrid.check_weight_headroom`)
sufficient as stated.

The staleness recurrence at per-party staleness
-----------------------------------------------

This is the asynchronous end of the unified staleness recurrence
derived in :mod:`fl.overlap` (one-round staleness: the pipelined
runner).  There, every party is exactly one round stale and the DGA
correction makes the corrected contribution's delta equal the party's
raw local displacement, so the round grid and the accelerated server
step both consume one-round-stale displacements.  Here staleness is
per-party and unbounded, so the correction moves from algebra to
weighting: a version-``v`` contribution decodes against the version-
``v`` reference it was coded on (every broadcast ships its grid, so
the codes are always attributable), re-codes onto the CURRENT grid
through the shared :class:`fl.quantize.RoundCodec`, and folds at the
shift-decayed weight.  The server step (fl.server_opt), when
configured, consumes the buffered mean exactly as the synchronous loop
does — the FedAC delayed-gradient analysis (arXiv:2006.08950) is what
bounds the staleness penalty the decay is tuned against.

Version-tagged wire contract
----------------------------

Broadcasts and contributions stamp the model version into ordinary
frame metadata under :data:`rayfed_tpu.transport.wire
.ASYNC_VERSION_KEY` (``TransportManager.send(version_tag=...)``) — a
new metadata KEY, not a frame-layout change, fingerprinted by
``tool/check_wire_format.py`` like every cross-party contract.  The
version-0 bootstrap needs no negotiation: every controller derives the
identical ``mode="abs"`` grid from the initial params it already
holds (:func:`bootstrap_grid` — same pure-numpy derivation as the
synchronous loop's grids), and every later grid rides the broadcast
payload itself.  Rosters ride epoch tags: a party's final push
(``fin``) retires it from the roster and bumps the epoch stamped on
subsequent broadcasts.

When NOT to go async (see docs/source/async_rounds.rst): homogeneous
fleets (the buffer only re-derives the synchronous round at extra
version churn), secure aggregation (pairwise masks are keyed by a
synchronous round tuple — no per-arrival fold can unmask), and
workloads needing every party represented in every emitted model
(async emission is a weighted SAMPLE of the fleet per version).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from rayfed_tpu import chaos, telemetry
from rayfed_tpu.fl import quantize as qz
from rayfed_tpu.fl.compression import PackedTree, PackSpec, pack_tree
from rayfed_tpu.fl.fedavg import (
    finalize_packed_quantized,
    quantized_accum_kernel,
)
from rayfed_tpu.fl.quantize import (
    QuantGrid,
    QuantizedPackedTree,
    RoundCodec,
    grid_descriptor,
    make_round_grid,
)

logger = logging.getLogger(__name__)

#: Shift cap: beyond this staleness every weight decays identically
#: (``w >> cap``) — and a unit weight has already decayed to zero at
#: shift 1, so the cap mostly bounds the grid-retention window.
DEFAULT_STALENESS_CAP = 8

#: Contributions buffered per emitted model version (FedBuff's K).
DEFAULT_BUFFER_K = 4

# Per-process counters surfaced by fed.metrics_snapshot() under the
# "async" section of metrics.METRICS_SCHEMA (the quorum/ring pattern:
# the driver lives per process, not on the transport).
ASYNC_STATS: Dict[str, Any] = {
    "versions_emitted": 0,
    "folds": 0,
    "buffer_occupancy": 0,
    "staleness_hist": {},
    "decay_shift_total": 0,
    "dropped_decayed_out": 0,
    "dropped_unretained": 0,
    "recoded_stale": 0,
}


def reset_async_stats() -> None:
    """Zero the per-process async counters (tests / bench sections)."""
    ASYNC_STATS.update(
        versions_emitted=0, folds=0, buffer_occupancy=0,
        staleness_hist={}, decay_shift_total=0, dropped_decayed_out=0,
        dropped_unretained=0, recoded_stale=0,
    )


def decay_weight(weight: int, staleness: int,
                 staleness_cap: int = DEFAULT_STALENESS_CAP) -> int:
    """The exact integer staleness decay: ``w >> min(s, cap)``.

    ONE producer for driver, tests and docs — the whole exactness
    argument rests on the decayed weight staying a non-negative
    integer, so the decay must never be reimplemented as a float
    multiply at a call site.
    """
    w = int(weight)
    s = int(staleness)
    if w < 0 or float(weight) != w:
        raise ValueError(
            f"compressed-domain folds need non-negative integral "
            f"weights (example counts), got {weight!r}"
        )
    if s < 0:
        raise ValueError(
            f"staleness is versions-behind, never negative (got {s}) — "
            f"a contribution cannot be coded against an unemitted model"
        )
    return w >> min(s, int(staleness_cap))


def bootstrap_grid(model_buf: Any, wire_dtype: str = "uint8",
                   chunk_elems: Optional[int] = None) -> QuantGrid:
    """The version-0 grid: ``mode="abs"`` over the initial params.

    Before the first version there is no observed delta to range a
    delta grid (the synchronous loop's bootstrap runs round 0
    unquantized instead — an async buffer cannot, the running fold IS
    integer).  An abs-mode grid over the initial model codes the
    version-0 contributions themselves; every controller derives it
    from the bit-identical initial params, so like every round grid the
    derivation IS the negotiation (fingerprint-checked on each frame).
    From version 1 on the coordinator rotates to delta grids ranged by
    the observed version delta, shipped on the broadcast payload.
    """
    if isinstance(model_buf, PackedTree):
        model_buf = model_buf.buf
    flat = np.asarray(model_buf).reshape(-1).astype(np.float32)
    if flat.size and float(flat.max() - flat.min()) == 0.0:
        # An all-constant init (all-zeros is the classic) ranges every
        # chunk to the eps floor: every version-0 contribution clips
        # to the constant, the first emitted delta is exactly zero,
        # and the zero-delta guard then reuses this grid forever — the
        # fleet is silently stuck at the init.  Loud, at derivation.
        raise ValueError(
            "bootstrap_grid: initial params are all-constant — the "
            "version-0 abs grid ranges over the initial value spread, "
            "so a constant init clips every contribution to itself "
            "(randomize the init, as real models do)"
        )
    return make_round_grid(
        flat, chunk_elems=chunk_elems, wire_dtype=wire_dtype,
        mode="abs",
    )


class AsyncBuffer:
    """The RUNNING compressed-domain fold for one model version.

    Holds a donated-i32 accumulator over the grid's padded block
    layout and folds each arrival with ONE call of the unchanged
    :func:`fl.fedavg.quantized_accum_kernel` (chunk = the whole padded
    buffer, offset 0 — the same donated widening multiply-add the
    streaming aggregator chains per chunk).  :meth:`finalize` is the
    same single fused rescale every synchronous topology ends in, so
    the emitted bytes are identical to a sorted-order
    :func:`fl.fedavg.packed_quantized_sum` refold of the folded
    ``(codes, w_eff)`` set — the buffered fold is order-free by
    integer arithmetic, not by tolerance.
    """

    __slots__ = ("grid", "ref", "staleness_cap", "_acc", "_kernel",
                 "_padded", "_template", "_count", "_total_w",
                 "staleness_hist", "decay_shift_total")

    def __init__(self, grid: QuantGrid, ref: Optional[np.ndarray],
                 template: PackedTree,
                 staleness_cap: int = DEFAULT_STALENESS_CAP) -> None:
        import jax.numpy as jnp

        self.staleness_cap = int(staleness_cap)
        # Tree skeleton for the finalized PackedTree (entries/treedef/
        # passthrough); the fold itself never looks at it.
        self._template = template
        self._padded = 0
        self._kernel = None
        self._acc = None
        self.grid = grid
        self.ref = None
        self.staleness_hist: Dict[int, int] = {}
        self.decay_shift_total = 0
        self._count = 0
        self._total_w = 0
        self.reset(grid, ref)
        del jnp  # imported eagerly so reset() never pays first-import

    @property
    def occupancy(self) -> int:
        """Contributions folded into the current (unemitted) version."""
        return self._count

    @property
    def total_weight(self) -> int:
        return self._total_w

    def reset(self, grid: QuantGrid, ref: Optional[np.ndarray]) -> None:
        """Start the next version's buffer on (possibly rotated) grid.

        Rotation never changes the packed layout — the padded
        accumulator and the cached kernel survive grid swaps; only the
        scales/zps/reference move.
        """
        import jax.numpy as jnp

        if self._acc is not None and (
            grid.total_elems != self.grid.total_elems
            or grid.chunk_elems != self.grid.chunk_elems
        ):
            raise ValueError(
                f"grid rotation changed the packed layout "
                f"({self.grid.total_elems}/{self.grid.chunk_elems} -> "
                f"{grid.total_elems}/{grid.chunk_elems}) — the running "
                f"buffer is per-model-layout; build a new AsyncBuffer "
                f"when the model structure changes"
            )
        self.grid = grid
        if ref is not None:
            ref = np.asarray(ref).reshape(-1).astype(np.float32)
            if int(ref.size) != grid.total_elems:
                raise ValueError(
                    f"reference has {ref.size} elements, grid covers "
                    f"{grid.total_elems}"
                )
        elif grid.mode == "delta":
            raise ValueError(
                "delta-mode grids fold codes of x - ref: pass the "
                "version's shared reference buffer"
            )
        self.ref = ref
        self._padded = grid.nblocks * grid.chunk_elems
        self._kernel = quantized_accum_kernel(
            self._padded, grid.wire_dtype
        )
        self._acc = jnp.zeros(self._padded, jnp.int32)
        self._count = 0
        self._total_w = 0
        self.staleness_hist = {}
        self.decay_shift_total = 0
        ASYNC_STATS["buffer_occupancy"] = 0

    def fold(self, qt: QuantizedPackedTree, weight: int = 1,
             staleness: int = 0) -> int:
        """Fold one arrival; returns the effective (decayed) weight.

        Returns 0 — and folds NOTHING — when the shift decays the
        weight away entirely (the contribution is too stale to move the
        average by even one integer count).  Raises when the codes were
        taken on a different grid: stale codes must re-code through the
        shared :class:`fl.quantize.RoundCodec` first (the coordinator
        driver does; see :func:`run_async_coordinator`).
        """
        import jax.numpy as jnp

        if not isinstance(qt, QuantizedPackedTree):
            raise TypeError(
                f"AsyncBuffer folds QuantizedPackedTree contributions, "
                f"got {type(qt).__name__}"
            )
        if qt.gmeta != self.grid.meta():
            raise ValueError(
                f"contribution was coded on a different grid "
                f"(fp={qt.gmeta.fp:#010x} vs "
                f"{self.grid.fingerprint():#010x}) — version-stale "
                f"codes re-code through the shared RoundCodec before "
                f"the fold"
            )
        shift = min(int(staleness), self.staleness_cap)
        w_eff = decay_weight(weight, staleness, self.staleness_cap)
        self.staleness_hist[shift] = self.staleness_hist.get(shift, 0) + 1
        hist = ASYNC_STATS["staleness_hist"]
        hist[shift] = hist.get(shift, 0) + 1
        if w_eff <= 0:
            ASYNC_STATS["dropped_decayed_out"] += 1
            return 0
        # Overflow guard BEFORE touching the accumulator: a rejected
        # fold must leave the buffer exactly as it was.
        self.grid.check_weight_headroom(self._total_w + w_eff)
        codes = np.asarray(qt.buf).reshape(-1)
        if codes.size != self.grid.total_elems:
            raise ValueError(
                f"contribution carries {codes.size} codes, grid covers "
                f"{self.grid.total_elems}"
            )
        if codes.size != self._padded:
            # Pad onto the canonical block grid; the finalize slices
            # back to total_elems, so the pad value never reaches the
            # output — zeros keep the padded adds trivially exact.
            padded = np.zeros(self._padded, codes.dtype)
            padded[: codes.size] = codes
            codes = padded
        self._acc = self._kernel(
            self._acc, jnp.asarray(codes), 0, w_eff
        )
        self._count += 1
        self._total_w += w_eff
        self.decay_shift_total += shift
        ASYNC_STATS["folds"] += 1
        ASYNC_STATS["buffer_occupancy"] = self._count
        ASYNC_STATS["decay_shift_total"] += shift
        return w_eff

    def finalize(self, out_dtype: Any = np.float32) -> PackedTree:
        """The buffered version's weighted mean — ONE fused rescale
        (:func:`fl.fedavg.finalize_packed_quantized`), byte-identical
        to the sorted-order ``packed_quantized_sum`` refold of the
        folded set.  The buffer stays live; call :meth:`reset` to
        start the next version."""
        if self._count == 0:
            raise ValueError(
                "finalize on an empty buffer — the weighted average of "
                "no contributions is undefined (emission is gated on "
                "occupancy for exactly this reason)"
            )
        buf = finalize_packed_quantized(
            self._acc, self.grid.scales, self.grid.zps,
            float(self._total_w), self.grid.total_elems,
            self.grid.chunk_elems, out_dtype, ref=self.ref,
        )
        tmpl = self._template
        spec = PackSpec(
            tmpl.spec.entries, tmpl.spec.treedef,
            np.dtype(out_dtype).name,
        )
        # finalize_packed_quantized consumed nothing (acc is not
        # donated there) — but the NEXT fold's donation would invalidate
        # the view finalize returned lazily; materialization happens at
        # reset() via the fresh zeros, so no copy is needed here.
        return PackedTree(buf, tmpl.passthrough, spec)


def _wrap_server_opt(server_opt: Any) -> Any:
    if server_opt is None or hasattr(server_opt, "step_fn"):
        return server_opt
    from rayfed_tpu.fl.server_opt import PackedServerOptimizer

    return PackedServerOptimizer(server_opt)


def run_async_coordinator(
    mgr: Any,
    party: str,
    members: Sequence[str],
    params: Any,
    *,
    cycles: Any,
    buffer_k: int = DEFAULT_BUFFER_K,
    flush_s: Optional[float] = None,
    wire_quant: str = "uint8",
    chunk_elems: Optional[int] = None,
    staleness_cap: int = DEFAULT_STALENESS_CAP,
    grid_retention: Optional[int] = None,
    server_opt: Any = None,
    stream: str = "async",
    timeout_s: Optional[float] = None,
    version_log: Optional[List[Dict[str, Any]]] = None,
    record_folds: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The buffered-async coordinator over a bare TransportManager.

    Parks one receive per active member and multiplexes arrivals
    through a queue; each arrival folds into the running
    :class:`AsyncBuffer` (re-coding through the shared
    :class:`fl.quantize.RoundCodec` when its version's grid has
    rotated), and a new model version emits every ``buffer_k``
    contributions or — evaluated at arrival time — ``flush_s`` seconds
    (T-second emission is an arrival-driven check on purpose: an empty
    buffer has nothing to emit, so a timer thread would only ever fire
    into the same gate).  The reply to each push carries the CURRENT
    model, its grid and its version (``version_tag`` frame metadata):
    the reply leg IS the version broadcast, so a party is never more
    than one push behind discovering a new version.

    ``cycles``: pushes expected per member (int, or dict keyed by
    member — heterogeneous counts are roster churn: a member's final
    push retires it and bumps the epoch tag).  ``grid_retention``: how
    many historical versions' (grid, reference) pairs stay decodable;
    older arrivals are dropped-with-counter (their shift-decayed
    weight is ≤ ``w >> staleness_cap`` anyway).  ``record_folds``
    (tests): appends ``{version, party, qt, weight, w_eff,
    staleness}`` per fold — the refold oracle's input.
    """
    import jax.numpy as jnp

    members = [str(m) for m in members]
    if isinstance(cycles, int):
        expected = {m: int(cycles) for m in members}
    else:
        expected = {m: int(cycles[m]) for m in members}
    total_pushes = sum(expected.values())
    retention = (
        int(grid_retention) if grid_retention is not None
        else int(staleness_cap) + 2
    )
    sopt = _wrap_server_opt(server_opt)

    tmpl = pack_tree(params, jnp.float32)
    model = np.asarray(tmpl.buf).astype(np.float32)
    grid0 = bootstrap_grid(model, wire_quant, chunk_elems)
    # version -> (grid, reference) for decode of version-stale codes.
    grids: Dict[int, Any] = {0: (grid0, None)}
    version = 0
    epoch = 0
    buf = AsyncBuffer(grid0, None, tmpl, staleness_cap=staleness_cap)
    last_emit = time.perf_counter()
    emitted_folds = 0

    arrivals: "queue.Queue" = queue.Queue()

    def _park(member: str, cycle: int) -> None:
        ref = mgr.recv(member, f"{stream}.up.{member}", str(cycle))
        ref.add_done_callback(
            lambda r, _m=member, _c=cycle: arrivals.put((_m, _c, r))
        )

    roster = {m for m in members if expected[m] > 0}
    for m in roster:
        _park(m, 0)

    def _emit_version() -> None:
        nonlocal version, model, last_emit, emitted_folds
        folds = buf.occupancy
        total_w = buf.total_weight
        hist = dict(buf.staleness_hist)
        shifts = buf.decay_shift_total
        with telemetry.span(
            "async.version", party=party, stream=stream,
            round=version + 1, epoch=epoch,
            detail={"folds": folds, "total_weight": total_w,
                    "decay_shift_total": shifts},
        ):
            agg = buf.finalize(np.float32)
            if sopt is not None:
                sopt.ensure(model)
                agg = sopt.step_fn(model)(agg)
                sopt.resync(model, np.asarray(agg.buf))
            new_model = np.asarray(agg.buf).astype(np.float32)
            delta = new_model - model
            if np.any(delta):
                new_grid = make_round_grid(
                    delta, chunk_elems=grid0.chunk_elems,
                    wire_dtype=wire_quant, mode="delta",
                    expand=qz.QUANT_DELTA_EXPAND,
                )
                new_ref: Optional[np.ndarray] = new_model
            else:
                # Degenerate no-movement version: keep the grid (and
                # its reference) — rotating onto an all-zero delta
                # range would produce a clip-everything grid.
                new_grid, new_ref = grids[version]
            version += 1
            grids[version] = (new_grid, new_ref)
            for old in [v for v in grids if v < version - retention]:
                del grids[old]
            model = new_model
            buf.reset(new_grid, new_ref)
        ASYNC_STATS["versions_emitted"] += 1
        emitted_folds += folds
        if version_log is not None:
            version_log.append({
                "version": version, "folds": folds,
                "total_weight": total_w, "staleness_hist": hist,
                "decay_shift_total": shifts,
                "model": model.copy(),
                # Wall-clock emission stamp: time-to-target-loss curves
                # (bench) read it; refold oracles ignore it.
                "t_wall": time.time(),
            })
        last_emit = time.perf_counter()

    processed = 0
    while processed < total_pushes:
        member, cycle, ref = arrivals.get()
        payload = ref.resolve(timeout_s)
        processed += 1
        qt = payload["qt"]
        v_from = int(payload["v"])
        weight = int(payload["weight"])
        staleness = version - v_from
        # Version rides the round tag (the async analogue of a round:
        # trace_report's per-round pages become per-version pages) and
        # the staleness attribution rides detail — tool/trace_report.py
        # aggregates it into the staleness report.  The detail dict is
        # filled in as the fold resolves (the span emits at exit).
        fold_detail: Dict[str, Any] = {
            "staleness": staleness, "cycle": cycle,
            "v_from": v_from, "weight": weight,
        }
        with telemetry.span(
            "async.fold", party=party, peer=member, stream=stream,
            round=version, epoch=epoch, detail=fold_detail,
        ):
            held = grids.get(v_from)
            if held is None:
                # Beyond the retention window the reference needed to
                # decode is gone; the shift-decayed weight out there is
                # negligible by construction — drop loudly.
                ASYNC_STATS["dropped_unretained"] += 1
                logger.warning(
                    "[%s] dropping contribution from %s coded at "
                    "version %d (current %d, retention %d)",
                    party, member, v_from, version, retention,
                )
                w_eff = 0
            else:
                if v_from != version:
                    g_old, ref_old = held
                    if qt.gmeta != g_old.meta():
                        raise ValueError(
                            f"contribution from {member} claims "
                            f"version {v_from} but its codes carry "
                            f"grid fp={qt.gmeta.fp:#010x}, version "
                            f"{v_from}'s grid is "
                            f"{g_old.fingerprint():#010x}"
                        )
                    decoded = qt.dequantize(np.float32, ref=ref_old)
                    codec = RoundCodec(buf.grid, buf.ref)
                    qt = codec.to_wire(decoded)
                    ASYNC_STATS["recoded_stale"] += 1
                    fold_detail["recoded"] = True
                w_eff = buf.fold(qt, weight, staleness)
                fold_detail["w_eff"] = w_eff
                if record_folds is not None:
                    record_folds.append({
                        "version": version, "party": member,
                        "qt": qt, "weight": weight, "w_eff": w_eff,
                        "staleness": staleness,
                    })
        now = time.perf_counter()
        if buf.occupancy and (
            buf.occupancy >= int(buffer_k)
            or (flush_s is not None and now - last_emit >= flush_s)
        ):
            _emit_version()
        cur_grid, _cur_ref = grids[version]
        mgr.send(
            member,
            {
                "v": version,
                "buf": model,
                "scales": cur_grid.scales,
                "zps": cur_grid.zps,
                "mode": cur_grid.mode,
                "epoch": epoch,
            },
            f"{stream}.dn.{member}", str(cycle),
            stream=stream, version_tag=version, epoch_tag=epoch,
            quant_meta=grid_descriptor(cur_grid),
        )
        if bool(payload.get("fin")) or cycle + 1 >= expected[member]:
            roster.discard(member)
            epoch += 1
            telemetry.event(
                "async.roster", party=party, peer=member,
                stream=stream, epoch=epoch, round=version,
            )
        else:
            _park(member, cycle + 1)

    # Residue: arrivals that landed after the last emission still owe
    # the fleet a version (every contribution reaches some model).
    if buf.occupancy:
        _emit_version()
    return {
        "w": model,
        "versions": version,
        "epoch": epoch,
        "folds": emitted_folds,
        "template": tmpl,
    }


def run_async_party(
    mgr: Any,
    party: str,
    coordinator: str,
    params: Any,
    local_step_fn: Callable[[str, PackedTree, int, int], PackedTree],
    *,
    cycles: int,
    weight: int = 1,
    wire_quant: str = "uint8",
    chunk_elems: Optional[int] = None,
    stream: str = "async",
    timeout_s: Optional[float] = None,
) -> Dict[str, Any]:
    """One virtual party's push loop (no round barrier anywhere).

    Each cycle: run ``local_step_fn(party, packed_model, version,
    cycle) -> PackedTree`` (its measured duration feeds the chaos
    ``local_step`` hook — a seeded ``local_slowdown`` schedule turns a
    homogeneous in-process fleet into a deterministic 2-10x straggler
    spread), code the result on the CURRENT version's grid through the
    party's error-feedback :class:`fl.quantize.RoundCodec`, push it
    version-tagged, and adopt whatever model version the reply carries.
    The party never waits for any other party — only for its own
    reply, which the coordinator sends immediately after folding.
    """
    import jax.numpy as jnp

    tmpl = pack_tree(params, jnp.float32)
    model = np.asarray(tmpl.buf).astype(np.float32)
    grid = bootstrap_grid(model, wire_quant, chunk_elems)
    gref: Optional[np.ndarray] = None
    version = 0
    f32_spec = PackSpec(tmpl.spec.entries, tmpl.spec.treedef, "float32")
    packed = PackedTree(model, tmpl.passthrough, f32_spec)
    scope = f"{stream}.{party}"

    for c in range(int(cycles)):
        t_wall = time.time()
        t0 = time.perf_counter()
        contrib = local_step_fn(party, packed, version, c)
        dur = time.perf_counter() - t0
        telemetry.emit(
            "async.local", t_start=t_wall, dur_s=dur, party=party,
            stream=stream, round=version, detail={"cycle": c},
        )
        # The chaos hook may SLEEP here (local_slowdown multiplier over
        # the measured baseline) — that stall is exactly the
        # heterogeneous-device time the async buffer absorbs.
        chaos.fire(
            "local_step", party, version=version, cycle=c,
            baseline_s=dur,
        )
        codec = RoundCodec(grid, gref, scope=scope)
        qt = codec.to_wire(contrib)
        with telemetry.span(
            "async.cycle", party=party, stream=stream,
            round=version, detail={"cycle": c},
        ):
            mgr.send(
                coordinator,
                {
                    "v": version,
                    "cycle": c,
                    "weight": int(weight),
                    "fin": c + 1 >= int(cycles),
                    "qt": qt,
                },
                f"{stream}.up.{party}", str(c),
                stream=stream, version_tag=version,
                quant_meta=codec.descriptor,
            )
            reply = mgr.recv(
                coordinator, f"{stream}.dn.{party}", str(c)
            ).resolve(timeout_s)
        # The fold always lands (the coordinator replies after it) —
        # commit the pending error-feedback residual.
        codec.commit()
        rv = int(reply["v"])
        if rv != version:
            version = rv
            model = np.asarray(reply["buf"]).astype(np.float32)
            mode = str(reply["mode"])
            grid = QuantGrid(
                np.asarray(reply["scales"]), np.asarray(reply["zps"]),
                grid.chunk_elems, grid.total_elems, wire_quant, mode,
            )
            gref = model if mode == "delta" else None
            packed = PackedTree(model, tmpl.passthrough, f32_spec)
    return {"w": model, "version": version}


def run_async_fleet(
    parties: Sequence[str],
    params: Any,
    local_step_fn: Callable[[str, PackedTree, int, int], PackedTree],
    *,
    cycles: Any = 4,
    weights: Optional[Dict[str, int]] = None,
    buffer_k: int = DEFAULT_BUFFER_K,
    flush_s: Optional[float] = None,
    wire_quant: str = "uint8",
    chunk_elems: Optional[int] = None,
    staleness_cap: int = DEFAULT_STALENESS_CAP,
    grid_retention: Optional[int] = None,
    server_opt: Any = None,
    stream: str = "async",
    timeout_s: float = 300.0,
    version_log: Optional[List[Dict[str, Any]]] = None,
    record_folds: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """In-process virtual-party harness: N loopback TransportManagers
    (local-link auto-upgrade), one thread per party, the first name
    coordinating — the PR 16/17 bench topology, packaged so tests and
    ``bench.py --smoke`` drive the identical fleet instead of two
    hand-rolled copies.  No party subprocesses, by design: the tier-1
    budget rides in-process fleets (ISSUE 20 satellite 6).
    """
    import socket

    from rayfed_tpu.config import ClusterConfig, JobConfig, PartyConfig
    from rayfed_tpu.transport.manager import TransportManager

    parties = [str(p) for p in parties]
    if len(parties) < 2:
        raise ValueError("an async fleet needs a coordinator + >= 1 member")
    coordinator, members = parties[0], parties[1:]

    socks = [socket.socket() for _ in parties]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = {p: s.getsockname()[1] for p, s in zip(parties, socks)}
    for s in socks:
        s.close()

    def _mk(p: str) -> Any:
        cc = ClusterConfig(
            parties={
                q: PartyConfig.from_dict(
                    {"address": f"127.0.0.1:{ports[q]}"}
                )
                for q in parties
            },
            current_party=p,
        )
        return TransportManager(
            cc,
            JobConfig(
                device_put_received=False,
                zero_copy_host_arrays=True,
                local_link="auto",
            ),
        )

    mgrs = {p: _mk(p) for p in parties}
    results: Dict[str, Any] = {}
    errors: Dict[str, BaseException] = {}
    try:
        for m in mgrs.values():
            m.start()

        def _coord() -> None:
            try:
                results[coordinator] = run_async_coordinator(
                    mgrs[coordinator], coordinator, members, params,
                    cycles=cycles, buffer_k=buffer_k, flush_s=flush_s,
                    wire_quant=wire_quant, chunk_elems=chunk_elems,
                    staleness_cap=staleness_cap,
                    grid_retention=grid_retention,
                    server_opt=server_opt, stream=stream,
                    timeout_s=timeout_s, version_log=version_log,
                    record_folds=record_folds,
                )
            # fedlint: disable=FED004 — transferred, not swallowed: the parent re-raises from the errors dict after join
            except BaseException as e:
                errors[coordinator] = e

        def _member(p: str) -> None:
            try:
                n = cycles if isinstance(cycles, int) else cycles[p]
                results[p] = run_async_party(
                    mgrs[p], p, coordinator, params, local_step_fn,
                    cycles=n,
                    weight=(weights or {}).get(p, 1),
                    wire_quant=wire_quant, chunk_elems=chunk_elems,
                    stream=stream, timeout_s=timeout_s,
                )
            # fedlint: disable=FED004 — transferred, not swallowed: the parent re-raises from the errors dict after join
            except BaseException as e:
                errors[p] = e

        threads = [threading.Thread(target=_coord, daemon=True)] + [
            threading.Thread(target=_member, args=(p,), daemon=True)
            for p in members
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout_s)
        if errors:
            raise RuntimeError(
                f"async fleet failed: "
                f"{ {p: repr(e) for p, e in errors.items()} }"
            )
        if any(t.is_alive() for t in threads):
            raise TimeoutError(
                f"async fleet did not complete within {timeout_s}s"
            )
    finally:
        for m in mgrs.values():
            try:
                m.stop()
            except Exception:  # pragma: no cover
                logger.exception("async fleet manager stop failed")
    out = dict(results[coordinator])
    out["party_results"] = {p: results[p] for p in members}
    return out
