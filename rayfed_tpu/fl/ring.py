"""Chunk-striped ring aggregation: reduce-scatter + all-gather FedAvg.

The coordinator topology (``fl.aggregate`` / ``fl.streaming``) funnels
every contribution into one party: the coordinator moves
``2·(N-1)·|model|`` bytes per round while every other party moves
``~|model|`` — bench r05 put ~620 ms of coordinator-serialized wire time
on a 4-party ResNet round.  Here the round is a **reduce-scatter
followed by an all-gather** over the sorted party ring (the weight-
update sharding of arXiv:2004.13336 applied to the cross-silo wire):

1. **Stripe layout.**  The packed buffer's canonical chunk grid
   (:func:`rayfed_tpu.fl.fedavg.packed_block_grid` — the transport's
   4 MB chunks) is striped round-robin across the N ring parties
   (:func:`~rayfed_tpu.fl.fedavg.packed_stripe_schedule`): block ``b``
   belongs to stripe ``b % N``, stripe ``k`` is owned by the ring's
   ``k``-th party.  The schedule is derived independently by every
   party from the same constants — it is part of the cross-party
   contract, like the wire format.

2. **Reduce-scatter.**  Every party slices its own packed contribution
   into per-stripe compacted payloads and pushes each to that stripe's
   owner on a stable delta stream (``{stream}/rs``) — round-over-round
   unchanged chunks never cross the wire (wire v3 per-chunk CRCs +
   delta bitmap).  Each owner folds the arriving stripe blocks into a
   donated f32 accumulator with the **party-order-per-block schedule**
   (:class:`rayfed_tpu.fl.streaming.StripeAggregator`), carrying the
   (Σ weight·payload, Σ weight) pair, and finalizes with the shared
   one-fused-divide (:func:`~rayfed_tpu.fl.fedavg
   .finalize_packed_stripe`).  Both the fold chain and the finalize are
   elementwise, so each reduced stripe is byte-identical to the same
   element range of ``packed_weighted_sum`` — and therefore of the
   coordinator path — regardless of arrival order.

3. **All-gather.**  Each owner sends its reduced stripe to its ring
   successor; every party forwards what it receives onward until the
   stripe has visited the whole ring (N-1 hops), also on per-stripe
   delta streams.  Every party assembles the N stripes back onto the
   chunk grid into the identical result ``PackedTree``.

Per-party traffic is ``~2·|model|`` **independent of N** (vs the hub's
``2·(N-1)·|model|`` at the coordinator): the difference between 4
parties and 40.

**Commit ring.**  A failure anywhere must make *every* party abandon
the round together (a half-fallen-back cluster desyncs its seq-id
streams).  Failures propagate two ways: the failing party poisons every
rendezvous key it was going to produce (reusing the transport's poison
+ frame-abort machinery), and a 2-pass token ring (commit → release)
runs after assembly so a party that already has all its bytes still
learns that someone else didn't.  As with any atomic commit, a crash
inside the tiny release pass itself can still strand successors — that
residual window is bounded by two token hops and backstopped by the
recv deadline; the bulk phases (the multi-MB transfers, where failures
actually happen) are fully covered.

``run_fedavg_rounds(mode="ring")`` drives this per round and falls back
to the coordinator topology (``streaming_aggregate``) for the round
when the ring aborts — same bytes-identical result, no lost round.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

# Version of the ring stripe manifest ("rsm" sideband leaf) — bump when
# make_stripe_meta's schema OR SEMANTICS change.  Fingerprinted
# (together with the schema) by tool/check_wire_format.py: stripe
# payloads are a cross-party contract layered on the ordinary payload
# manifest, so drift must be deliberate.  The frame layout itself is
# untouched.
# History: 1 = original; 2 = optional "qg" field (the shared
# quantization grid's fingerprint on compressed-domain "rs" stripes —
# receivers cross-check it before folding integer codes); 3 = "ag"
# stripes of a compressed-domain round carry grid CODES (dt = the
# grid's integer dtype, "qg" present) instead of f32 — the gather hop
# is coded on the shared round grid (see ring_aggregate's quant docs).
RING_STRIPE_VERSION = 3

# Module-level round counters (mirrors rayfed_tpu.metrics' style of
# cheap global accounting): the trainer's fallback path and tests read
# these to assert a ring round actually completed / aborted.
RING_STATS: Dict[str, int] = {
    "rounds_completed": 0,
    "rounds_aborted": 0,
    "fallback_rounds": 0,
}

# Test-only fault injection: when set, called with the phase name
# ("local", "rs", "reduce", "ag", "commit") at each step of the member
# flow.  Raising from the hook simulates a mid-round failure at exactly
# that phase (the mid-round peer-death tests drive the fallback path
# through this).
_fault_hook: Optional[Callable[[str], None]] = None


def _maybe_fault(phase: str) -> None:
    if _fault_hook is not None:
        _fault_hook(phase)


# Seq ids one ring_aggregate call consumes — callers pre-allocating ids
# for an off-main-thread call (fl.overlap's comms lane) draw exactly
# this many from runtime.next_seq_id() in program order.
RING_SEQ_IDS = 5


class RingRoundError(RuntimeError):
    """A ring round aborted (peer death, wire failure, poisoned hop).

    The round's contributions are still intact on their owners —
    re-aggregate them over the coordinator topology
    (``run_fedavg_rounds(mode="ring")`` does exactly that).
    """


def make_stripe_meta(
    stripe: int,
    n_stripes: int,
    nblocks: int,
    total_elems: int,
    dtype: str,
    phase: str,
    qgrid_fp: Optional[int] = None,
) -> Dict[str, Any]:
    """The ``rsm`` sideband of a stripe payload — single producer of its
    schema (``tool/check_wire_format.py`` fingerprints it).

    ``phase`` is ``"rs"`` (a raw stripe contribution) or ``"ag"`` (a
    reduced stripe on the gather ring).  Receivers cross-check it
    against their independently derived schedule so a mis-wired payload
    fails loudly instead of folding into the wrong offsets.

    ``qgrid_fp`` (v2, compressed-domain rounds): the shared
    quantization grid's fingerprint — "rs" stripes carry integer codes
    whose meaning IS the grid, so a receiver folding them into its i32
    accumulator first proves both ends derived the identical grid.
    """
    rsm = {
        "v": RING_STRIPE_VERSION,
        "s": int(stripe),
        "n": int(n_stripes),
        "nb": int(nblocks),
        "el": int(total_elems),
        "dt": str(dtype),
        "ph": str(phase),
    }
    if qgrid_fp is not None:
        rsm["qg"] = int(qgrid_fp)
    return rsm


def _stripe_slice(buf: np.ndarray, blocks: Sequence[int], chunk_elems: int,
                  total_elems: int) -> np.ndarray:
    """Compact the stripe's blocks out of the packed buffer, in
    ascending block order (the order the fold schedule assumes)."""
    parts = [
        buf[b * chunk_elems : min((b + 1) * chunk_elems, total_elems)]
        for b in blocks
    ]
    if not parts:
        return np.empty(0, buf.dtype)
    if len(parts) == 1:
        return np.ascontiguousarray(parts[0])
    return np.concatenate(parts)


def _stripe_elems(blocks: Sequence[int], chunk_elems: int, nblocks: int,
                  total_elems: int) -> int:
    n = len(blocks) * chunk_elems
    if blocks and blocks[-1] == nblocks - 1:
        n -= nblocks * chunk_elems - total_elems  # short tail block
    return n


def code_gather_stripe(
    stripe, ref_slice, scales, zps, chunk_elems: int, wire_dtype: str
) -> np.ndarray:
    """Code a finalized f32 stripe onto the SHARED round grid's rows —
    the quantized ring's gather-hop coding (ROADMAP 2a: the
    reduce-scatter was already integer; this closes the f32 gather).

    Mirrors the coordinator topology's ``quantize_downlink``: the
    finalized stripe is the round's OUTPUT, so its coding error is the
    same downlink-class error every quantized broadcast already
    carries — and because the round grid is shared and the coding is
    block-local (the same fused kernels ``fl.quantize`` compiles for
    the full buffer, applied to the stripe's rows), every controller
    decodes the identical bytes, and the assembled ring result equals
    the full-buffer recode of the exact aggregate
    (``quantize_packed(exact, grid, ref).dequantize(...)``) bit for
    bit.  The stripe OWNER substitutes the decoded codes for its own
    stripe too, so ring parties byte-agree by construction.
    """
    import jax.numpy as jnp

    from rayfed_tpu.fl.quantize import _quantize_kernel

    arr = np.asarray(stripe, np.float32).reshape(-1)
    se = int(arr.size)
    with_ref = ref_slice is not None
    ref = (
        np.asarray(ref_slice, np.float32).reshape(-1)
        if with_ref else jnp.zeros(0, jnp.float32)
    )
    qbuf, _ = _quantize_kernel(
        int(chunk_elems), se, str(wire_dtype), with_ref
    )(arr, ref, np.asarray(scales, np.float32),
      np.asarray(zps, np.float32), jnp.zeros(se, jnp.float32))
    return np.asarray(qbuf)


def decode_gather_stripe(
    codes, ref_slice, scales, zps, chunk_elems: int, out_dtype
) -> np.ndarray:
    """Decode a gather-hop stripe's grid codes back to the output dtype
    — the receiver half of :func:`code_gather_stripe` (identical on
    every controller: shared grid rows + shared reference slice)."""
    import jax.numpy as jnp

    from rayfed_tpu.fl.quantize import _dequantize_kernel

    arr = np.asarray(codes).reshape(-1)
    se = int(arr.size)
    with_ref = ref_slice is not None
    ref = (
        np.asarray(ref_slice, np.float32).reshape(-1)
        if with_ref else jnp.zeros(0, jnp.float32)
    )
    out = _dequantize_kernel(
        int(chunk_elems), se, str(arr.dtype), np.dtype(out_dtype).name,
        with_ref,
    )(arr, ref, np.asarray(scales, np.float32),
      np.asarray(zps, np.float32))
    return np.asarray(out)


def _check_meta(meta_json: str, want: Dict[str, Any]) -> None:
    # "rsm", not "meta": this is the ring stripe manifest (a payload-
    # level contract fingerprinted via ring_stripe_schema), NOT frame
    # metadata — fedlint FED006 polices literal keys on the latter.
    rsm = json.loads(meta_json)
    if rsm.get("v", 0) > RING_STRIPE_VERSION:
        raise ValueError(
            f"stripe payload uses ring manifest v{rsm.get('v')}; this "
            f"party understands up to v{RING_STRIPE_VERSION}"
        )
    for key, expect in want.items():
        if rsm.get(key) != expect:
            raise ValueError(
                f"stripe manifest mismatch: {key}={rsm.get(key)!r}, "
                f"expected {expect!r} — ring peers disagree on the "
                f"stripe schedule"
            )


def ring_aggregate(
    fed_objects: Sequence[Any],
    weights: Optional[Sequence[float]] = None,
    *,
    stream: str = "ring",
    timeout: Optional[float] = None,
    out_dtype: Any = None,
    chunk_elems: Optional[int] = None,
    seq_ids: Optional[Sequence[int]] = None,
    round_tag: Optional[int] = None,
    timings: Optional[Dict[str, float]] = None,
    expect_parties: Optional[Sequence[str]] = None,
    quant: Optional[Any] = None,
    quant_ref: Optional[Any] = None,
    quant_scope: Optional[str] = None,
) -> Any:
    """FedAvg round over the chunk-striped ring (see module docstring).

    Drop-in for ``streaming_aggregate`` when the contributions are
    PackedTrees with one contribution per party: every controller calls
    it at the same program point with the same arguments.  Returns the
    averaged PackedTree on every party — byte-identical to
    ``packed_weighted_sum(values, weights)`` over the same contribution
    order, and therefore to the coordinator topology.

    ``stream`` scopes the delta caches (keep it constant across
    rounds); ``out_dtype`` as in ``streaming_aggregate`` (keep f32 for
    server optimizers / error feedback).  ``chunk_elems`` overrides the
    canonical grid granularity — every controller must pass the same
    value (tests use it to stripe small payloads).  Aborted rounds
    raise :class:`RingRoundError` on **every** controller (poison
    cascade + commit ring) so callers can fall back in lockstep.

    ``seq_ids``: :data:`RING_SEQ_IDS` pre-allocated rendezvous ids (in
    ``next_seq_id`` order).  Default (None) allocates them here; a call
    dispatched to a background lane (:mod:`rayfed_tpu.fl.overlap`) MUST
    pass main-thread-drawn ids — see
    :func:`~rayfed_tpu.fl.streaming.streaming_aggregate`.  ``round_tag``
    stamps every frame of the round with the round index
    (``wire.ROUND_TAG_KEY``).  ``timings`` (optional dict) receives
    ``push_s`` (reduce-scatter pushes ACKed) and ``agg_s`` (whole-call
    wall).

    ``quant``: the round's shared
    :class:`~rayfed_tpu.fl.quantize.QuantGrid` — the reduce-scatter
    runs **in the compressed domain**: each party quantizes its
    contribution onto the grid (pre-quantized contributions pass a
    fingerprint check), stripe payloads carry integer codes (half the
    bf16 bytes) with the grid fingerprint in their ``rsm`` manifest,
    and each stripe owner folds codes into a donated i32 accumulator
    with ONE fused rescale at finalize
    (:class:`~rayfed_tpu.fl.streaming.StripeAggregator` integer path).
    The all-gather hop is coded on the SAME shared round grid
    (:func:`code_gather_stripe` — each owner ships its finalized
    stripe as grid codes, relays forward the codes, and every party
    *owner included* assembles the decoded codes), so BOTH halves of
    the ring round ride integer bytes.  The gather coding is the
    ring's analogue of the coordinator path's quantized downlink: the
    finalized stripes are the round's OUTPUT, so the (tiny,
    grid-step-bounded) coding error is the same downlink-class error
    every quantized broadcast already carries — and because the grid
    is shared and coding is block-local, the assembled result is
    byte-identical on every controller and equals the full-buffer
    recode of the exact aggregate:
    ``quantize_packed(packed_quantized_sum(...), grid,
    ref).dequantize(...)``.  ``quant_ref``: the round's
    shared reference buffer for ``mode="delta"`` grids (parties code
    ``update − ref``; each stripe owner's finalize adds back its
    compacted reference slice).  ``out_dtype`` defaults to f32.
    ``quant_scope`` keys the per-process error-feedback
    residual exactly as in ``streaming_aggregate`` — committed only
    when the round lands, so the coordinator fallback re-quantizes the
    SAME update with the SAME residual after a ring abort.

    ``expect_parties``: the controllers expected to be LIVE this round
    (default: the whole cluster config).  Elastic-membership callers
    (``fl.quorum``) pass the current roster so a departed/dead party is
    not treated as a non-member controller owed the result broadcast —
    a checked send to a corpse would otherwise abort every ring round
    after churn.  Must be identical on every controller (it is: the
    roster is announcement-driven).
    """
    from rayfed_tpu.fed_object import FedObject
    from rayfed_tpu.fl.fedavg import (
        _check_weights,
        packed_block_grid,
        packed_stripe_schedule,
    )
    from rayfed_tpu.fl.streaming import DEFAULT_CHUNK_ELEMS
    from rayfed_tpu.runtime import get_runtime

    runtime = get_runtime()
    objs = list(fed_objects)
    if not objs:
        raise ValueError("ring_aggregate needs at least one contribution")
    for obj in objs:
        if not isinstance(obj, FedObject):
            raise TypeError(
                "ring_aggregate consumes FedObjects (party-owned "
                f"contributions), got {type(obj).__name__}"
            )
    owners = [obj.get_party() for obj in objs]
    if len(set(owners)) != len(owners):
        raise ValueError(
            "ring_aggregate needs exactly one contribution per party "
            f"(owners: {owners}) — aggregate duplicates locally first"
        )
    if weights is not None:
        if len(weights) != len(objs):
            raise ValueError(
                f"{len(weights)} weights for {len(objs)} contributions"
            )
        weights = [float(w) for w in weights]
        total_w = _check_weights(weights)
    else:
        total_w = float(len(objs))

    # The ring: contribution owners in sorted order.  Stripe k is owned
    # by ring[k]; the FOLD order stays the fed_objects order (the same
    # order the coordinator path reduces in), which need not equal ring
    # order — idx_of maps between the two.
    ring = sorted(owners)
    n = len(ring)
    idx_of = {obj.get_party(): i for i, obj in enumerate(objs)}

    # Seq ids — allocated unconditionally and identically on every
    # controller (success, abort and non-member paths all consume the
    # same five), preserving the rendezvous determinism contract.
    if seq_ids is None:
        rs_id = runtime.next_seq_id()
        ag_id = runtime.next_seq_id()
        commit_id = runtime.next_seq_id()
        release_id = runtime.next_seq_id()
        nm_id = runtime.next_seq_id()
    else:
        rs_id, ag_id, commit_id, release_id, nm_id = seq_ids
    import time as _time

    from rayfed_tpu import telemetry as _telemetry

    t_call0 = _time.perf_counter()
    t_mark = t_call0
    me = runtime.party
    # Flight-recorder ring phase boundaries (reduce_scatter /
    # all_gather / commit).  Disarmed: a bare perf_counter read per
    # phase; armed: a ring append — never I/O.
    _phase_span = _telemetry.phase_spanner(
        "ring", round=round_tag, party=me,
    )

    backstop = (
        timeout if timeout is not None
        else runtime.job_config.recv_backstop_s
    )
    parties = (
        list(expect_parties) if expect_parties is not None
        else list(runtime.cluster_config.parties)
    )
    non_members = [p for p in parties if p not in set(ring)]

    from rayfed_tpu.proxy import (
        recv_on_runtime,
        send_many_on_runtime,
        send_on_runtime,
    )

    if me not in idx_of:
        # Non-member controller (its party contributes nothing this
        # round): the first ring party broadcasts the assembled result
        # before its commit, and a release token after the commit ring
        # ran — consuming BOTH keeps this controller's success/abort
        # decision in lockstep with the members'.
        try:
            ref = recv_on_runtime(runtime, ring[0], nm_id, nm_id)
            result = ref.resolve(timeout=backstop)
            recv_on_runtime(
                runtime, ring[0], f"{release_id}.nm", release_id
            ).resolve(timeout=backstop)
            RING_STATS["rounds_completed"] += 1
            return result
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as exc:
            RING_STATS["rounds_aborted"] += 1
            raise RingRoundError(
                f"ring round broadcast from {ring[0]!r} failed: {exc!r}"
            ) from exc

    from rayfed_tpu.transport.manager import ring_neighbors

    transport = runtime.transport
    m = ring.index(me)
    my_idx = idx_of[me]
    pred, succ = ring_neighbors(ring, me)
    chunk_elems = (
        int(chunk_elems) if chunk_elems else DEFAULT_CHUNK_ELEMS
    )

    def _broadcast_non_members(result) -> None:
        """Result to non-member controllers — checked, so a failed
        broadcast aborts the round instead of leaving them parked."""
        refs = send_many_on_runtime(
            runtime, non_members, result, nm_id, nm_id,
            stream=f"{stream}/nm", round_tag=round_tag,
        )
        for p, ref in refs.items():
            if not ref.resolve(timeout=backstop):
                raise RingRoundError(
                    f"result broadcast to non-member {p!r} failed"
                )

    def _release_non_members() -> None:
        """Post-commit release tokens (tiny).  Failures here are the
        same residual commit-window class as a member dying inside the
        release pass: the non-member aborts at its backstop — log, but
        the members' round already committed."""
        refs = send_many_on_runtime(
            runtime, non_members, {"ok": 1}, f"{release_id}.nm",
            release_id, round_tag=round_tag,
        )
        for p, ref in refs.items():
            if not ref.resolve(timeout=backstop):  # pragma: no cover
                logger.warning(
                    "[%s] non-member release token to %s failed",
                    me, p,
                )

    # Compressed-domain plumbing: ONE shared sender-side codec
    # discipline (fl.quantize.RoundCodec — grid-fingerprint check + EF
    # two-phase commit, identical across streaming/ring/quorum, so the
    # ring-abort → coordinator-fallback path re-quantizes with the
    # SAME residual by construction).  No-op when quant is None.
    from rayfed_tpu.fl.quantize import RoundCodec

    codec = RoundCodec(quant, quant_ref, quant_scope)
    qref = codec.ref
    q_descriptor = codec.descriptor
    _to_wire = codec.to_wire
    _quant_commit = codec.commit
    _quant_rollback = codec.rollback

    if n == 1:
        # Degenerate single-party ring: reduce locally with the same
        # fused chain; still serve any non-member controllers.
        from rayfed_tpu.fl.fedavg import (
            packed_quantized_sum,
            packed_weighted_sum,
        )

        try:
            value = objs[0].get_local_ref().resolve(timeout=backstop)
            if quant is not None:
                result = packed_quantized_sum(
                    [_to_wire(value)], weights, out_dtype=out_dtype,
                    ref=qref,
                )
            else:
                result = packed_weighted_sum(
                    [value], weights, out_dtype=out_dtype
                )
            if non_members:
                _broadcast_non_members(result)
                _release_non_members()
        except BaseException as exc:
            _poison_ring_edges(
                runtime, exc, ring=ring, m=0, my_idx=my_idx,
                rs_id=rs_id, ag_id=ag_id, commit_id=commit_id,
                release_id=release_id, nm_id=nm_id,
                non_members=non_members,
            )
            # Same contract as the main path: the poison unparks any
            # non-member controllers, but an interrupt must stop the
            # caller unwrapped.
            _quant_rollback()
            if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                raise
            RING_STATS["rounds_aborted"] += 1
            if isinstance(exc, RingRoundError):
                raise
            raise RingRoundError(f"ring round aborted: {exc!r}") from exc
        _quant_commit()
        RING_STATS["rounds_completed"] += 1
        return result

    # Everything below may touch the wire: failures poison every key
    # this party was going to produce, then surface as RingRoundError.
    pending_cancels: List[tuple] = []
    agg = None
    try:
        _maybe_fault("local")
        my_packed = objs[my_idx].get_local_ref().resolve(timeout=backstop)
        from rayfed_tpu.fl.compression import PackedTree, PackSpec

        if not isinstance(my_packed, PackedTree):
            raise TypeError(
                "ring_aggregate consumes PackedTree contributions, got "
                f"{type(my_packed).__name__} — produce updates with "
                "fl.compress(tree, packed=True)"
            )
        if quant is not None:
            if int(chunk_elems) != quant.chunk_elems:
                raise ValueError(
                    f"ring chunk grid ({chunk_elems} elems) must match "
                    f"the quantization grid ({quant.chunk_elems}) — "
                    f"both ARE the canonical packed_block_grid chunking"
                )
            my_packed = _to_wire(my_packed)
        buf = np.asarray(my_packed.buf).reshape(-1)
        if buf.size == 0:
            raise ValueError(
                "ring_aggregate needs float leaves to stripe; use "
                "fl.aggregate for passthrough-only trees"
            )
        wire_dt = buf.dtype
        total_elems = int(buf.size)
        nblocks = packed_block_grid(total_elems, chunk_elems)
        stripes = packed_stripe_schedule(nblocks, n)
        # Compressed-domain output defaults to f32 — what every party
        # RETURNS.  (The gather hop re-codes the finalized stripes on
        # the shared round grid as a pure wire encoding — see the
        # all-gather phase below — but every controller decodes back
        # to this dtype, owner included.)
        out_dt = (
            np.dtype(out_dtype) if out_dtype is not None
            else (np.dtype(np.float32) if quant is not None else wire_dt)
        )
        q_fp = None if quant is None else quant.fingerprint()

        def elems(k: int) -> int:
            return _stripe_elems(
                stripes[k], chunk_elems, nblocks, total_elems
            )

        # -- reduce-scatter: my contribution out, my stripe folded in --
        my_stripe_elems = elems(m)
        if my_stripe_elems:
            rs_want = {
                "s": m, "n": n, "nb": nblocks, "el": total_elems,
                "dt": wire_dt.name, "ph": "rs",
            }
            if q_fp is not None:
                # Integer codes mean nothing without the grid: prove
                # both ends derived the identical one before any fold.
                rs_want["qg"] = q_fp
            agg = _make_stripe_agg(
                runtime, len(objs), weights,
                out_dt.name if quant is not None else out_dtype,
                my_stripe_elems,
                chunk_elems, label=f"stripe {m}",
                meta_check=lambda v: _check_meta(v, rs_want),
                quant=quant, quant_blocks=stripes[m],
                # This owner's stripe-compacted slice of the shared
                # reference — its finalize adds back exactly the
                # elements its blocks cover.
                quant_ref=(
                    None if qref is None else _stripe_slice(
                        qref, stripes[m], chunk_elems, total_elems
                    )
                ),
            )
            entries = []
            for i, obj in enumerate(objs):
                if i == my_idx:
                    continue
                entries.append(
                    (obj.get_party(), f"{rs_id}.rs.{i}.{m}", rs_id,
                     agg.sink(i))
                )
                pending_cancels.append((f"{rs_id}.rs.{i}.{m}", rs_id))
            # One loop hop demuxes all N-1 contribution streams.
            transport.recv_stream_many(entries)

        _maybe_fault("rs")
        rs_refs = []
        for k in range(n):
            if k == m or not elems(k):
                continue
            payload: Dict[str, Any] = {
                "data": _stripe_slice(
                    buf, stripes[k], chunk_elems, total_elems
                ),
                "rsm": json.dumps(
                    make_stripe_meta(
                        k, n, nblocks, total_elems, wire_dt.name, "rs",
                        qgrid_fp=q_fp,
                    ),
                    sort_keys=True,
                ),
            }
            if k == 0 and my_packed.passthrough:
                # Non-float leaves ride to the first stripe's owner,
                # which reduces them once and ships the result with its
                # gathered stripe.
                payload["pt"] = tuple(my_packed.passthrough)
            rs_refs.append(
                (
                    ring[k],
                    f"{rs_id}.rs.{my_idx}.{k}",
                    send_on_runtime(
                        runtime, ring[k], payload,
                        f"{rs_id}.rs.{my_idx}.{k}", rs_id,
                        stream=f"{stream}/rs", round_tag=round_tag,
                        quant_meta=q_descriptor,
                    ),
                )
            )
        if my_stripe_elems:
            agg.add_local(
                my_idx,
                _stripe_slice(buf, stripes[m], chunk_elems, total_elems),
            )
        for dest, up, ref in rs_refs:
            if not ref.resolve(timeout=backstop):
                raise RingRoundError(
                    f"reduce-scatter push {up!r} to {dest!r} failed"
                )
        if timings is not None:
            timings["push_s"] = _time.perf_counter() - t_call0

        _maybe_fault("reduce")
        if my_stripe_elems:
            my_reduced = agg.result(timeout=backstop)
        else:
            my_reduced = np.empty(0, out_dt)
        t_mark = _phase_span(
            "reduce_scatter", t_mark,
            detail={"stripe": m, "parties": n},
        )

        # Reduced passthrough: stripe 0's owner always exists (block 0
        # is always in stripe 0) and holds every party's non-float
        # leaves; reduce with the identical per-leaf semantics as the
        # one-shot path.
        reduced_pt: tuple = ()
        if m == 0 and my_packed.passthrough:
            from rayfed_tpu.fl.fedavg import _reduce_passthrough

            pts: List[tuple] = [()] * len(objs)
            pts[my_idx] = tuple(my_packed.passthrough)
            for i in range(len(objs)):
                if i == my_idx:
                    continue
                val = agg.payload_value(i)
                pts[i] = tuple(val["pt"])
            reduced_pt = tuple(
                _reduce_passthrough(pts, weights, total_w)
            )

        # -- all-gather: reduced stripes travel the ring ---------------
        # Compressed-domain rounds code the gather hop on the SHARED
        # round grid (ROADMAP 2a — the reduce-scatter was already
        # integer, the gather shipped f32): the owner codes its
        # finalized stripe, ships + relays the integer codes, and
        # every party (owner INCLUDED) assembles the decoded codes, so
        # the ring result is byte-identical on every controller and
        # equals the full-buffer recode of the exact aggregate — the
        # ring's analogue of the coordinator path's quantized downlink.
        _maybe_fault("ag")

        def _gather_ctx(k: int):
            rows_s, rows_z = quant.rows(stripes[k])
            ref_slice = (
                None if qref is None
                else _stripe_slice(qref, stripes[k], chunk_elems,
                                   total_elems)
            )
            return rows_s, rows_z, ref_slice

        # The gather wire dtype is a round-wide contract: derived from
        # the GRID alone, never from whether this party happens to own
        # a stripe (a zero-stripe party still validates its peers'
        # coded stripes against it).
        ag_dt_name = (
            quant.wire_dtype if quant is not None else out_dt.name
        )
        if quant is not None and my_stripe_elems:
            rows_s, rows_z, ref_slice = _gather_ctx(m)
            my_codes = code_gather_stripe(
                my_reduced, ref_slice, rows_s, rows_z, chunk_elems,
                quant.wire_dtype,
            )
            my_assembled = decode_gather_stripe(
                my_codes, ref_slice, rows_s, rows_z, chunk_elems, out_dt
            )
        else:
            my_codes = None
            my_assembled = np.asarray(my_reduced)
        gathered: Dict[int, np.ndarray] = {m: my_assembled}
        fwd_refs: List[tuple] = []
        fwd_lock = threading.Lock()

        def _ag_payload(k: int, data: np.ndarray) -> Dict[str, Any]:
            payload = {
                "data": data,
                "rsm": json.dumps(
                    make_stripe_meta(
                        k, n, nblocks, total_elems, ag_dt_name, "ag",
                        qgrid_fp=q_fp,
                    ),
                    sort_keys=True,
                ),
            }
            if k == 0 and reduced_pt:
                payload["pt"] = reduced_pt
            return payload

        def _ag_send(k: int, hop: int, payload: Dict[str, Any]) -> None:
            ref = send_on_runtime(
                runtime, succ, payload, f"{ag_id}.ag.{k}.{hop}", ag_id,
                stream=f"{stream}/ag/{k}", round_tag=round_tag,
            )
            with fwd_lock:
                fwd_refs.append((k, hop, ref))

        if elems(m):
            _ag_send(
                m, 1,
                _ag_payload(
                    m, my_codes if my_codes is not None else gathered[m]
                ),
            )

        collected: Dict[int, Any] = {}
        for k in sorted(
            (k for k in range(n) if k != m and elems(k)),
            key=lambda k: (m - k) % n,
        ):
            hop = (m - k) % n  # how many hops stripe k took to reach me

            def _on_stripe(value, k=k, hop=hop):
                # "el" is the FULL buffer's element count (the grid the
                # stripe indexes into); the stripe's own length follows
                # from the schedule and is re-checked at assembly.
                ag_want = {
                    "s": k, "n": n, "nb": nblocks, "el": total_elems,
                    "dt": ag_dt_name, "ph": "ag",
                }
                if q_fp is not None:
                    # Gather codes mean nothing without the grid —
                    # prove both ends derived the identical one before
                    # any decode (and before the relay hop).
                    ag_want["qg"] = q_fp
                _check_meta(value["rsm"], ag_want)
                if hop + 1 <= n - 1:  # successor is not stripe k's owner
                    _ag_send(k, hop + 1, value)
                return value

            # Forward-on-arrival: the then() runs on the codec pool as
            # each stripe decodes, so relaying stripe k overlaps with
            # stripe k+1 still being on the wire.
            collected[k] = recv_on_runtime(
                runtime, pred, f"{ag_id}.ag.{k}.{hop}", ag_id
            ).then(_on_stripe)

        for k, ref in collected.items():
            value = ref.resolve(timeout=backstop)
            arr = np.asarray(value["data"]).reshape(-1)
            if quant is not None:
                rows_s, rows_z, ref_slice = _gather_ctx(k)
                arr = decode_gather_stripe(
                    arr, ref_slice, rows_s, rows_z, chunk_elems, out_dt
                )
            gathered[k] = arr
            if k == 0 and "pt" in value:
                reduced_pt = tuple(value["pt"])
        with fwd_lock:
            pending_fwd = list(fwd_refs)
        for k, hop, ref in pending_fwd:
            if not ref.resolve(timeout=backstop):
                raise RingRoundError(
                    f"all-gather forward of stripe {k} (hop {hop}) to "
                    f"{succ!r} failed"
                )
        t_mark = _phase_span("all_gather", t_mark)

        # -- assemble the full buffer back onto the chunk grid ---------
        full = np.empty(total_elems, out_dt)
        for k in range(n):
            data = gathered.get(k)
            if data is None or not len(stripes[k]):
                continue
            if data.size != elems(k):
                raise RingRoundError(
                    f"stripe {k} carries {data.size} elements, schedule "
                    f"says {elems(k)}"
                )
            off = 0
            for b in stripes[k]:
                size = min(chunk_elems, total_elems - b * chunk_elems)
                full[b * chunk_elems : b * chunk_elems + size] = (
                    data[off : off + size]
                )
                off += size
        spec = my_packed.spec
        if out_dt.name != spec.wire_dtype:
            spec = PackSpec(spec.entries, spec.treedef, out_dt.name)
        result = PackedTree(full, reduced_pt, spec)

        # Non-member result broadcast rides BEFORE the commit ring:
        # a failed broadcast then aborts the round on every controller
        # (the commit never completes), and non-members only RETURN the
        # result once their release token arrives — lockstep with the
        # members.
        if m == 0 and non_members:
            _broadcast_non_members(result)

        # -- commit ring: agree the round landed everywhere ------------
        _maybe_fault("commit")
        token = {"ok": 1}

        def _token_send(up: str, down) -> None:
            if not send_on_runtime(
                runtime, succ, token, up, down, round_tag=round_tag
            ).resolve(timeout=backstop):
                raise RingRoundError(
                    f"commit token {up!r} to {succ!r} failed"
                )

        if m == 0:
            _token_send(f"{commit_id}.c.1", commit_id)
            recv_on_runtime(
                runtime, pred, f"{commit_id}.c.{n}", commit_id
            ).resolve(timeout=backstop)
            _token_send(f"{release_id}.r.1", release_id)
        else:
            recv_on_runtime(
                runtime, pred, f"{commit_id}.c.{m}", commit_id
            ).resolve(timeout=backstop)
            _token_send(f"{commit_id}.c.{m + 1}", commit_id)
            recv_on_runtime(
                runtime, pred, f"{release_id}.r.{m}", release_id
            ).resolve(timeout=backstop)
            if m < n - 1:
                _token_send(f"{release_id}.r.{m + 1}", release_id)
    except BaseException as exc:
        _quant_rollback()
        for up, down in pending_cancels:
            transport.cancel_stream(up, down)
        _poison_ring_edges(
            runtime, exc, ring=ring, m=m, my_idx=my_idx,
            rs_id=rs_id, ag_id=ag_id, commit_id=commit_id,
            release_id=release_id, nm_id=nm_id, non_members=non_members,
        )
        if isinstance(exc, (KeyboardInterrupt, SystemExit)):
            # The poison still unparks the peers, but an interrupt must
            # STOP the caller — wrapping it as RingRoundError would let
            # the trainer's fallback swallow it and keep training.
            raise
        RING_STATS["rounds_aborted"] += 1
        _telemetry.event(
            "ring.abort", round=round_tag, party=me, outcome="error",
            detail={"error": repr(exc)},
        )
        if isinstance(exc, RingRoundError):
            raise
        raise RingRoundError(f"ring round aborted: {exc!r}") from exc

    if m == 0 and non_members:
        try:
            _release_non_members()
        except Exception:  # pragma: no cover - post-commit best effort
            logger.exception("[%s] non-member release pass failed", me)
    _quant_commit()
    RING_STATS["rounds_completed"] += 1
    _phase_span("commit", t_mark)
    if timings is not None:
        timings.setdefault("push_s", 0.0)
        timings["agg_s"] = _time.perf_counter() - t_call0
    return result


def _make_stripe_agg(runtime, n_sources, weights, out_dtype, expect_elems,
                     chunk_elems, label, meta_check=None, quant=None,
                     quant_blocks=None, quant_ref=None):
    from rayfed_tpu.fl.streaming import StripeAggregator

    return StripeAggregator(
        n_sources,
        weights=weights,
        allowed=runtime.cluster_config.serializing_allowed_list,
        party=runtime.party,
        # The fold grid must match the stripe compaction grid, or an
        # overridden granularity would fold in 4 MB units only (no
        # streaming overlap) and over-allocate the accumulator.
        chunk_elems=chunk_elems,
        out_dtype=out_dtype,
        expect_elems=expect_elems,
        label=label,
        meta_check=meta_check,
        # Compressed-domain rounds: integer codes fold into a donated
        # i32 accumulator; quant_blocks selects this stripe's grid rows
        # for the single fused rescale, quant_ref its compacted
        # reference slice.
        quant=quant,
        quant_blocks=quant_blocks,
        quant_ref=quant_ref,
    )


def _poison_ring_edges(
    runtime, exc, *, ring, m, my_idx, rs_id, ag_id, commit_id, release_id,
    nm_id, non_members,
) -> None:
    """Best-effort poison of every rendezvous key this party produces.

    The receivers' recvs (and stream sinks) then raise the originating
    error within a round-trip instead of parking until the backstop,
    and each of them unwinds its OWN outgoing edges the same way — the
    abort cascades around the ring.  Duplicate poisons of an
    already-consumed key are deduped by the mailbox.
    """
    poison = getattr(runtime.transport, "_send_poison", None)
    if poison is None:  # transport without a poison path (custom proxy)
        return
    n = len(ring)
    succ = ring[(m + 1) % n]
    edges = []
    for k in range(n):  # reduce-scatter pushes I owed stripe owners
        if k != m:
            edges.append((ring[k], f"{rs_id}.rs.{my_idx}.{k}", rs_id))
    for k in range(n):  # all-gather forwards I owed my successor
        hop = (m - k) % n + 1
        if hop <= n - 1:
            edges.append((succ, f"{ag_id}.ag.{k}.{hop}", ag_id))
    edges.append((succ, f"{commit_id}.c.{m + 1}", commit_id))
    if m < n - 1:
        edges.append((succ, f"{release_id}.r.{m + 1}", release_id))
    if m == 0:
        for p in non_members:
            edges.append((p, nm_id, nm_id))
            edges.append((p, f"{release_id}.nm", release_id))
    for dest, up, down in edges:
        if dest == runtime.party:
            continue  # n==1 degenerate ring: succ is this party itself
        try:
            poison(dest, up, down, exc)
        except Exception:  # pragma: no cover - best effort
            logger.exception(
                "[%s] failed to poison ring edge (%s, %s) at %s",
                runtime.party, up, down, dest,
            )
