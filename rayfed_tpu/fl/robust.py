"""Byzantine-robust aggregation: bounded-influence alternatives to the mean.

Plain FedAvg is a mean — one malicious (or merely broken) party can move
the aggregate arbitrarily far.  These estimators bound any single
party's influence; they slot in wherever :func:`rayfed_tpu.fl.tree_average`
does (all-to-all aggregation, or the coordinator's reducer via
``aggregate``'s building blocks).  The reference ships no aggregation at
all (its engine leaves FL math to users, canonical mean loop at
``tests/test_fed_get.py:47-82``); this module is capability beyond it.

All estimators are jit-compiled pytree arithmetic over the stacked
contributions — one fused XLA op per leaf, f32 accumulation:

- :func:`tree_median` — coordinate-wise median.  Breakdown point 1/2;
  the classic robust baseline.
- :func:`tree_trimmed_mean` — coordinate-wise trimmed mean: drop the
  ``trim`` largest and smallest values per coordinate, average the
  rest.  With ``trim ≥ f`` it tolerates ``f`` Byzantine parties
  (Yin et al., 2018) while keeping more of the mean's efficiency than
  the median.
- :func:`krum` / :func:`multi_krum` — select the contribution(s) whose
  squared distance to their ``n − f − 2`` nearest peers is smallest
  (Blanchard et al., 2017): a *selection* rule, so the result is an
  actual party update, never a synthesized point.

Usage (every controller, identical arguments — multi-controller safe;
the choice of estimator must be part of the shared program)::

    values = fed.get(update_objs)           # all-to-all fetch
    agg = fl.tree_trimmed_mean(values, trim=1)

Heterogeneous fleets: the *selection* rules (Krum) compare floating
scores; although the distance matmul runs at HIGHEST precision, exact
cross-backend bit-identity is not guaranteed, and a flipped near-tie
returns a different whole tree per controller.  Run selection
coordinator-side there — ``run_fedavg_rounds(aggregator=...)`` already
does (one party reduces, the result broadcasts).
"""

from __future__ import annotations

import functools
from typing import Any, List, Sequence

import jax
import jax.numpy as jnp


def _stack_leaves(trees: Sequence[Any]):
    trees = list(trees)
    if not trees:
        raise ValueError("need at least one contribution")
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack([l.astype(jnp.float32) for l in leaves]),
        *trees,
    )
    return stacked, trees[0]


@functools.partial(jax.jit, static_argnums=())
def _median_tree(stacked: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: jnp.median(s, axis=0), stacked
    )


def _cast_like(out: Any, proto: Any) -> Any:
    """Cast float leaves back to the contribution dtype; int leaves keep
    the f32 result (same contract as ``fedavg._mean_leaf``: an int mean/
    median stays the float it always was, never a truncated int)."""
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype)
        if jnp.issubdtype(p.dtype, jnp.floating)
        else m,
        out,
        proto,
    )


def tree_median(trees: Sequence[Any]) -> Any:
    """Coordinate-wise median of param pytrees (f32; float leaves cast
    back to their dtype, int leaves stay float — never truncated)."""
    stacked, proto = _stack_leaves(trees)
    return _cast_like(_median_tree(stacked), proto)


@functools.partial(jax.jit, static_argnums=(1,))
def _tmean_tree(stacked: Any, trim: int) -> Any:
    def leaf(s):
        s = jnp.sort(s, axis=0)
        kept = s[trim : s.shape[0] - trim] if trim else s
        return jnp.mean(kept, axis=0)

    return jax.tree_util.tree_map(leaf, stacked)


def tree_trimmed_mean(trees: Sequence[Any], *, trim: int) -> Any:
    """Coordinate-wise ``trim``-trimmed mean.

    Sorts each coordinate across the ``n`` contributions, drops the
    ``trim`` smallest and ``trim`` largest values, and averages the
    remaining ``n − 2·trim`` — tolerating up to ``trim`` Byzantine
    parties per coordinate.  ``trim = 0`` is the plain mean.
    """
    trees = list(trees)
    n = len(trees)
    if trim < 0:
        raise ValueError(f"trim must be >= 0, got {trim}")
    if n - 2 * trim < 1:
        raise ValueError(
            f"trim={trim} leaves no contributions out of {n} "
            f"(need n - 2*trim >= 1)"
        )
    stacked, proto = _stack_leaves(trees)
    return _cast_like(_tmean_tree(stacked, int(trim)), proto)


def _pairwise_sq_dists(flat: jax.Array) -> jax.Array:
    """[n, d] → [n, n] squared euclidean distances.

    HIGHEST matmul precision: Krum *selects* by argmin over these
    scores, so a bf16-class default matmul could flip a near-tied
    selection between backends — a selection flip forks the global
    model, unlike the ulp-level divergence a mean tolerates.  On a
    heterogeneous fleet (mixed TPU/CPU controllers), run the selection
    coordinator-side anyway (see the module docstring).
    """
    sq = jnp.sum(flat**2, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * jnp.matmul(
        flat, flat.T, precision=jax.lax.Precision.HIGHEST
    )
    return jnp.maximum(d2, 0.0)


@functools.partial(jax.jit, static_argnums=(1,))
def _krum_scores_flat(flat: jax.Array, k: int) -> jax.Array:
    d2 = _pairwise_sq_dists(flat)
    # Exclude self-distance (0 on the diagonal) by pushing it past
    # every real distance, then sum the k smallest.
    d2 = d2 + jnp.diag(jnp.full((flat.shape[0],), jnp.inf))
    nearest = jnp.sort(d2, axis=1)[:, :k]
    return jnp.sum(nearest, axis=1)


def krum_scores(trees: Sequence[Any], *, num_byzantine: int) -> jax.Array:
    """Per-party Krum score: sum of squared distances to the party's
    ``n − f − 2`` nearest peers (lower = more central).  ``f`` =
    ``num_byzantine``; requires ``n ≥ f + 3``."""
    trees = list(trees)
    n = len(trees)
    f = int(num_byzantine)
    if f < 0:
        raise ValueError(f"num_byzantine must be >= 0, got {f}")
    if n < f + 3:
        raise ValueError(
            f"Krum needs n >= f + 3 contributions (got n={n}, f={f})"
        )
    k = n - f - 2  # neighbors counted into the score

    flat = jnp.stack(
        [
            jnp.concatenate(
                [
                    jnp.ravel(l).astype(jnp.float32)
                    for l in jax.tree_util.tree_leaves(t)
                ]
            )
            for t in trees
        ]
    )
    return _krum_scores_flat(flat, k)


def krum(trees: Sequence[Any], *, num_byzantine: int) -> Any:
    """Blanchard et al.'s Krum: return the single most central
    contribution (the one with the lowest score) — an actual party
    update, never a synthesized point."""
    trees = list(trees)
    scores = krum_scores(trees, num_byzantine=num_byzantine)
    # Host-side argmin over a tiny vector: selection happens in the
    # driver (the choice is data-dependent; every controller computes
    # the identical scores from the identical contributions).
    return list(trees)[int(jnp.argmin(scores))]


def multi_krum(
    trees: Sequence[Any], *, num_byzantine: int, num_selected: int
) -> Any:
    """Average of the ``num_selected`` lowest-score contributions —
    Krum's robustness with more of the mean's variance reduction."""
    trees = list(trees)
    m = int(num_selected)
    # Theory bound (Blanchard et al.): averaging more than n - f - 2
    # selections can include Byzantine updates, degenerating toward the
    # plain mean this module exists to replace.
    cap = len(trees) - int(num_byzantine) - 2
    if not 1 <= m <= cap:
        raise ValueError(
            f"num_selected must be in [1, n - f - 2] = [1, {cap}] "
            f"(n={len(trees)}, f={num_byzantine}), got {m}"
        )
    scores = krum_scores(trees, num_byzantine=num_byzantine)
    order = jnp.argsort(scores)
    chosen: List[Any] = [trees[int(i)] for i in order[:m]]
    from rayfed_tpu.fl.fedavg import tree_average

    return tree_average(chosen)
