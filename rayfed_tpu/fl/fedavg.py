"""FedAvg: cross-party weighted parameter averaging.

Multi-controller semantics (every party runs the same line): each party
contributes its local update as a ``FedObject``; :func:`aggregate` fetches
all contributions via ``fed.get`` — owners *push* to every peer per the
broadcast-on-get semantics (reference ``api.py:385-400``) — and averages
locally.  The tree arithmetic is jit-compiled, so with params sharded over
a party-local mesh the average runs as one fused XLA op per leaf on
device, and the cross-party hop is the only DCN traffic.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _check_weights(weights: Sequence[float]) -> float:
    """Validated total of a weight vector.

    An empty or all-zero (or non-finite) weight vector would silently
    divide the aggregate by 0 — surface it as a ValueError naming the
    problem instead of propagating inf/NaN params into the round."""
    if len(weights) == 0:
        raise ValueError("weights must be non-empty")
    total = float(sum(float(w) for w in weights))
    if total == 0.0:
        raise ValueError(
            "weights sum to zero (e.g. every party reported 0 examples) "
            "— the weighted average is undefined; drop the round or pass "
            "weights=None for a plain mean"
        )
    if not np.isfinite(total):
        raise ValueError(f"weights sum to a non-finite value ({total})")
    return total


def _mean_leaf(*leaves):
    """Mean of one leaf position: low-precision floats (e.g. bf16 from
    fl.compression) accumulate in f32 and cast back; everything else —
    including int leaves — keeps numpy's promoting arithmetic (an int
    mean stays the float it always was, never a truncated int)."""
    dt = leaves[0].dtype
    if jnp.issubdtype(dt, jnp.floating):
        acc = leaves[0].astype(jnp.float32)
        for leaf in leaves[1:]:
            acc = acc + leaf.astype(jnp.float32)
        return (acc / len(leaves)).astype(dt)
    return sum(leaves[1:], start=leaves[0]) / len(leaves)


@functools.partial(jax.jit, static_argnums=())
def _tree_mean(trees: List[Any]) -> Any:
    return jax.tree_util.tree_map(_mean_leaf, *trees)


def tree_weighted_sum(trees: Sequence[Any], weights: Sequence[float]) -> Any:
    """Weighted sum of param pytrees (weights need not be normalized).

    Raises :class:`ValueError` on an empty or zero-sum weight vector
    (the normalization below would otherwise divide by zero).
    """
    total = _check_weights(weights)
    norm = [w / total for w in weights]

    def _leaf(*leaves):
        dt = leaves[0].dtype
        floating = jnp.issubdtype(dt, jnp.floating)
        acc = leaves[0].astype(jnp.float32) if floating else leaves[0]
        acc = acc * norm[0]
        for leaf, w in zip(leaves[1:], norm[1:]):
            acc = acc + (leaf.astype(jnp.float32) if floating else leaf) * w
        return acc.astype(dt) if floating else acc

    return jax.tree_util.tree_map(_leaf, *trees)


@functools.lru_cache(maxsize=None)
def _packed_reduce_jit(out_dtype_name: str):
    """ONE fused program over the packed wire buffers: zero-init, then a
    per-party multiply-add chain in f32, final divide + cast to the
    output dtype.  The per-element op sequence is exactly the chain the
    streaming aggregator's chunk kernel applies (fl.streaming), which is
    what makes streamed and one-shot aggregation bit-identical."""

    @jax.jit
    def _reduce(bufs, w, total_w):
        acc = jnp.zeros(bufs[0].shape, jnp.float32)
        for i, b in enumerate(bufs):
            acc = acc + w[i] * b.astype(jnp.float32)
        return (acc / total_w).astype(jnp.dtype(out_dtype_name))

    return _reduce


def packed_block_grid(total_elems: int, chunk_elems: Optional[int] = None) -> int:
    """Number of blocks in the packed buffer's canonical chunk grid.

    The grid every fold schedule refers to: ``chunk_elems`` wire
    elements per block (default
    :data:`rayfed_tpu.fl.streaming.DEFAULT_CHUNK_ELEMS`, the transport's
    4 MB bf16 chunk), last block short.  Exported so the streaming
    aggregator, the ring topology (:mod:`rayfed_tpu.fl.ring`) and tests
    all derive the identical grid from the identical constant.
    """
    if chunk_elems is None:
        from rayfed_tpu.fl.streaming import DEFAULT_CHUNK_ELEMS

        chunk_elems = DEFAULT_CHUNK_ELEMS
    if total_elems < 0:
        raise ValueError(f"total_elems must be >= 0, got {total_elems}")
    return max(1, -(-total_elems // int(chunk_elems)))


def packed_stripe_schedule(
    nblocks: int, n_stripes: int
) -> List[List[int]]:
    """Round-robin assignment of the chunk grid to ``n_stripes`` stripes.

    Block ``b`` belongs to stripe ``b % n_stripes``; stripe ``k`` of a
    sorted party ring is owned by the ring's ``k``-th party.  This is
    THE canonical stripe layout (documented in
    ``docs/source/ring_topology.rst``): both the ring reduce-scatter's
    senders and its stripe owners derive it independently, so the
    mapping is part of the cross-party contract, like the wire format.
    """
    if n_stripes < 1:
        raise ValueError(f"n_stripes must be >= 1, got {n_stripes}")
    return [
        list(range(k, nblocks, n_stripes)) for k in range(n_stripes)
    ]


@functools.lru_cache(maxsize=None)
def _stripe_finalize_jit(total_elems: int, out_dtype_name: str):
    @jax.jit
    def _finish(acc, total_w):
        return (acc[:total_elems] / total_w).astype(
            jnp.dtype(out_dtype_name)
        )

    return _finish


def finalize_packed_stripe(acc, total_w: float, total_elems: int, out_dtype):
    """THE packed-aggregate finalize: ``(acc[:n] / total_w).astype(out)``.

    One fused divide + cast over an f32 accumulator holding
    ``sum_i(w_i * x_i)`` — the second half of the (weight·payload,
    weight) pair every fold path carries.  Shared by the one-shot
    reduce, the streaming aggregator, and each ring stripe owner: the
    operation is elementwise, so finalizing a stripe's compacted
    accumulator produces exactly the bytes the whole-buffer finalize
    would produce at those element positions — the keystone of
    ring/coordinator bit-identity.
    """
    return _stripe_finalize_jit(
        int(total_elems), np.dtype(out_dtype).name
    )(acc, np.float32(total_w))


# ---------------------------------------------------------------------------
# Compressed-domain (shared-grid integer) aggregation — the aggregator
# half of the fl.quantize codec/aggregator split.  The sum commutes with
# the shared grid: sum_i w_i*x_i == scale_b*(sum_i w_i*q_i - zp_b*W), so
# the fold is a widening i32 multiply-add over the integer codes and the
# rescale happens ONCE at finalize.  Integer adds are exact and
# associative, which is what makes the streamed, one-shot, ring-striped
# and quorum-subset folds byte-identical by construction.
# ---------------------------------------------------------------------------


def quant_weights(
    weights: Optional[Sequence[float]], n: int
) -> Tuple[List[int], int]:
    """Integer weight vector for the compressed-domain fold.

    The i32 accumulator holds ``sum_i w_i * q_i`` exactly only for
    non-negative **integral** weights (FedAvg example counts are) —
    fractional or negative weights would break both exactness and the
    overflow bound.  Returns ``(per-source ints, total)``; raises
    naming the offending weight otherwise.
    """
    if weights is None:
        return [1] * n, n
    if len(weights) != n:
        raise ValueError(f"{len(weights)} weights for {n} sources")
    out: List[int] = []
    for i, w in enumerate(weights):
        f = float(w)
        if not np.isfinite(f) or f < 0 or f != int(f):
            raise ValueError(
                f"compressed-domain aggregation needs non-negative "
                f"integral weights (example counts); weight {i} is "
                f"{w!r} — pre-scale to integers or use the float path"
            )
        out.append(int(f))
    total = sum(out)
    if total == 0:
        raise ValueError(
            "weights sum to zero — the weighted average is undefined"
        )
    return out, total


@functools.lru_cache(maxsize=None)
def quantized_accum_kernel(chunk_elems: int, wire_dtype: str):
    """One donated-i32-accumulator widening multiply-add step:
    ``acc[off:off+C] += w * widen(q)``.

    The integer sibling of the streaming f32 chunk kernel
    (``fl.streaming._accum_kernel``) and of the per-party chain inside
    :func:`packed_quantized_sum` — integer adds are exact, so all of
    them agree bit-for-bit in ANY fold order, and the single fused
    rescale (:func:`finalize_packed_quantized`) is the only place
    floats appear.
    """
    import jax
    import jax.numpy as jnp

    del wire_dtype  # codes widen to i32 whatever the wire width

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _apply(acc, chunk, off, w):
        seg = jax.lax.dynamic_slice(acc, (off,), (chunk_elems,))
        return jax.lax.dynamic_update_slice(
            acc, seg + w * chunk.astype(jnp.int32), (off,)
        )

    return _apply


@functools.lru_cache(maxsize=None)
def masked_code_kernel():
    """ONE fused weight-and-mask step of a secure round
    (fl.secagg): ``bitcast_i32(u32(w·q) + net_mask)`` over the whole
    code buffer.

    The sibling of :func:`quantized_accum_kernel` on the SENDER side:
    the grid codes widen to i32, fold in this party's own integral
    weight (pairwise masks only cancel at unit fold weight — ``w_i·m −
    w_j·m ≠ 0``), and add the party's net pairwise mask in uint32, whose
    arithmetic wraps mod 2³² by definition (the masked value must be
    uniform over the ring the sum lives in).  The receiver folds the
    resulting i32 codes through the UNCHANGED
    :func:`quantized_accum_kernel` at weight 1 — i32 addition wraps the
    same ring — so after every pair mask met its negative the
    accumulator holds exactly ``Σ w_i·q_i`` and the finalize emits the
    unmasked round's bytes.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _mask(q, w, net_mask_u32):
        v = w * q.astype(jnp.int32)  # |w·q| ≤ qabs_max·W: exact in i32
        return jax.lax.bitcast_convert_type(
            jax.lax.bitcast_convert_type(v, jnp.uint32) + net_mask_u32,
            jnp.int32,
        )

    return _mask


@functools.lru_cache(maxsize=None)
def masked_correction_kernel():
    """Subtract a dropout round's orphaned-mask correction
    (``fl.secagg.mask_correction``) from the donated i32 accumulator —
    uint32 bitcast arithmetic, mod 2³² like every masked step."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def _sub(acc, corr_u32):
        a = jax.lax.bitcast_convert_type(acc, jnp.uint32)
        return jax.lax.bitcast_convert_type(a - corr_u32, jnp.int32)

    return _sub


@functools.lru_cache(maxsize=None)
def _quant_reduce_jit(nblocks: int, chunk_elems: int):
    """One-shot integer reduce: widen + weighted-add chain over the
    packed code buffers, padded onto the canonical block grid (the
    same padded accumulator shape the streaming fold carries)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _reduce(bufs, w):
        acc = jnp.zeros(nblocks * chunk_elems, jnp.int32)
        for i, b in enumerate(bufs):
            acc = acc.at[: b.shape[0]].add(w[i] * b.astype(jnp.int32))
        return acc

    return _reduce


@functools.lru_cache(maxsize=None)
def _quant_finalize_jit(chunk_elems: int, total_elems: int,
                        out_dtype_name: str, with_ref: bool):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _finish(acc, ref, scales, zps, total_w):
        a = acc.reshape(-1, chunk_elems).astype(jnp.float32)
        x = scales[:, None] * (a - zps[:, None] * total_w)
        x = x.reshape(-1)[:total_elems] / total_w
        if with_ref:
            # Delta-coded rounds: the codes summed to W·(mean delta);
            # the shared reference (every party holds it bit-
            # identically) adds back AFTER the divide, elementwise.
            x = ref + x
        return x.astype(jnp.dtype(out_dtype_name))

    return _finish


def finalize_packed_quantized(
    acc, scales, zps, total_w: float, total_elems: int,
    chunk_elems: int, out_dtype, ref=None,
):
    """THE compressed-domain finalize: the single fused rescale
    ``[ref +] (scale_b * (acc - zp_b*W)) / W`` over a block-grid-padded
    i32 accumulator holding ``sum_i w_i * q_i``.

    ``ref`` (delta-coded rounds): the shared reference buffer the codes
    were taken against — a flat f32 array of ``total_elems`` elements
    (a stripe owner passes its stripe-compacted slice).

    The quantized sibling of :func:`finalize_packed_stripe`, and like
    it the SINGLE producer of the output bytes for every topology: the
    one-shot reduce, the streaming aggregator, each ring stripe owner
    (with its block-subset ``scales``/``zps`` rows and reference
    slice) and the quorum refold all call exactly this.  Elementwise
    with per-block parameters, so a stripe's rows produce exactly the
    bytes the whole-buffer finalize produces at those element
    positions.
    """
    import jax.numpy as jnp

    with_ref = ref is not None
    if with_ref:
        ref = jnp.asarray(np.asarray(ref).reshape(-1), jnp.float32)
        if int(ref.size) != int(total_elems):
            raise ValueError(
                f"reference has {ref.size} elements, finalize covers "
                f"{total_elems}"
            )
    else:
        ref = jnp.zeros(0, jnp.float32)
    return _quant_finalize_jit(
        int(chunk_elems), int(total_elems), np.dtype(out_dtype).name,
        with_ref,
    )(acc, ref, np.asarray(scales, np.float32),
      np.asarray(zps, np.float32), np.float32(total_w))


@functools.lru_cache(maxsize=None)
def server_step_kernel(kind: str, hyper: Tuple[float, ...]):
    """ONE fused server-optimization step over packed f32 buffers —
    the aggregate-then-step composition of :mod:`rayfed_tpu.fl.
    server_opt`, placed beside :func:`finalize_packed_stripe` /
    :func:`finalize_packed_quantized` because it consumes exactly their
    output: ``step(x, avg, *state) -> x'`` where ``x`` is the round's
    shared starting buffer, ``avg`` the finalized aggregate and
    ``state`` the packed auxiliary sequence(s).  ``avg`` is deliberately
    NOT donated: the streaming aggregator's result holder retains the
    same buffer (and harnesses step several controller replicas over
    one array) — the donated pass of the aggregate-then-step
    composition is the fold accumulator upstream, and this kernel still
    allocates exactly one output buffer.

    Kinds (hyperparameters are static — one compile per config):

    - ``"momentum"`` ``(lr, momentum)`` — FedAvgM on the packed buffer:
      ``x' = x − lr·(momentum·m + (x − avg))``.  ``lr=1, momentum=0``
      RETURNS ``avg`` literally (bit-exact plain FedAvg, not a
      float-rounded reconstruction of it).
    - ``"fedac"`` ``(lam, gamma, beta)`` — FedAC's linear-coupling
      acceleration (Yuan & Ma 2020) with the round pseudo-gradient
      ``Δ = x − avg``: conservative step ``y' = x − lam·Δ``, aggressive
      step ``z' = z − gamma·Δ`` over the auxiliary sequence ``z``, and
      the broadcast point ``x' = (1−beta)·y' + beta·z'``.  ``lam=1,
      beta=0`` returns ``avg`` literally.

    The step deliberately emits ONLY the new broadcast buffer: the
    state advances via :func:`server_resync_kernel` from the broadcast
    pair ``(x, x')`` on EVERY controller, which is what keeps the
    replicated state byte-identical cluster-wide (see fl.server_opt).
    """
    import jax
    import jax.numpy as jnp

    if kind == "momentum":
        lr, momentum = (float(h) for h in hyper)

        @jax.jit
        def _step(x, avg, m):
            x = x.astype(jnp.float32)
            avg = avg.astype(jnp.float32)
            if momentum == 0.0 and lr == 1.0:
                return avg  # plain FedAvg, bit-exactly
            return x - lr * (momentum * m + (x - avg))

        return _step
    if kind == "fedac":
        lam, gamma, beta = (float(h) for h in hyper)

        @jax.jit
        def _step(x, avg, z):
            x = x.astype(jnp.float32)
            avg = avg.astype(jnp.float32)
            if beta == 0.0 and lam == 1.0:
                return avg  # plain FedAvg, bit-exactly
            delta = x - avg
            y_new = x - lam * delta
            z_new = z - gamma * delta
            return (1.0 - beta) * y_new + beta * z_new

        return _step
    raise ValueError(
        f"unknown server-opt kind {kind!r} — one of 'momentum', 'fedac'"
    )


@functools.lru_cache(maxsize=None)
def server_resync_kernel(kind: str, hyper: Tuple[float, ...]):
    """Advance the packed server-opt state from the round's broadcast
    pair: ``resync(x, x_new, *state) -> new state tuple``.

    The companion of :func:`server_step_kernel`, and the reason every
    controller's state replica stays BYTE-identical with zero extra
    wire bytes: the state is defined as a deterministic f32 function of
    ``(x, x_new, state)`` where ``x_new`` is the round's broadcast —
    the one buffer the whole cluster already byte-agrees on (decoded
    codes in quantized rounds, the f32 broadcast otherwise).  The
    coordinator runs the SAME resync on the same decoded bytes instead
    of keeping its exact-step state, so any downlink quantization error
    is absorbed into the state consistently everywhere (the same
    self-correction an EF residual performs, one level up).  State
    buffers are deliberately NOT donated: FedAC's z₀ aliases the
    caller's initial-point array, and the harnesses/tests retain state
    references across rounds — an aliased donation frees a buffer
    someone else still reads.

    - ``"momentum"``: ``m' = (x − x_new)/lr`` (exactly the step the
      broadcast realized).
    - ``"fedac"``: ``z' = z − (gamma/D)·((1−beta)·x + beta·z − x_new)``
      with ``D = (1−beta)·lam + beta·gamma`` — algebraically
      ``z − gamma·Δ`` with ``Δ`` implied by the realized broadcast.
    """
    import jax
    import jax.numpy as jnp

    if kind == "momentum":
        lr, _momentum = (float(h) for h in hyper)

        # No donation: the old momentum buffer is replaced wholesale
        # without being read, and XLA warns on donated-but-unused.
        @jax.jit
        def _resync(x, x_new, m):
            del m  # replaced wholesale by the realized step
            x = x.astype(jnp.float32)
            x_new = x_new.astype(jnp.float32)
            return ((x - x_new) / lr,)

        return _resync
    if kind == "fedac":
        lam, gamma, beta = (float(h) for h in hyper)
        denom = (1.0 - beta) * lam + beta * gamma

        # No donation on z either: FedAC's z₀ aliases the caller's
        # initial-point array (PackedServerOpt.init), and the state
        # holder/test harnesses may retain references across rounds —
        # one transient f32 buffer is not worth an aliasing hazard.
        @jax.jit
        def _resync(x, x_new, z):
            x = x.astype(jnp.float32)
            x_new = x_new.astype(jnp.float32)
            return (
                z - (gamma / denom) * ((1.0 - beta) * x + beta * z - x_new),
            )

        return _resync
    raise ValueError(
        f"unknown server-opt kind {kind!r} — one of 'momentum', 'fedac'"
    )


def packed_quantized_sum(
    quantized_trees: Sequence[Any],
    weights: Optional[Sequence[float]] = None,
    out_dtype: Any = None,
    ref: Any = None,
):
    """Fused compressed-domain reduce over QuantizedPackedTree
    contributions sharing one grid — the one-shot reference every
    streamed/striped/quorum integer fold is asserted bit-identical to.

    ``ref``: the shared reference buffer for delta-coded contributions
    (``grid.mode == "delta"``) — the finalize adds it back.

    ``out_dtype`` defaults to **float32** (re-coding the mean onto the
    8-bit grid would be exactly the loss no residual compensates; the
    downlink quantizes separately, with its own grid and residual).
    """
    from rayfed_tpu.fl.quantize import QuantizedPackedTree, _check_ref

    packeds = list(quantized_trees)
    if not packeds:
        raise ValueError("packed_quantized_sum needs at least one tree")
    for i, p in enumerate(packeds):
        if not isinstance(p, QuantizedPackedTree):
            raise ValueError(
                f"contribution {i} is not a QuantizedPackedTree (got "
                f"{type(p).__name__}) — quantize with "
                f"fl.quantize.quantize_packed(tree, grid)"
            )
    gmeta = packeds[0].gmeta
    spec = packeds[0].spec
    for i, p in enumerate(packeds[1:], 1):
        if p.gmeta != gmeta or p.spec != spec:
            raise ValueError(
                f"contribution {i} was coded on a different grid "
                f"(fp={p.gmeta.fp:#010x} vs {gmeta.fp:#010x}) — all "
                f"parties must quantize onto the round's shared grid"
            )
    n = len(packeds)
    iw, itotal = quant_weights(weights, n)
    grid = packeds[0].grid()
    grid.check_weight_headroom(itotal)
    ref = _check_ref(grid, ref)
    nblocks = packed_block_grid(gmeta.total_elems, gmeta.chunk_elems)
    acc = _quant_reduce_jit(nblocks, gmeta.chunk_elems)(
        tuple(p.buf for p in packeds),
        np.asarray(iw, np.int32),
    )
    total_w = float(itotal)
    out_name = np.dtype(
        out_dtype if out_dtype is not None else np.float32
    ).name
    buf = finalize_packed_quantized(
        acc, grid.scales, grid.zps, total_w, gmeta.total_elems,
        gmeta.chunk_elems, out_name, ref=ref,
    )
    passthrough = _reduce_passthrough(
        [p.passthrough for p in packeds],
        None if weights is None else list(weights),
        total_w,
    )
    return _packed_result(buf, passthrough, spec, out_name)


def _packed_result(buf, passthrough, spec, out_name):
    """Plain (float) PackedTree around a finalized aggregate buffer."""
    from rayfed_tpu.fl.compression import PackedTree, PackSpec

    if out_name != spec.wire_dtype:
        spec = PackSpec(spec.entries, spec.treedef, out_name)
    return PackedTree(buf, passthrough, spec)


def _reduce_passthrough(passthroughs, weights, total):
    """Average the non-float (passthrough) leaf tuples of N PackedTrees
    with :func:`tree_average`'s per-leaf semantics.  Shared by the
    one-shot (:func:`packed_weighted_sum`) and streaming
    (``fl.streaming``) reduces so the two stay result-identical."""
    if not passthroughs[0]:
        return ()
    if weights is None:
        return tuple(_mean_leaf(*ls) for ls in zip(*passthroughs))
    norm = [float(x) / total for x in weights]

    def _pt(*leaves):
        acc = leaves[0] * norm[0]
        for leaf, wt in zip(leaves[1:], norm[1:]):
            acc = acc + leaf * wt
        return acc

    return tuple(_pt(*ls) for ls in zip(*passthroughs))


def packed_weighted_sum(
    packed_trees: Sequence[Any],
    weights: Optional[Sequence[float]] = None,
    out_dtype: Any = None,
):
    """Fused single-jit reduce over PackedTree contributions.

    Instead of a tree_map over N full trees (one XLA op per leaf per
    tree), the whole model reduces as ONE compiled chain over the packed
    wire buffers — the same math the streaming path
    (:class:`rayfed_tpu.fl.streaming.StreamingAggregator`) applies
    chunk-by-chunk, so the two are bit-identical.  Passthrough
    (non-float) leaves keep the per-leaf averaging semantics of
    :func:`tree_average`.

    ``out_dtype``: dtype of the returned packed buffer — defaults to
    the contributions' wire dtype.  Pass f32 when the aggregate feeds a
    server optimizer or an error-feedback loop: re-quantizing the mean
    to an aggressive wire dtype here is exactly the loss no residual
    would compensate.
    """
    from rayfed_tpu.fl.compression import PackedTree
    from rayfed_tpu.fl.quantize import QuantizedPackedTree

    packeds = list(packed_trees)
    if not packeds:
        raise ValueError("packed_weighted_sum needs at least one tree")
    if any(isinstance(p, QuantizedPackedTree) for p in packeds):
        raise ValueError(
            "packed_weighted_sum got QuantizedPackedTree contributions "
            "— their buffers are integer CODES, not values; fold them "
            "with packed_quantized_sum (the compressed-domain reduce)"
        )
    if not isinstance(packeds[0], PackedTree):
        raise ValueError(
            f"contribution 0 is not a PackedTree "
            f"(got {type(packeds[0]).__name__}) — pack updates with "
            f"fl.compress(tree, packed=True)"
        )
    spec = packeds[0].spec
    for i, p in enumerate(packeds[1:], 1):
        if not isinstance(p, PackedTree) or p.spec != spec:
            raise ValueError(
                f"contribution {i} is not a PackedTree with the same "
                f"spec — all parties must pack the identical structure"
            )
    n = len(packeds)
    if weights is None:
        w = np.ones(n, np.float32)
        total = float(n)
    else:
        if len(weights) != n:
            raise ValueError(f"{len(weights)} weights for {n} trees")
        total = _check_weights(weights)
        w = np.asarray([float(x) for x in weights], np.float32)
    out_name = np.dtype(
        out_dtype if out_dtype is not None else packeds[0].buf.dtype
    ).name
    buf = _packed_reduce_jit(out_name)(
        tuple(p.buf for p in packeds), jnp.asarray(w), np.float32(total)
    )
    passthrough = _reduce_passthrough(
        [p.passthrough for p in packeds], weights, total
    )
    if out_name != spec.wire_dtype:
        from rayfed_tpu.fl.compression import PackSpec

        spec = PackSpec(spec.entries, spec.treedef, out_name)
    return PackedTree(buf, passthrough, spec)


def tree_average(trees: Sequence[Any], weights: Optional[Sequence[float]] = None):
    """Mean (or example-count-weighted mean) of param pytrees.

    PackedTree contributions with a shared spec take the fused
    single-jit reduce (:func:`packed_weighted_sum`): one compiled chain
    over the packed buffers instead of per-leaf dispatches.
    """
    trees = list(trees)
    if not trees:
        raise ValueError("tree_average needs at least one tree")
    if weights is not None and len(weights) != len(trees):
        raise ValueError(f"{len(weights)} weights for {len(trees)} trees")
    from rayfed_tpu.fl.compression import PackedTree
    from rayfed_tpu.fl.quantize import QuantizedPackedTree

    if all(isinstance(t, QuantizedPackedTree) for t in trees):
        if trees[0].gmeta.mode != "abs":
            # Delta codes only mean something against the round's
            # shared reference buffer, which this signature cannot
            # carry — send callers to the explicit reduce.
            raise ValueError(
                "tree_average cannot fold delta-coded "
                "QuantizedPackedTree contributions (the codes are "
                "relative to the round's shared reference) — call "
                "packed_quantized_sum(trees, weights, ref=<shared "
                "reference buffer>) directly"
            )
        return packed_quantized_sum(trees, weights)
    if all(isinstance(t, PackedTree) for t in trees) and all(
        t.spec == trees[0].spec for t in trees[1:]
    ):
        return packed_weighted_sum(trees, weights)
    if weights is None:
        return _tree_mean(trees)
    return tree_weighted_sum(trees, tuple(float(w) for w in weights))


def aggregate(
    fed_objects: Sequence[Any],
    weights: Optional[Sequence[float]] = None,
    *,
    mode: str = "auto",
    coordinator: Optional[str] = None,
    materialize: bool = True,
    reducer: Optional[Any] = None,
):
    """FedAvg round: fetch every party's update and reduce (mean by default).

    ``fed_objects``: one FedObject per party (each owned by its producing
    party).  Every party calls this with the same list at the same point
    in the program, so all parties return the identical averaged tree.

    ``reducer(values) -> tree`` replaces the weighted mean with a custom
    reduction (e.g. :func:`rayfed_tpu.fl.tree_trimmed_mean` or a Krum
    selection) over the round's contributions; it rides the SAME wire
    topology the mean does (coordinator-side execution at N>2, one
    reduce + broadcast), so there is exactly one place that decides who
    talks to whom.  Mutually exclusive with ``weights``.

    Wire topology (``mode``):

    - ``"all_to_all"``: every owner pushes to every peer and each party
      averages locally — N·(N-1) transfers.  Lowest latency at N=2.
    - ``"coordinator"``: contributions go to one party (default: the
      owner of ``fed_objects[0]``), which averages and broadcasts the
      result — 2·(N-1) transfers.  The right shape for N>2.
    - ``"auto"``: coordinator when more than two objects, else
      all-to-all.

    The choice is made from ``len(fed_objects)`` and the argument values
    only — identical on every controller, preserving seq-id determinism.

    ``materialize=False`` (coordinator mode only) returns the averaged
    model as a **FedObject** instead of a value: no ``fed.get`` barrier,
    so consecutive rounds pipeline — pass the returned object straight
    into the next round's ``train.remote(...)`` and the coordinator's
    average/broadcast overlaps the workers' next-round work (the arg
    push replaces broadcast-on-get; same bytes, no driver-side stall).
    Improves on the reference, whose round loop blocks on ``fed.get``
    every round (``tests/test_fed_get.py:47-82`` shape).
    """
    import rayfed_tpu as fed

    if reducer is not None and weights is not None:
        raise ValueError(
            "reducer and weights are mutually exclusive (a custom "
            "reducer defines its own weighting)"
        )

    objs = list(fed_objects)
    if mode == "auto":
        # Pipelined (lazy) rounds only exist in coordinator topology, so
        # materialize=False picks it regardless of party count.
        mode = (
            "coordinator"
            if len(objs) > 2 or not materialize
            else "all_to_all"
        )
    if mode == "all_to_all":
        if not materialize:
            raise ValueError(
                'materialize=False requires mode="coordinator" (all_to_all '
                "averages locally, which must fetch the contributions)"
            )
        values = fed.get(objs)
        if reducer is not None:
            return reducer(values)
        return tree_average(values, weights)
    if mode != "coordinator":
        raise ValueError(f"unknown aggregate mode {mode!r}")

    coord = coordinator or objs[0].get_party()
    w = None if weights is None else tuple(float(x) for x in weights)

    def _reduce(*trees):
        if reducer is not None:
            return reducer(list(trees))
        return tree_average(trees, w)

    avg_obj = fed.remote(_reduce).party(coord).remote(*objs)
    if not materialize:
        return avg_obj
    return fed.get(avg_obj)


class FedAvgActorBase:
    """Template for a party-local training actor (wrap with ``@fed.remote``).

    Holds params (+ optional extra state) on device between rounds;
    subclass or compose with a concrete ``train_step``.  Methods return
    plain pytrees so they cross parties through the tensor wire format.
    """

    def __init__(self, params: Any):
        self._params = params

    def get_params(self) -> Any:
        return self._params

    def set_params(self, params: Any) -> None:
        self._params = params

    def train_local(self, step_fn, batches) -> Any:
        """Run ``step_fn(params, *batch) -> (params, loss)`` over batches."""
        loss = None
        for batch in batches:
            self._params, loss = step_fn(self._params, *batch)
        return self._params, loss
