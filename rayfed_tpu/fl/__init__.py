"""Federated-learning algorithms built on the fed API.

The reference provides the *engine* (party-pinned tasks + push transport)
and leaves FL algorithms to users; its own canonical workload is the
FedAvg-style mean-aggregation loop in ``tests/test_fed_get.py:47-82``.
Here the common algorithms ship with the framework:

- :mod:`fedavg` — horizontal FL: weighted parameter averaging across
  parties, plus an actor template for local training.
- :mod:`split` — vertical/split FL: forward activations pushed one way,
  gradients pushed back (BASELINE.md config #5).
- :mod:`fedopt` — server optimizers (FedAvgM/FedAdam/FedYogi) over the
  round's pseudo-gradient, and the FedProx client loss wrapper.
- :mod:`server_opt` — the packed-domain rework of the server step:
  FedAC / server momentum as fused finalize-side kernels over the
  packed wire buffers, composing with ``wire_quant``/``quorum``/
  ``mode="ring"/"hierarchy"`` and cutting ROUNDS-to-target, not just
  round time (``run_fedavg_rounds(server_opt=fl.fedac(...))``).
- :mod:`secagg` — secure aggregation: pairwise-masked integer folds
  (sum-only reveal) with HELLO-handshake key agreement and
  quorum-dropout mask recovery (``run_fedavg_rounds(secure_agg=True)``).
- :mod:`hierarchy` — many-party scale-out: deterministic region
  partition, region-ring reduce-scatter, quantized cross-region
  partial-sum streaming (``run_fedavg_rounds(mode="hierarchy",
  region_size=...)``); byte-identical to the flat compressed-domain
  fold, per-party traffic flat in N.
- :mod:`async_rounds` — buffered asynchronous rounds (FedBuff-style):
  parties push staleness-tagged quantized deltas whenever local work
  finishes; the coordinator folds each arrival into a running
  donated-i32 buffer with exact integer-shift staleness decay and
  emits a new model version every K contributions or T seconds
  (``fl.run_async_fleet(...)``).
- :mod:`dp` — differential privacy: global-norm clipping + Gaussian
  noise on outgoing updates.
- :mod:`robust` — Byzantine-robust aggregation (coordinate median,
  trimmed mean, Krum/multi-Krum) bounding any single party's influence.
"""

from rayfed_tpu.fl.compression import (
    ErrorFeedback,
    PackedTree,
    compress,
    decompress,
    pack_tree,
    unpack_tree,
)
from rayfed_tpu.fl.dp import clip_by_global_norm, privatize
from rayfed_tpu.fl.fedavg import (
    aggregate,
    packed_quantized_sum,
    packed_weighted_sum,
    tree_average,
    tree_weighted_sum,
)
from rayfed_tpu.fl.quantize import (
    QuantCompressor,
    QuantGrid,
    QuantizedPackedTree,
    dequantize_packed,
    make_round_grid,
    quantize_packed,
)
from rayfed_tpu.fl.hierarchy import (
    HierarchyRoundError,
    RegionSumTree,
    hierarchy_aggregate,
)
from rayfed_tpu.fl.overlap import PipelinedRoundRunner, dga_correct
from rayfed_tpu.fl.async_rounds import (
    AsyncBuffer,
    decay_weight,
    run_async_coordinator,
    run_async_fleet,
    run_async_party,
)
from rayfed_tpu.fl.quorum import (
    QuorumRoundError,
    quorum_aggregate,
    run_quorum_rounds,
)
from rayfed_tpu.fl.ring import RingRoundError, ring_aggregate
from rayfed_tpu.fl.streaming import (
    StreamingAggregator,
    StripeAggregator,
    streaming_aggregate,
)
from rayfed_tpu.fl.fedopt import (
    fedprox_loss,
    server_adam,
    server_sgd,
    server_yogi,
)
from rayfed_tpu.fl.server_opt import (
    PackedServerOpt,
    PackedServerOptimizer,
    PackedServerState,
    fedac,
    server_momentum,
)
from rayfed_tpu.fl.trainer import validate_round_config
from rayfed_tpu.fl.robust import (
    krum,
    multi_krum,
    tree_median,
    tree_trimmed_mean,
)
from rayfed_tpu.fl.secagg import (
    MaskedCodeTree,
    MaskedRoundCodec,
    RoundMasker,
    SecAggError,
    mask_update,
    unmask_sum,
)
from rayfed_tpu.fl.split import SplitTrainer
from rayfed_tpu.fl.trainer import run_fedavg_rounds

__all__ = [
    "aggregate",
    "packed_weighted_sum",
    "packed_quantized_sum",
    "QuantCompressor",
    "QuantGrid",
    "QuantizedPackedTree",
    "dequantize_packed",
    "make_round_grid",
    "quantize_packed",
    "streaming_aggregate",
    "ring_aggregate",
    "hierarchy_aggregate",
    "HierarchyRoundError",
    "RegionSumTree",
    "RingRoundError",
    "QuorumRoundError",
    "quorum_aggregate",
    "run_quorum_rounds",
    "PipelinedRoundRunner",
    "dga_correct",
    "AsyncBuffer",
    "decay_weight",
    "run_async_coordinator",
    "run_async_fleet",
    "run_async_party",
    "StreamingAggregator",
    "StripeAggregator",
    "ErrorFeedback",
    "tree_average",
    "tree_weighted_sum",
    "SplitTrainer",
    "compress",
    "decompress",
    "PackedTree",
    "pack_tree",
    "unpack_tree",
    "server_sgd",
    "server_adam",
    "server_yogi",
    "fedprox_loss",
    "PackedServerOpt",
    "PackedServerOptimizer",
    "PackedServerState",
    "fedac",
    "server_momentum",
    "validate_round_config",
    "mask_update",
    "unmask_sum",
    "MaskedCodeTree",
    "MaskedRoundCodec",
    "RoundMasker",
    "SecAggError",
    "privatize",
    "clip_by_global_norm",
    "run_fedavg_rounds",
    "tree_median",
    "tree_trimmed_mean",
    "krum",
    "multi_krum",
]
