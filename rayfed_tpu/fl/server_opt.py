"""Packed-domain server optimization: FedAC / server momentum as fused
finalize-side kernels — cut ROUNDS, not just round time.

The comms campaign (packed codec, streaming folds, ring, compressed
domain, hierarchy) optimized seconds-per-round; this module attacks the
other factor of time-to-accuracy: the NUMBER of communication rounds.
"Federated Accelerated Stochastic Gradient Descent" (FedAC, Yuan & Ma
2020) provably reaches a target loss in fewer rounds than plain FedAvg
by treating the round aggregate as a pseudo-gradient and running an
accelerated server recurrence over it.  The legacy
:mod:`rayfed_tpu.fl.fedopt` optimizers already do the momentum half —
but as per-leaf tree arithmetic over UNPACKED trees, which is why they
were excluded from every packed-domain path (``wire_quant``,
``quorum``, ``mode="ring"/"hierarchy"``).  Here the server step is a
packed-buffer operation living exactly where the aggregation already
lives:

- :class:`PackedServerOpt` — the optimizer *spec* (kind +
  hyperparameters; pure data, hashable, identical on every
  controller).  :func:`server_momentum` builds FedAvgM, :func:`fedac`
  builds FedAC's linear-coupling acceleration ``(λ, γ, β)``:
  conservative step ``y' = x − λ·Δ``, aggressive step ``z' = z − γ·Δ``
  over the auxiliary sequence ``z``, broadcast point
  ``x' = (1−β)·y' + β·z'`` — with ``Δ = x − avg`` the round
  pseudo-gradient.  ``λ=1, β=0`` (or ``momentum=0, lr=1``) reproduces
  plain FedAvg bit-exactly.
- :class:`PackedServerState` — the auxiliary sequence(s) as packed f32
  buffers (one flat buffer per sequence, the same layout the wire
  codec packs), registered as a JAX pytree so it snapshots/restores
  through :class:`rayfed_tpu.checkpoint.FedCheckpointer` like params.
- :class:`PackedServerOptimizer` — one controller's runtime state
  holder.  The step itself (:func:`rayfed_tpu.fl.fedavg.
  server_step_kernel`) runs as ONE fused jitted pass placed beside the
  single finalize: the finalizing node (streaming/quorum coordinator,
  hierarchy root) consumes the EXACT finalized f32 aggregate — the
  donated pass of the composition is the integer fold accumulator
  upstream — and emits the post-step model, which is what
  the downlink ships (quantized rounds re-code the POST-step model via
  the shared :func:`~rayfed_tpu.fl.quantize.quantize_downlink`, so the
  downlink grid is ranged by the post-step delta).  Ring rounds have
  no downlink: every controller already holds the byte-identical
  assembled aggregate and applies the step locally — same kernel, same
  inputs, same bytes.

**State without a state broadcast.**  Every controller replicates the
state, but nobody ships it: after each round the state advances via
:func:`~rayfed_tpu.fl.fedavg.server_resync_kernel` from the broadcast
pair ``(x, x')`` — a deterministic f32 function of buffers the whole
cluster already byte-agrees on.  The coordinator runs the SAME resync
on the decoded broadcast instead of keeping its exact-step state, so
downlink quantization error is absorbed into the state identically
everywhere (momentum becomes "the step the broadcast actually
realized"), every controller can take over as quorum coordinator after
a failover with the right state in hand, and per-party checkpoints of
the state are interchangeable.

**Composition** (enforced by ``fl.trainer.validate_round_config``):
composes with ``wire_quant``, ``streaming_agg``, ``quorum`` (the
cutoff's subset refold reweights the aggregate to the arrived Σw, and
the step consumes exactly that subset mean), ``mode="ring"`` and
``mode="hierarchy"`` (state steps once, at the root, and the tree
broadcast carries the post-step model) and ``checkpointer`` (snapshots
carry the state plus a spec stamp; restoring across differing specs is
refused loudly).  ``overlap=True`` composes too, via the unified
staleness recurrence (``fl/overlap.py`` module docstring): anchoring
the DGA correction ``agg + (w − w_at_send)`` on the POST-step broadcast
makes the step's pseudo-gradient the mean one-round-stale local
displacement — the delayed-gradient regime Federated Accelerated SGD
analyzes — and the pipelined runner drives the identical
``step_fn``/``resync`` pair from its comms lane (bit-exact replay:
``tests/test_overlap.py``).  The buffered asynchronous driver
(``fl/async_rounds.py``) runs the same recurrence at per-party
staleness.  ``secure_agg`` and elastic ``join_ticket`` entry are loud
exclusions (the masked recovery window has not been exercised with a
post-finalize step; welcomes do not carry server-opt state) — never
silent fallbacks.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# How many auxiliary packed buffers each optimizer kind carries.
_STATE_WIDTH = {"momentum": 1, "fedac": 1}


class PackedServerOpt:
    """A server-optimizer *spec*: kind + static hyperparameters.

    Pure data — every controller constructs an equal spec from the
    same arguments, the kernels cache-compile per spec, and the spec
    stamps checkpoint metadata so a restore across differing specs
    fails loudly instead of silently resetting the trajectory.
    """

    __slots__ = ("kind", "hyper")

    def __init__(self, kind: str, hyper: Sequence[float]) -> None:
        if kind not in _STATE_WIDTH:
            raise ValueError(
                f"unknown server-opt kind {kind!r} — one of "
                f"{sorted(_STATE_WIDTH)}"
            )
        self.kind = str(kind)
        self.hyper = tuple(float(h) for h in hyper)
        if kind == "momentum":
            lr, momentum = self.hyper
            if not lr > 0:
                raise ValueError(f"momentum lr must be > 0, got {lr}")
            if not 0.0 <= momentum < 1.0:
                raise ValueError(
                    f"momentum coefficient must be in [0, 1), got "
                    f"{momentum}"
                )
        else:  # fedac
            lam, gamma, beta = self.hyper
            if not lam > 0:
                raise ValueError(f"fedac lam must be > 0, got {lam}")
            if not gamma >= lam:
                raise ValueError(
                    f"fedac gamma must be >= lam (the aggressive step "
                    f"dominates the conservative one), got gamma="
                    f"{gamma} < lam={lam}"
                )
            if not 0.0 <= beta < 1.0:
                raise ValueError(
                    f"fedac beta must be in [0, 1), got {beta}"
                )

    @property
    def n_state(self) -> int:
        return _STATE_WIDTH[self.kind]

    def init(self, x_buf: Any) -> "PackedServerState":
        """Fresh state for a run starting at packed buffer ``x_buf``:
        momentum starts at zero; FedAC's aggressive sequence starts at
        the initial point (``z₀ = x₀``)."""
        import jax.numpy as jnp

        x = jnp.asarray(np.asarray(x_buf).reshape(-1), jnp.float32)
        if self.kind == "momentum":
            bufs: Tuple[Any, ...] = (jnp.zeros_like(x),)
        else:  # fedac
            bufs = (x,)
        return PackedServerState(self.kind, self.hyper, bufs)

    def describe(self) -> Dict[str, Any]:
        """The JSON-safe spec stamp for checkpoint metadata."""
        return {"kind": self.kind, "hyper": [float(h) for h in self.hyper]}

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, PackedServerOpt)
            and self.kind == other.kind
            and self.hyper == other.hyper
        )

    def __hash__(self) -> int:
        return hash((self.kind, self.hyper))

    def __repr__(self) -> str:  # pragma: no cover
        return f"PackedServerOpt({self.kind!r}, {self.hyper})"


def server_momentum(lr: float = 1.0, momentum: float = 0.9) -> PackedServerOpt:
    """FedAvgM over packed buffers: ``x' = x − lr·(momentum·m + Δ)``.

    ``lr=1, momentum=0`` reproduces plain FedAvg bit-exactly (the step
    kernel returns the aggregate literally in that configuration).
    """
    return PackedServerOpt("momentum", (lr, momentum))


def fedac(lam: float = 1.0, gamma: float = 3.0,
          beta: float = 0.5) -> PackedServerOpt:
    """FedAC (Yuan & Ma 2020) as a server recurrence over packed
    buffers — linear-coupling acceleration of the round
    pseudo-gradient.

    ``lam`` is the conservative (FedAvg-like) step, ``gamma >= lam``
    the aggressive step over the auxiliary sequence, ``beta`` the
    coupling weight of the aggressive sequence in the next broadcast
    point.  ``lam=1, beta=0`` is plain FedAvg bit-exactly; moderate
    ``gamma``/``beta`` provably cut rounds-to-target on smooth
    objectives (benched on the quadratic + toy-logistic workloads —
    ``fedac_rounds_to_target_frac`` in ``bench.py --smoke``).
    """
    return PackedServerOpt("fedac", (lam, gamma, beta))


class PackedServerState:
    """Server-optimizer auxiliary sequences as packed f32 buffers.

    Registered as a JAX pytree (children = the buffers, aux = the
    spec), so it checkpoints through ``FedCheckpointer`` exactly like
    a params tree and restores structurally via a target built from
    :meth:`PackedServerOpt.init`.
    """

    __slots__ = ("kind", "hyper", "bufs")

    def __init__(self, kind: str, hyper: Tuple[float, ...],
                 bufs: Tuple[Any, ...]) -> None:
        self.kind = str(kind)
        self.hyper = tuple(float(h) for h in hyper)
        self.bufs = tuple(bufs)
        width = _STATE_WIDTH.get(self.kind)
        if width is not None and len(self.bufs) != width:
            raise ValueError(
                f"{self.kind} server-opt state carries {width} "
                f"buffer(s), got {len(self.bufs)}"
            )

    def __repr__(self) -> str:  # pragma: no cover
        sizes = [int(getattr(b, "size", 0)) for b in self.bufs]
        return (
            f"PackedServerState({self.kind!r}, {self.hyper}, "
            f"bufs={sizes})"
        )


import jax  # noqa: E402  (after the numpy-only spec machinery)

jax.tree_util.register_pytree_node(
    PackedServerState,
    lambda s: (tuple(s.bufs), (s.kind, s.hyper)),
    lambda aux, ch: PackedServerState(aux[0], aux[1], tuple(ch)),
)


def describe_server_opt(server_opt: Optional[Any]) -> Dict[str, Any]:
    """The checkpoint-metadata stamp for ANY ``server_opt`` argument:
    ``{"kind": "none"}`` for plain FedAvg, ``{"kind": "fedopt"}`` for a
    legacy :class:`~rayfed_tpu.fl.fedopt.ServerOptimizer` (its
    callables carry no comparable hyperparameters), and the full
    kind+hyper spec for a :class:`PackedServerOpt`.  Single producer —
    the classic and quorum loops stamp and compare exactly this."""
    if server_opt is None:
        return {"kind": "none"}
    if isinstance(server_opt, PackedServerOpt):
        return server_opt.describe()
    return {"kind": "fedopt"}


def check_snapshot_server_opt(stored: Optional[Dict[str, Any]],
                              expected: Dict[str, Any]) -> None:
    """Refuse — loudly, naming both sides — to resume a run whose
    ``server_opt`` config differs from the snapshot's.

    A silent mismatch is the nasty failure mode: restoring a plain-
    FedAvg snapshot into a momentum/FedAC run (or vice versa) resets
    the optimizer trajectory without failing anything — the loss curve
    just quietly degrades.  ``stored=None`` (a snapshot from before
    the stamp existed) is tolerated ONLY for stateless configs
    (``none``/``fedopt`` — exactly the runs old snapshots could have
    come from); a packed run demands the stamp because it also demands
    the state buffers.
    """
    if stored is None:
        if expected["kind"] in ("none", "fedopt"):
            return
        raise ValueError(
            f"checkpoint carries no server_opt stamp (written before "
            f"packed server optimization existed?) but this run uses "
            f"server_opt={expected} — its state buffers cannot be in "
            f"the snapshot; restart from scratch or drop server_opt"
        )
    stored_n = {
        "kind": str(stored.get("kind")),
        **(
            {"hyper": [float(h) for h in stored["hyper"]]}
            if "hyper" in stored else {}
        ),
    }
    if stored_n != expected:
        raise ValueError(
            f"server_opt mismatch between the run and its checkpoint: "
            f"this run is configured with {expected}, the snapshot was "
            f"written by {stored_n} — restoring would silently "
            f"{'reset' if expected['kind'] != 'none' else 'discard'} "
            f"the optimizer trajectory; resume with the matching "
            f"server_opt or point the checkpointer elsewhere"
        )


class PackedServerOptimizer:
    """One controller's server-opt runtime: the replicated state plus
    the step/resync discipline every aggregation topology shares.

    Life cycle per round (all controllers, identical arguments):

    1. ``ensure(x_buf)`` — lazy state init at the round's shared
       starting buffer (first round only).
    2. ``step_fn(x_buf)`` — the finalize-side hook handed to
       ``streaming_aggregate``/``quorum_aggregate``/
       ``hierarchy_aggregate`` (ring/classic paths call it directly on
       the assembled aggregate): ONE fused kernel, exact f32 in, the
       post-step broadcast model out.
    3. ``resync(x_buf, new_buf)`` — after the broadcast landed, every
       controller advances its state replica from the byte-agreed
       ``(x, x')`` pair.  A failed/aborted round never reaches resync,
       so retries and quorum failovers re-run the SAME step from the
       SAME state.
    """

    __slots__ = ("opt", "_state")

    def __init__(self, opt: PackedServerOpt,
                 state: Optional[PackedServerState] = None) -> None:
        if not isinstance(opt, PackedServerOpt):
            raise TypeError(
                f"PackedServerOptimizer wraps a PackedServerOpt spec, "
                f"got {type(opt).__name__} (legacy fedopt.ServerOptimizer "
                f"optimizers keep the unpacked tree path)"
            )
        self.opt = opt
        self._state: Optional[PackedServerState] = None
        if state is not None:
            self.load_state(state)

    @property
    def state(self) -> Optional[PackedServerState]:
        return self._state

    def load_state(self, state: PackedServerState) -> None:
        """Adopt a restored state (checkpoint resume); the spec must
        match — a silently adopted foreign state IS the trajectory
        reset the checkpoint guard exists to prevent."""
        if not isinstance(state, PackedServerState):
            raise TypeError(
                f"expected a PackedServerState, got {type(state).__name__}"
            )
        if (state.kind, state.hyper) != (self.opt.kind, self.opt.hyper):
            raise ValueError(
                f"restored server-opt state was written by "
                f"({state.kind}, {state.hyper}), this run is "
                f"({self.opt.kind}, {self.opt.hyper})"
            )
        self._state = state

    def ensure(self, x_buf: Any) -> None:
        if self._state is None:
            self._state = self.opt.init(x_buf)

    def step_fn(self, x_buf: Any):
        """The round's finalize-side hook: ``fn(aggregate PackedTree)
        -> post-step PackedTree`` (f32 buffer; passthrough leaves pass
        through — momentum over non-float leaves is meaningless, they
        keep the aggregate's per-leaf reduce)."""
        import jax.numpy as jnp

        from rayfed_tpu.fl.fedavg import server_step_kernel

        if self._state is None:
            raise RuntimeError("call ensure(x_buf) before step_fn")
        state = self._state
        x = jnp.asarray(np.asarray(x_buf).reshape(-1), jnp.float32)
        kernel = server_step_kernel(self.opt.kind, self.opt.hyper)

        def _step(result: Any) -> Any:
            from rayfed_tpu.fl.compression import PackedTree, PackSpec
            from rayfed_tpu.fl.quantize import QuantizedPackedTree

            if isinstance(result, QuantizedPackedTree):
                raise TypeError(
                    "the server step consumes the FINALIZED float "
                    "aggregate — got integer codes; apply it between "
                    "finalize and the downlink recode"
                )
            if not isinstance(result, PackedTree):
                raise TypeError(
                    f"the server step consumes a PackedTree aggregate, "
                    f"got {type(result).__name__}"
                )
            n = int(getattr(result.buf, "size", 0))
            if n != int(x.size):
                raise ValueError(
                    f"aggregate has {n} elements, server-opt state "
                    f"covers {int(x.size)} — the round's packed layout "
                    f"changed mid-run"
                )
            buf = kernel(x, jnp.asarray(result.buf), *state.bufs)
            spec = result.spec
            if spec.wire_dtype != "float32":
                spec = PackSpec(spec.entries, spec.treedef, "float32")
            return PackedTree(buf, result.passthrough, spec)

        return _step

    def resync(self, x_buf: Any, new_buf: Any) -> None:
        """Advance the state replica from the round's byte-agreed
        broadcast pair — every controller calls this with identical
        buffers, so every replica stays byte-identical."""
        import jax.numpy as jnp

        from rayfed_tpu.fl.fedavg import server_resync_kernel

        if self._state is None:
            raise RuntimeError("resync before any round was stepped")
        x = jnp.asarray(np.asarray(x_buf).reshape(-1), jnp.float32)
        new = jnp.asarray(np.asarray(new_buf).reshape(-1), jnp.float32)
        if int(new.size) != int(x.size):
            raise ValueError(
                f"broadcast has {int(new.size)} elements, server-opt "
                f"state covers {int(x.size)}"
            )
        bufs = server_resync_kernel(self.opt.kind, self.opt.hyper)(
            x, new, *self._state.bufs
        )
        self._state = PackedServerState(
            self.opt.kind, self.opt.hyper, tuple(bufs)
        )

    def describe(self) -> Dict[str, Any]:
        return self.opt.describe()


def reference_step(opt: PackedServerOpt, x: np.ndarray, avg: np.ndarray,
                   state: List[np.ndarray]) -> Tuple[np.ndarray, List[np.ndarray]]:
    """Pure-numpy reference of one (step, true-state-update) round —
    what the fused kernels are unit-tested against (tests/bench only;
    the production state advances via the resync kernel instead)."""
    x = np.asarray(x, np.float32)
    avg = np.asarray(avg, np.float32)
    if opt.kind == "momentum":
        lr, momentum = opt.hyper
        m = momentum * state[0] + (x - avg)
        return (x - lr * m).astype(np.float32), [m.astype(np.float32)]
    lam, gamma, beta = opt.hyper
    delta = x - avg
    y_new = x - lam * delta
    z_new = state[0] - gamma * delta
    x_new = (1.0 - beta) * y_new + beta * z_new
    return x_new.astype(np.float32), [z_new.astype(np.float32)]
