"""Wire compression for federated exchanges.

Cross-party pushes ride DCN; at ResNet/Llama scale the parameter payload
is the round's dominant wire cost.  Casting float leaves to bfloat16 for
the wire halves the bytes with ~3 decimal digits kept — the standard FL
compression baseline (more aggressive schemes — top-k sparsification,
int8 — trade convergence; bf16 is numerically safe for parameter
averaging when the accumulate runs in f32, which
:func:`rayfed_tpu.fl.tree_average` does).

Two wire forms:

**Per-leaf** (the original): every float leaf is cast individually via
``tree_map`` — N leaves means N XLA dispatches per direction, and the
wire codec moves N separate buffers with N manifest entries.

**Packed** (:class:`PackedTree`, the fast path): all float leaves are
flattened into ONE contiguous wire-dtype buffer by a single fused
cast+concat kernel (one XLA dispatch for the whole tree), with a static
spec carrying per-leaf ``(offset, size, shape, dtype)`` so decode is one
fused cast (or zero casts, when the consumer wants the wire dtype) plus
per-leaf **zero-copy views** into the buffer.  Non-float leaves ride
alongside untouched.  Because ``PackedTree`` is a registered JAX pytree,
the transport's tensor codec sees exactly one large array leaf — which
crosses the wire as a single zero-copy buffer (shard-streamed and
pipelined above :data:`rayfed_tpu.transport.wire.SHARD_STREAM_THRESHOLD`;
at :data:`~rayfed_tpu.transport.wire.STRIPE_MIN_BYTES` and above its
4 MB chunks additionally fan out round-robin across the per-destination
connection pool, with the device→host fetch and CRC of chunk *k+1*
overlapping the socket write of chunk *k*, and stream sends snapshot
into a reusable page-aligned send arena instead of allocating per round
— see ``docs/source/send_path.rst``) instead of dozens of small ones —
and aggregation arithmetic (:func:`rayfed_tpu.fl.tree_average`) fuses
over the whole model as one elementwise op.

Both :func:`pack_tree` and :func:`unpack_tree` are traceable: inside a
``jit`` (e.g. :func:`rayfed_tpu.models.resnet.make_fed_train_step`) the
cast/slice/concat ops fuse into the surrounding program, so a party's
whole local round — unpack, train, repack — is one compiled call.

Usage (each side of the exchange):

    push:     fed_obj = train.remote(...)  # task returns compress(tree)
    consume:  params = decompress(fed.get(obj), jnp.float32)

``compress(tree, packed=True)`` selects the packed form; ``decompress``
accepts either form transparently.

**Codec/aggregator split.**  This module is pure *codec*: wire forms
(:class:`PackedTree`, and the shared-grid integer form
:class:`~rayfed_tpu.fl.quantize.QuantizedPackedTree` from
:mod:`rayfed_tpu.fl.quantize`, re-exported here) plus the sender-side
residual state that keeps lossy codecs convergent
(:class:`ErrorFeedback` for plain dtype narrowing,
:class:`~rayfed_tpu.fl.quantize.QuantCompressor` for the grid codec).
Nothing here folds: the *aggregator* half — the fold kernels, the
single finalizes, and the per-wire-form kernel selection — lives in
:mod:`rayfed_tpu.fl.fedavg` (``packed_weighted_sum`` /
``packed_quantized_sum`` and their shared finalizes) and
:mod:`rayfed_tpu.fl.streaming` (the streamed/striped folds), which
pick a float or widening-integer accumulate from the codec's wire
dtype.  Decode paths dispatch through ``tree.unpack`` so every wire
form knows how to restore itself.
"""

from __future__ import annotations

import functools
import math
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def cast_floats(tree: Any, dtype) -> Any:
    """Cast every floating leaf to ``dtype`` (ints/bools untouched).

    Per-leaf path: one dispatch per leaf when called eagerly.  Inside a
    jit the casts fuse; for eager hot paths prefer the packed form.
    """

    def _cast(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf

    return jax.tree_util.tree_map(_cast, tree)


class PackSpec(NamedTuple):
    """Static description of a packed tree (hashable: jit/aux friendly).

    ``entries`` — one tuple per original leaf, in flatten order:
    ``("f", offset, size, shape, orig_dtype_name)`` for packed float
    leaves (offset/size in *elements* of the wire dtype), or
    ``("p", index)`` for passthrough leaves.  ``treedef`` — the original
    tree structure.  ``wire_dtype`` — dtype name of the packed buffer.
    """

    entries: Tuple
    treedef: Any
    wire_dtype: str


class PackedTree:
    """Wire form of a pytree: one contiguous float buffer + passthrough.

    Registered as a JAX pytree node, so it flows through ``tree_map``,
    ``jit`` and the transport codec like any container; its children are
    ``(buf, *passthrough)`` and the :class:`PackSpec` rides as static
    aux data (pickled with the container skeleton on the wire).
    """

    __slots__ = ("buf", "passthrough", "spec")

    def __init__(self, buf: Any, passthrough: Tuple, spec: PackSpec) -> None:
        self.buf = buf
        self.passthrough = tuple(passthrough)
        self.spec = spec

    @property
    def nbytes(self) -> int:
        total = getattr(self.buf, "nbytes", 0)
        for leaf in self.passthrough:
            total += getattr(leaf, "nbytes", 0)
        return total

    def unpack(self, dtype: Any = None) -> Any:
        """Reconstruct the original tree; see :func:`unpack_tree`."""
        return unpack_tree(self, dtype)

    def __reduce__(self):
        # Explicit reduce: keeps the pickled skeleton stable under
        # __slots__ and admits the class through the restricted
        # unpickler by name (see serialization._INTERNAL_ALLOWED).
        return (PackedTree, (self.buf, self.passthrough, self.spec))

    def __repr__(self) -> str:  # pragma: no cover
        n = sum(1 for e in self.spec.entries if e[0] == "f")
        return (
            f"PackedTree({n} float leaves packed as "
            f"{self.spec.wire_dtype}[{getattr(self.buf, 'shape', '?')}], "
            f"{len(self.passthrough)} passthrough)"
        )


jax.tree_util.register_pytree_node(
    PackedTree,
    lambda pt: ((pt.buf, *pt.passthrough), pt.spec),
    lambda spec, children: PackedTree(children[0], tuple(children[1:]), spec),
)


def _is_float_leaf(leaf: Any) -> bool:
    return hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating)


@functools.lru_cache(maxsize=None)
def _jit_packer(wire_dtype: str):
    """ONE fused cast+concat kernel for a whole leaf list (single dispatch)."""
    dt = jnp.dtype(wire_dtype)

    @jax.jit
    def _pack(leaves):
        return jnp.concatenate([l.reshape(-1).astype(dt) for l in leaves])

    return _pack


@functools.partial(jax.jit, static_argnums=(1, 2))
def _jit_unpacker(buf, entries: Tuple, dtype: str):
    """Fused cast + static slices: the whole decode is one XLA program."""
    cast = buf.astype(jnp.dtype(dtype)) if dtype else buf
    return tuple(
        jax.lax.slice(cast, (e[1],), (e[1] + e[2],)).reshape(e[3])
        for e in entries
        if e[0] == "f"
    )


def _is_traced(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


def pack_tree(tree: Any, wire_dtype: Any = jnp.bfloat16) -> PackedTree:
    """Pack every float leaf of ``tree`` into one ``wire_dtype`` buffer.

    JAX-array (or traced) leaves go through a single jitted fused
    cast+concat — one dispatch for the whole tree instead of one astype
    per leaf.  Pure-numpy trees are packed host-side with one output
    allocation.  Leaf order is flatten order; offsets are deterministic,
    so two parties packing the same structure produce identical specs
    (required for jit-cache stability across rounds and parties).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    wire_name = np.dtype(wire_dtype).name
    entries = []
    float_leaves = []
    passthrough = []
    offset = 0
    for leaf in leaves:
        if _is_float_leaf(leaf):
            shape = tuple(int(d) for d in leaf.shape)
            size = math.prod(shape) if shape else 1
            entries.append(
                ("f", offset, size, shape, np.dtype(leaf.dtype).name)
            )
            float_leaves.append(leaf)
            offset += size
        else:
            entries.append(("p", len(passthrough)))
            passthrough.append(leaf)
    spec = PackSpec(tuple(entries), treedef, wire_name)

    if not float_leaves:
        buf = np.zeros(0, dtype=np.dtype(wire_name))
    elif any(isinstance(l, jax.Array) or _is_traced(l) for l in float_leaves):
        buf = _jit_packer(wire_name)(float_leaves)
    else:
        # Host path: one allocation, per-leaf vectorized copies (numpy
        # has no dispatch-per-op overhead to amortize).
        buf = np.empty(offset, dtype=np.dtype(wire_name))
        pos = 0
        for leaf in float_leaves:
            n = math.prod(leaf.shape) if leaf.shape else 1
            buf[pos : pos + n] = np.asarray(leaf).reshape(-1)  # casts in-place
            pos += n
    return PackedTree(buf, tuple(passthrough), spec)


def unpack_tree(packed: PackedTree, dtype: Any = None) -> Any:
    """Reconstruct the original tree from a :class:`PackedTree`.

    ``dtype=None`` keeps the wire dtype — on a host buffer the float
    leaves come back as **zero-copy views** into the packed buffer (no
    cast, no allocation).  With a target ``dtype`` the whole buffer is
    cast ONCE (one fused kernel on device, one vectorized pass on host)
    and the per-leaf reshapes are views of that single allocation.
    Traceable: inside jit the slices/casts fuse into the caller.
    """
    entries, treedef, wire_name = packed.spec
    buf = packed.buf
    dtype_name = None if dtype is None else np.dtype(dtype).name
    if dtype_name == wire_name:
        dtype_name = None

    float_views: Tuple = ()
    if any(e[0] == "f" for e in entries):
        if isinstance(buf, jax.Array) or _is_traced(buf):
            float_views = _jit_unpacker(buf, entries, dtype_name)
        else:
            host = np.asarray(buf)
            if dtype_name is not None:
                host = host.astype(np.dtype(dtype_name))
            float_views = tuple(
                host[e[1] : e[1] + e[2]].reshape(e[3])
                for e in entries
                if e[0] == "f"
            )

    leaves = []
    fi = 0
    for entry in entries:
        if entry[0] == "f":
            leaves.append(float_views[fi])
            fi += 1
        else:
            leaves.append(packed.passthrough[entry[1]])
    return jax.tree_util.tree_unflatten(treedef, leaves)


@functools.lru_cache(maxsize=None)
def _ef_kernel(wire_name: str):
    """Fused error-feedback step over the packed f32 buffer: add the
    carried residual, quantize to the wire dtype, carry the new
    quantization error.  One XLA program for the whole model."""
    dt = jnp.dtype(wire_name)

    @jax.jit
    def _step(buf32, resid):
        corrected = buf32 + resid
        wire_buf = corrected.astype(dt)
        new_resid = corrected - wire_buf.astype(jnp.float32)
        return wire_buf, new_resid

    return _step


class ErrorFeedback:
    """Residual error feedback keeping lossy wire dtypes convergent.

    Each :meth:`compress` call adds the residual quantization error of
    the PREVIOUS round to the outgoing update before casting to the wire
    dtype, then carries the new round's error forward (the EF14/EF-SGD
    scheme: what the wire dropped this round is re-sent next round
    instead of being lost forever).  With bf16 the correction is small;
    with aggressive dtypes (fp8) it is the difference between
    convergence and a noise floor — see the slow convergence test.

    Stateful per sender and per stream: keep one instance per outgoing
    compressed stream (e.g. one per trainer), and :meth:`reset` it when
    the tree structure changes.
    """

    def __init__(self, wire_dtype: Any = jnp.bfloat16) -> None:
        self._wire_name = np.dtype(wire_dtype).name
        self._resid: Any = None

    @property
    def residual(self) -> Any:
        """The carried f32 residual buffer (None before the first round)."""
        return self._resid

    def reset(self) -> None:
        self._resid = None

    def compress(self, tree: Any) -> PackedTree:
        """Pack ``tree`` with error feedback; returns the wire PackedTree."""
        packed32 = pack_tree(tree, jnp.float32)
        buf32 = packed32.buf
        if self._resid is None:
            self._resid = jnp.zeros(buf32.shape, jnp.float32)
        elif self._resid.shape != buf32.shape:
            raise ValueError(
                f"tree structure changed under error feedback "
                f"({self._resid.shape} residual vs {buf32.shape} buffer) "
                f"— call reset() when switching models"
            )
        wire_buf, self._resid = _ef_kernel(self._wire_name)(
            buf32, self._resid
        )
        spec = PackSpec(
            packed32.spec.entries, packed32.spec.treedef, self._wire_name
        )
        return PackedTree(wire_buf, packed32.passthrough, spec)


def compress(tree: Any, *, packed: bool = False, wire_dtype: Any = jnp.bfloat16):
    """Wire form of a float param tree (half the push bytes at bf16).

    ``packed=True`` selects the fused single-buffer form
    (:class:`PackedTree`): one cast kernel, one wire buffer, zero-copy
    decode — the fast path for whole-model pushes.
    """
    if packed:
        return pack_tree(tree, wire_dtype)
    return cast_floats(tree, wire_dtype)


def decompress(tree: Any, dtype=jnp.float32) -> Any:
    """Restore a wire-compressed tree (any form) to the compute dtype.

    Dispatches through ``tree.unpack`` so subclasses with their own
    decode (the shared-grid integer form dequantizes first) restore
    correctly.
    """
    if isinstance(tree, PackedTree):
        return tree.unpack(dtype)
    return cast_floats(tree, dtype)


# Re-export the shared-grid integer codec: one import surface for wire
# forms.  Lazy (PEP 562) because rayfed_tpu.fl.quantize subclasses
# PackedTree and therefore imports THIS module first — an eager import
# here would be circular when quantize is imported before compression.
_QUANTIZE_EXPORTS = (
    "QuantCompressor",
    "QuantGrid",
    "QuantizedPackedTree",
    "dequantize_packed",
    "make_round_grid",
    "quantize_packed",
)


def __getattr__(name: str):
    if name in _QUANTIZE_EXPORTS:
        from rayfed_tpu.fl import quantize

        return getattr(quantize, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


__all__ = [
    "PackSpec",
    "PackedTree",
    "ErrorFeedback",
    "cast_floats",
    "compress",
    "decompress",
    "pack_tree",
    "unpack_tree",
    "QuantCompressor",
    "QuantGrid",
    "QuantizedPackedTree",
    "dequantize_packed",
    "make_round_grid",
    "quantize_packed",
]
