"""Wire compression for federated exchanges.

Cross-party pushes ride DCN; at ResNet/Llama scale the parameter payload
is the round's dominant wire cost.  Casting float leaves to bfloat16 for
the wire halves the bytes with ~3 decimal digits kept — the standard FL
compression baseline (more aggressive schemes — top-k sparsification,
int8 — trade convergence; bf16 is numerically safe for parameter
averaging when the accumulate runs in f32, which
:func:`rayfed_tpu.fl.tree_average` does).

Usage (each side of the exchange):

    push:     fed_obj = train.remote(...)  # task returns compress(tree)
    consume:  params = decompress(fed.get(obj), jnp.float32)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def cast_floats(tree: Any, dtype) -> Any:
    """Cast every floating leaf to ``dtype`` (ints/bools untouched)."""

    def _cast(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf

    return jax.tree_util.tree_map(_cast, tree)


def compress(tree: Any) -> Any:
    """bf16 wire form of a float param tree (half the push bytes)."""
    return cast_floats(tree, jnp.bfloat16)


def decompress(tree: Any, dtype=jnp.float32) -> Any:
    """Restore a wire-compressed tree to the compute dtype."""
    return cast_floats(tree, dtype)
