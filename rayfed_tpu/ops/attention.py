"""Reference multi-head attention + the blockwise online-softmax core.

All attention in the framework flows through two functions:

- :func:`dot_product_attention` — the plain O(T²) reference used for
  testing and short sequences; einsum-based so XLA maps it onto the MXU.
- :func:`blockwise_accumulate` — one online-softmax accumulation step
  over a K/V block.  Ring attention (``ring_attention.py``) uses it with
  K/V blocks arriving over ``ppermute``; it is the same recurrence a
  flash-attention kernel runs per tile (m/l/o running max, normalizer,
  weighted sum — numerically identical to full softmax).

Layout convention everywhere: ``[batch, seq, heads, head_dim]`` (BTHD).
Accumulation is float32 regardless of input dtype (bf16-safe).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp() flushable


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    mask: Optional[jax.Array] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
    window: Optional[int] = None,
) -> jax.Array:
    """Plain softmax attention, BTHD layout.

    ``q_offset``/``kv_offset`` are the global positions of the first query
    / key token — used when q and k are shards of a longer sequence (the
    causal mask must compare *global* positions).  ``window`` (requires
    ``causal``) restricts each query to its last ``window`` keys.
    """
    if window is not None:
        if not causal:
            raise ValueError("window= requires causal=True")
        if window < 1:
            # Same contract as flash_attention: window=0 would mask every
            # score, and softmax of an all-NEG_INF row is silently uniform.
            raise ValueError(f"window must be >= 1, got {window}")
    orig_dtype = q.dtype
    head_dim = q.shape[-1]
    scale = sm_scale if sm_scale is not None else head_dim**-0.5
    qf = q.astype(jnp.float32) * scale
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = kv_offset + jnp.arange(k.shape[1])
        causal_mask = q_pos[:, None] >= k_pos[None, :]
        if window is not None:
            causal_mask = causal_mask & (
                q_pos[:, None] - k_pos[None, :] < window
            )
        s = jnp.where(causal_mask[None, None, :, :], s, NEG_INF)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    # Guard fully-masked rows (can happen for causal shards where every
    # key is in the future): softmax of all-NEG_INF must yield zeros.
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.max(s, axis=-1, keepdims=True) <= NEG_INF / 2, 0.0, p)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(orig_dtype)


def as_attn_fn(sharded, built_causal: bool, built_scale, builder: str):
    """Give a shard_map'd (q, k, v) attention the ``attn_fn`` signature.

    Model code (:func:`mha`, ``apply_llama``) calls ``attn_fn(q, k, v,
    causal=..., sm_scale=...)``; a ring/Ulysses builder bakes masking and
    scale in at build time, so the wrapper accepts those kwargs and
    rejects *conflicting* values instead of silently ignoring them.
    """

    def apply(q, k, v, *, causal=None, sm_scale=None, mask=None, window=None):
        if mask is not None:
            raise ValueError(
                f"{builder} attention does not support a dense mask"
            )
        if window is not None:
            # Accepted-then-rejected so LlamaConfig(sliding_window=...)
            # with a ring/Ulysses attn_fn fails with this explanation,
            # not a bare unexpected-keyword TypeError.
            raise ValueError(
                f"{builder} attention does not support sliding-window "
                f"attention (window={window}); drop sliding_window or use "
                f"the flash/dense attention path"
            )
        if causal is not None and bool(causal) != built_causal:
            raise ValueError(
                f"causal={causal} conflicts with the {builder}(...) "
                f"build-time setting causal={built_causal}"
            )
        if sm_scale is not None:
            # A builder given sm_scale=None applies the conventional
            # d**-0.5 — an explicit caller value equal to that effective
            # scale is agreement, not conflict.
            effective = (
                built_scale if built_scale is not None
                else q.shape[-1] ** -0.5
            )
            # isclose, not ==: 1/math.sqrt(d), d**-0.5, and an f32-stored
            # copy of either differ by ulps — agreement, not conflict.
            # rel_tol covers float32 provenance (~1e-7 ulp).
            if not math.isclose(sm_scale, effective, rel_tol=1e-6):
                raise ValueError(
                    f"sm_scale={sm_scale} conflicts with the {builder}(...) "
                    f"build-time scale {effective}"
                )
        return sharded(q, k, v)

    return apply


def mha(
    x: jax.Array,
    wq: jax.Array,
    wk: jax.Array,
    wv: jax.Array,
    wo: jax.Array,
    *,
    num_heads: int,
    causal: bool = False,
    attn_fn=None,
) -> jax.Array:
    """Full MHA block: project, attend, merge.  ``x``: [B, T, D_model].

    ``wq/wk/wv``: [D_model, H*Dh]; ``wo``: [H*Dh, D_model].  ``attn_fn``
    lets callers swap in ring/Ulysses/pallas attention (same signature as
    :func:`dot_product_attention`).
    """
    b, t, d_model = x.shape
    attn_fn = attn_fn or dot_product_attention
    q = (x @ wq).reshape(b, t, num_heads, -1)
    k = (x @ wk).reshape(b, t, num_heads, -1)
    v = (x @ wv).reshape(b, t, num_heads, -1)
    o = attn_fn(q, k, v, causal=causal)
    return o.reshape(b, t, -1) @ wo


def blockwise_accumulate(
    q: jax.Array,
    k_blk: jax.Array,
    v_blk: jax.Array,
    o_acc: jax.Array,
    m_acc: jax.Array,
    l_acc: jax.Array,
    *,
    scale: float,
    q_offset,
    kv_offset,
    causal: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One online-softmax step over a K/V block (the flash recurrence).

    State: ``o_acc`` [B,Tq,H,D] un-normalized output, ``m_acc``/``l_acc``
    [B,H,Tq] running row-max / normalizer, all float32.  ``q_offset`` /
    ``kv_offset`` may be traced scalars (ring step index × block length);
    the global-position causal mask also handles fully-future blocks
    (every element masked → zero contribution via the m/l guards below).
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k_blk.astype(jnp.float32)
    )
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = kv_offset + jnp.arange(k_blk.shape[1])
        causal_mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(causal_mask[None, None, :, :], s, NEG_INF)

    m_blk = jnp.max(s, axis=-1)  # [B,H,Tq]
    m_new = jnp.maximum(m_acc, m_blk)
    # exp(NEG_INF - NEG_INF) would be 1 on fully-masked rows; clamp the
    # shift so masked rows contribute exp(NEG_INF - 0) == 0 instead.
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])  # [B,H,Tq,Tk]
    correction = jnp.exp(jnp.where(m_acc <= NEG_INF / 2, NEG_INF, m_acc) - m_safe)
    l_new = l_acc * correction + jnp.sum(p, axis=-1)
    o_blk = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
    o_new = o_acc * correction.transpose(0, 2, 1)[..., None] + o_blk
    return o_new, m_new, l_new


def blockwise_finalize(o_acc: jax.Array, l_acc: jax.Array, dtype) -> jax.Array:
    """Normalize the accumulated output; fully-masked rows become zeros."""
    l_safe = jnp.where(l_acc == 0.0, 1.0, l_acc)
    out = o_acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(dtype)


def init_blockwise_state(
    q: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    b, tq, h, d = q.shape
    o = jnp.zeros((b, tq, h, d), jnp.float32)
    m = jnp.full((b, h, tq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tq), jnp.float32)
    return o, m, l
