"""Pallas TPU flash attention (tiled online-softmax) with custom VJP.

The MXU wants big tiles streamed through VMEM; materializing the [T, T]
score matrix in HBM wastes the bandwidth that is the usual bottleneck.
This kernel keeps one q tile resident in VMEM and streams k/v tiles
through it, carrying the online-softmax state (running max m, normalizer
l, un-normalized accumulator) in VMEM scratch across the innermost grid
dimension — TPU grids execute sequentially, so scratch persists across
the kv loop.  Matches `rayfed_tpu.ops.attention.dot_product_attention`
numerically (same recurrence as ``blockwise_accumulate``).

Backward is a memory-efficient blockwise recompute in plain JAX (scan
over kv blocks, O(T·block) live memory) using the saved per-row
log-sum-exp — the standard flash-attention backward formulation.

Runs in interpret mode off-TPU (auto-detected), so the CPU test mesh
exercises the same code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable off-TPU; kernels then run interpreted
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _flash_fwd_kernel(
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, block_k, d)
    v_ref,  # (1, block_k, d)
    o_ref,  # (1, block_q, d)
    lse_ref,  # (1, block_q, 128) — lane-broadcast so the block is tileable
    acc_ref,  # VMEM (block_q, d) f32
    m_ref,  # VMEM (block_q, 128) f32
    l_ref,  # VMEM (block_q, 128) f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Under causality a kv block strictly after the last query row of this
    # q block contributes nothing — skip its matmuls entirely.  Offsets
    # are static (compile-time) global positions of the first q/kv token.
    should_compute = True
    if causal:
        should_compute = (
            kv_offset + ki * block_k
            <= q_offset + qi * block_q + block_q - 1
        )

    @pl.when(should_compute)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if causal:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_offset + ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_ref[:, :1]  # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.maximum(jnp.max(s, axis=1, keepdims=True), m_prev)
        # Fully-masked rows keep m_cur == NEG_INF; clamp the shift so
        # their p = exp(NEG_INF - 0) == 0 instead of exp(0) == 1 (same
        # guard as attention.blockwise_accumulate).
        m_safe = jnp.where(m_cur <= NEG_INF / 2, 0.0, m_cur)
        p = jnp.exp(s - m_safe)
        correction = jnp.exp(
            jnp.where(m_prev <= NEG_INF / 2, NEG_INF, m_prev) - m_safe
        )
        l_cur = l_prev * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_ref[...] = acc_ref[...] * correction + pv
        m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur, l_ref.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        l_final = l_ref[:, :1]
        l_safe = jnp.where(l_final == 0.0, 1.0, l_final)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (
            m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-37))
        ).astype(lse_ref.dtype)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
    interpret: bool,
):
    """Run the pallas kernel on [BH, T, D] inputs; returns (o, lse).

    On the compiled TPU path the head dim is zero-padded to a multiple of
    128 (MXU lane width) — zeros in the contracting dim don't change
    q·kᵀ, and padded v columns produce padded output columns we slice
    off.  The lse output is lane-broadcast to (bh, t_q, 128) so its block
    satisfies the TPU (8, 128) tiling rule, then lane 0 is taken.
    """
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    if t_q % block_q or t_k % block_k:
        raise ValueError(
            f"block sizes ({block_q}, {block_k}) must divide the "
            f"sequence lengths ({t_q}, {t_k})"
        )
    if not interpret and (block_q % 8 or block_k % 8):
        raise ValueError(
            f"TPU tiling requires block sizes divisible by 8, got "
            f"({block_q}, {block_k})"
        )
    d_pad = d if interpret else ((d + 127) // 128) * 128
    if d_pad != d:
        pad = [(0, 0), (0, 0), (0, d_pad - d)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    grid = (bh, t_q // block_q, t_k // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
        kv_offset=kv_offset,
    )
    scratch = [
        pltpu.VMEM((block_q, d_pad), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q, d_pad), q.dtype),
            jax.ShapeDtypeStruct((bh, t_q, 128), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    if d_pad != d:
        o = o[..., :d]
    return o, lse[..., 0]


def _flash_bwd_dq_kernel(
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, block_k, d)
    v_ref,  # (1, block_k, d)
    do_ref,  # (1, block_q, d)
    lse_ref,  # (1, block_q, 128)
    delta_ref,  # (1, block_q, 128)
    dq_ref,  # out (1, block_q, d)
    acc_ref,  # VMEM (block_q, d) f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
):
    """dQ = (P ∘ (dO Vᵀ − D)) K · scale, accumulated over kv blocks."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    should_compute = True
    if causal:
        should_compute = (
            kv_offset + ki * block_k <= q_offset + qi * block_q + block_q - 1
        )

    @pl.when(should_compute)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_offset + ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        # exp(s - lse); fully-masked rows have lse ~ NEG_INF — zero them.
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, block_k, d)
    v_ref,  # (1, block_k, d)
    do_ref,  # (1, block_q, d)
    lse_ref,  # (1, block_q, 128)
    delta_ref,  # (1, block_q, 128)
    dk_ref,  # out (1, block_k, d)
    dv_ref,  # out (1, block_k, d)
    dk_acc_ref,  # VMEM (block_k, d) f32
    dv_acc_ref,  # VMEM (block_k, d) f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
):
    """dV = Pᵀ dO and dK = dSᵀ Q · scale, accumulated over q blocks."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    should_compute = True
    if causal:
        # A q block strictly before this kv block sees none of it.
        should_compute = (
            q_offset + qi * block_q + block_q - 1 >= kv_offset + ki * block_k
        )

    @pl.when(should_compute)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if causal:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_offset + ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dv_acc_ref[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # pᵀ @ do: (block_k, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # dsᵀ @ (q·scale): scale already folded into q

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_backward_pallas(
    q, k, v, o, lse, do, *, scale: float, causal: bool,
    block_q: int, block_k: int, q_offset: int, kv_offset: int, interpret: bool,
):
    """Pallas flash backward on [BH, T, D] inputs → (dq, dk, dv).

    Two tiled kernels: dQ iterates kv blocks innermost (accumulator over
    the q row block), dK/dV iterates q blocks innermost (accumulators
    over the kv block).  ``delta = rowsum(dO ∘ O)`` and the saved lse are
    lane-broadcast to 128 so their blocks satisfy TPU (8, 128) tiling.
    """
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    if t_q % block_q or t_k % block_k:
        raise ValueError(
            f"block sizes ({block_q}, {block_k}) must divide the "
            f"sequence lengths ({t_q}, {t_k})"
        )
    d_pad = d if interpret else ((d + 127) // 128) * 128
    if d_pad != d:
        pad = [(0, 0), (0, 0), (0, d_pad - d)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        o = jnp.pad(o, pad)
        do = jnp.pad(do, pad)
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # (bh, t_q)
    lse_b = jnp.broadcast_to(lse[..., None], (bh, t_q, 128))
    delta_b = jnp.broadcast_to(delta[..., None], (bh, t_q, 128))

    common = dict(
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
        kv_offset=kv_offset,
    )
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(bh, t_q // block_q, t_k // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_q, d_pad), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d_pad), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(bh, t_k // block_k, t_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d_pad), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_k, d_pad), k.dtype),
            jax.ShapeDtypeStruct((bh, t_k, d_pad), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d_pad), jnp.float32),
            pltpu.VMEM((block_k, d_pad), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b)

    if d_pad != d:
        dq, dk, dv = dq[..., :d], dk[..., :d], dv[..., :d]
    return dq, dk, dv


def _flash_backward_blockwise(
    q, k, v, o, lse, do, *, scale: float, causal: bool, block_k: int,
    q_offset: int = 0, kv_offset: int = 0,
):
    """Blockwise flash backward in plain JAX ([BH, T, D] layout, f32).

    Standard formulation: with P = exp(S - lse) and D = rowsum(dO ∘ O),
    dV = Pᵀ dO, dS = P ∘ (dO Vᵀ − D), dQ = dS K·scale, dK = dSᵀ Q·scale.
    Scans over kv blocks so only one [T_q, block_k] score tile is live.
    """
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_k = min(block_k, t_k)
    num_blocks = t_k // block_k
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32).reshape(bh, num_blocks, block_k, d)
    vf = v.astype(jnp.float32).reshape(bh, num_blocks, block_k, d)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # (bh, t_q)
    q_pos = q_offset + jnp.arange(t_q)

    def body(dq_acc, blk):
        k_blk, v_blk, j = blk  # (bh, block_k, d), index
        s = jnp.einsum("bqd,bkd->bqk", qf * scale, k_blk)
        if causal:
            k_pos = kv_offset + j * block_k + jnp.arange(block_k)
            s = jnp.where(q_pos[None, :, None] >= k_pos[None, None, :], s, NEG_INF)
        # Masked entries must contribute 0 — for fully-masked rows lse is
        # ~NEG_INF too, and exp(s - lse) would be exp(0) = 1.
        p = jnp.where(
            s <= NEG_INF / 2, 0.0, jnp.exp(s - lse[..., None])
        )  # (bh, t_q, block_k)
        dv = jnp.einsum("bqk,bqd->bkd", p, dof)
        dp = jnp.einsum("bqd,bkd->bqk", dof, v_blk)
        ds = p * (dp - delta[..., None])
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, k_blk) * scale
        dk = jnp.einsum("bqk,bqd->bkd", ds, qf) * scale
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((bh, t_q, d), jnp.float32)
    dq, (dk, dv) = jax.lax.scan(
        body,
        dq0,
        (kf.transpose(1, 0, 2, 3), vf.transpose(1, 0, 2, 3), jnp.arange(num_blocks)),
    )
    dk = dk.transpose(1, 0, 2, 3).reshape(bh, t_k, d)
    dv = dv.transpose(1, 0, 2, 3).reshape(bh, t_k, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9)
)
def _flash_bthd(
    q, k, v, scale, causal, block_q, block_k, q_offset, kv_offset, interpret
):
    out, _ = _flash_fwd_bthd(
        q, k, v, scale, causal, block_q, block_k, q_offset, kv_offset, interpret
    )
    return out


def _bthd_to_bht(x):  # [B,T,H,D] -> [B*H, T, D]
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _bht_to_bthd(x, b, h):  # [B*H, T, D] -> [B,T,H,D]
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd_bthd(
    q, k, v, scale, causal, block_q, block_k, q_offset, kv_offset, interpret
):
    b, t, h, d = q.shape
    o, lse = _flash_forward(
        _bthd_to_bht(q),
        _bthd_to_bht(k),
        _bthd_to_bht(v),
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
        kv_offset=kv_offset,
        interpret=interpret,
    )
    out = _bht_to_bthd(o, b, h)
    return out, (q, k, v, out, lse)


def _flash_bwd_bthd(
    scale, causal, block_q, block_k, q_offset, kv_offset, interpret, res, g
):
    q, k, v, out, lse = res
    b, t, h, d = q.shape
    dq, dk, dv = _flash_backward_pallas(
        _bthd_to_bht(q),
        _bthd_to_bht(k),
        _bthd_to_bht(v),
        _bthd_to_bht(out),
        lse,
        _bthd_to_bht(g),
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
        kv_offset=kv_offset,
        interpret=interpret,
    )
    return _bht_to_bthd(dq, b, h), _bht_to_bthd(dk, b, h), _bht_to_bthd(dv, b, h)


_flash_bthd.defvjp(_flash_fwd_bthd, _flash_bwd_bthd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    # Defaults from an on-chip sweep (v5e, T=2048-4096, fwd+bwd): a small
    # q tile keeps both bwd accumulators resident while a wide kv tile
    # amortizes the per-tile loop overhead.
    block_q: int = 128,
    block_k: int = 512,
    q_offset: int = 0,
    kv_offset: int = 0,
    mask: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Tiled flash attention, BTHD layout — drop-in for
    :func:`rayfed_tpu.ops.attention.dot_product_attention` (also as the
    ``attn_fn`` of Ulysses attention).

    ``q_offset``/``kv_offset`` are *static* global positions of the first
    q/kv token (sharded-causal use).  Arbitrary dense ``mask`` is not
    supported by the tiled kernel — use ``dot_product_attention``.
    ``interpret=None`` auto-selects the pallas interpreter off-TPU so the
    same code path runs on the CPU test mesh.
    """
    if mask is not None:
        raise ValueError(
            "flash_attention does not support a dense mask; use "
            "dot_product_attention (or causal=True with offsets)"
        )
    if interpret is None:
        interpret = not _on_tpu()
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    # Blocks must divide the sequence lengths: shrink the requested size
    # to the largest 8-aligned divisor (e.g. T=1280 with block_k=512 →
    # 256) instead of erroring on any non-multiple length.
    block_q = _fit_block(q.shape[1], block_q)
    block_k = _fit_block(k.shape[1], block_k)
    return _flash_bthd(
        q, k, v, scale, causal, block_q, block_k,
        int(q_offset), int(kv_offset), interpret,
    )


def _fit_block(t: int, want: int) -> int:
    """Largest block <= want that divides t (8-aligned when possible)."""
    b = min(want, t)
    while b > 8 and (t % b or b % 8):
        b -= 8
    if t % b == 0:
        return b
    while b > 1 and t % b:  # tiny/odd sequence lengths (tests)
        b -= 1
    return max(b, 1)
