"""Pallas TPU flash attention (tiled online-softmax) with custom VJP.

The MXU wants big tiles streamed through VMEM; materializing the [T, T]
score matrix in HBM wastes the bandwidth that is the usual bottleneck.
This kernel keeps one q tile resident in VMEM and streams k/v tiles
through it, carrying the online-softmax state (running max m, normalizer
l, un-normalized accumulator) in VMEM scratch across the innermost grid
dimension — TPU grids execute sequentially, so scratch persists across
the kv loop.  Matches `rayfed_tpu.ops.attention.dot_product_attention`
numerically (same recurrence as ``blockwise_accumulate``).

Backward is two tiled pallas kernels (dQ and dK/dV) that recompute the
score tile from the saved per-row log-sum-exp — the standard
flash-attention backward formulation, O(T·block) live memory.

Runs in interpret mode off-TPU (auto-detected), so the CPU test mesh
exercises the same code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable off-TPU; kernels then run interpreted
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _causal_dispatch(
    compute, causal, qi, ki, block_q, block_k, q_offset, kv_offset,
    window=None,
):
    """Run ``compute(masked)`` under the causal block classification.

    A block strictly past the diagonal contributes nothing (skipped); a
    block entirely at-or-before it needs no mask; only blocks straddling
    the diagonal pay for the iota/compare/select.  Shared by all three
    kernels so the boundary conditions cannot drift.

    ``window`` (sliding-window attention, requires ``causal``): query q
    sees keys in ``(q − window, q]``.  Blocks entirely below the band
    are skipped the same way fully-future blocks are — the kernel's
    FLOPs scale with O(T·window) instead of O(T²/2).
    """
    if not causal:
        compute(False)
        return
    q_first = q_offset + qi * block_q
    q_last = q_first + block_q - 1
    kv_first = kv_offset + ki * block_k
    kv_last = kv_first + block_k - 1
    active = kv_first <= q_last
    straddles = kv_last > q_first
    if window is not None:
        # Band-active: some pair satisfies q − k < window.
        active = active & (kv_last > q_first - window)
        # Band-straddling: the OLDEST pair falls outside the window.
        straddles = straddles | (q_last - kv_first >= window)

    @pl.when(active & jnp.logical_not(straddles))
    def _full():
        compute(False)

    @pl.when(active & straddles)
    def _diag():
        compute(True)


def _flash_fwd_kernel(
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, block_k, d)
    v_ref,  # (1, block_k, d)
    o_ref,  # (1, block_q, d)
    lse_ref,  # (1, block_q, 128) — lane-broadcast so the block is tileable
    acc_ref,  # VMEM (block_q, d) f32
    m_ref,  # VMEM (block_q, 128) f32
    l_ref,  # VMEM (block_q, 128) f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
    window=None,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Under causality a kv block strictly after the last query row of this
    # q block contributes nothing — skip its matmuls entirely; a block
    # entirely at-or-before the diagonal needs no mask — skip the iota/
    # compare/select (only diagonal-straddling blocks pay for masking).
    # Offsets are static (compile-time) positions of the first q/kv token.
    def _compute(masked: bool):
        # Feed the MXU native-dtype (bf16) operands — casting to f32 first
        # would force f32 matmul passes at a fraction of bf16 throughput.
        # Accumulation is f32 via preferred_element_type.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_k) f32
        if masked:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_offset + ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            visible = q_pos >= k_pos
            if window is not None:
                visible = visible & (q_pos - k_pos < window)
            s = jnp.where(visible, s, NEG_INF)

        m_prev = m_ref[:, :1]  # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.maximum(jnp.max(s, axis=1, keepdims=True), m_prev)
        # Fully-masked rows keep m_cur == NEG_INF; clamp the shift so
        # their p = exp(NEG_INF - 0) == 0 instead of exp(0) == 1 (same
        # guard as attention.blockwise_accumulate).
        m_safe = jnp.where(m_cur <= NEG_INF / 2, 0.0, m_cur)
        p = jnp.exp(s - m_safe)
        correction = jnp.exp(
            jnp.where(m_prev <= NEG_INF / 2, NEG_INF, m_prev) - m_safe
        )
        l_cur = l_prev * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * correction + pv
        m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur, l_ref.shape)

    _causal_dispatch(
        _compute, causal, qi, ki, block_q, block_k, q_offset, kv_offset,
        window=window,
    )

    @pl.when(ki == num_k - 1)
    def _finalize():
        l_final = l_ref[:, :1]
        l_safe = jnp.where(l_final == 0.0, 1.0, l_final)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (
            m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-37))
        ).astype(lse_ref.dtype)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
    interpret: bool,
    out_dtype=None,
    window=None,
):
    """Run the pallas kernel on [BH, T, D] inputs; returns (o, lse).

    ``out_dtype`` overrides the output dtype of ``o`` (default: q's) —
    ring callers take f32 so per-step partials are not rounded to bf16
    before the cross-step merge.

    The head dim is used directly as the block lane dim — Mosaic pads
    sub-128 tiles internally, which beats explicitly zero-padding to 128
    (that would double HBM traffic and MXU passes for d=64).  The lse
    output is lane-broadcast to (bh, t_q, 128) so its block satisfies
    the TPU (8, 128) tiling rule, then lane 0 is taken.
    """
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    if t_q % block_q or t_k % block_k:
        raise ValueError(
            f"block sizes ({block_q}, {block_k}) must divide the "
            f"sequence lengths ({t_q}, {t_k})"
        )
    if not interpret and (block_q % 8 or block_k % 8):
        raise ValueError(
            f"TPU tiling requires block sizes divisible by 8, got "
            f"({block_q}, {block_k})"
        )
    grid = (bh, t_q // block_q, t_k // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
        kv_offset=kv_offset,
        window=window,
    )
    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q, d), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((bh, t_q, 128), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    return o, lse[..., 0]


def _flash_bwd_dq_kernel(
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, block_k, d)
    v_ref,  # (1, block_k, d)
    do_ref,  # (1, block_q, d)
    lse_ref,  # (1, block_q, 128)
    delta_ref,  # (1, block_q, 128)
    dq_ref,  # out (1, block_q, d)
    acc_ref,  # VMEM (block_q, d) f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
    window=None,
):
    """dQ = (P ∘ (dO Vᵀ − D)) K · scale, accumulated over kv blocks."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _compute(masked: bool):
        # Native-dtype (bf16) MXU operands, f32 accumulation — see fwd.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if masked:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_offset + ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            visible = q_pos >= k_pos
            if window is not None:
                visible = visible & (q_pos - k_pos < window)
            s = jnp.where(visible, s, NEG_INF)
            # exp(s - lse); fully-masked rows have lse ~ NEG_INF — zero.
            p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        else:
            p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _causal_dispatch(
        _compute, causal, qi, ki, block_q, block_k, q_offset, kv_offset,
        window=window,
    )

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, block_k, d)
    v_ref,  # (1, block_k, d)
    do_ref,  # (1, block_q, d)
    lse_ref,  # (1, block_q, 128)
    delta_ref,  # (1, block_q, 128)
    dk_ref,  # out (1, block_k, d)
    dv_ref,  # out (1, block_k, d)
    dk_acc_ref,  # VMEM (block_k, d) f32
    dv_acc_ref,  # VMEM (block_k, d) f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
    window=None,
):
    """dV = Pᵀ dO and dK = dSᵀ Q · scale, accumulated over q blocks."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    def _compute(masked: bool):
        # Native-dtype (bf16) MXU operands, f32 accumulation — see fwd.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_k)
        if masked:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_offset + ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            visible = q_pos >= k_pos
            if window is not None:
                visible = visible & (q_pos - k_pos < window)
            s = jnp.where(visible, s, NEG_INF)
            p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        else:
            p = jnp.exp(s - lse)
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # pᵀ @ do: (block_k, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk_acc_ref[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # dsᵀ @ q (un-normalized; scale applied at finalize)

    _causal_dispatch(
        _compute, causal, qi, ki, block_q, block_k, q_offset, kv_offset,
        window=window,
    )

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = (dk_acc_ref[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _lse_delta_lanes(o, lse, do):
    """Lane-broadcast (lse, delta) to (bh, t_q, 128) for the bwd kernels.

    ``delta = rowsum(dO ∘ O)``; both depend only on (o, lse, do), so ring
    callers hoist this out of their per-step loop.
    """
    bh, t_q, _ = o.shape
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # (bh, t_q)
    lse_b = jnp.broadcast_to(lse[..., None], (bh, t_q, 128))
    delta_b = jnp.broadcast_to(delta[..., None], (bh, t_q, 128))
    return lse_b, delta_b


def _flash_backward_pallas(
    q, k, v, o, lse, do, *, scale: float, causal: bool,
    block_q: int, block_k: int, q_offset: int, kv_offset: int, interpret: bool,
    lse_delta_b=None, out_dtype=None, window=None,
):
    """Pallas flash backward on [BH, T, D] inputs → (dq, dk, dv).

    ``out_dtype`` overrides the gradients' dtype (default: the inputs') —
    ring callers take f32 so per-step partials are not rounded to bf16
    before cross-step accumulation.

    Two tiled kernels: dQ iterates kv blocks innermost (accumulator over
    the q row block), dK/dV iterates q blocks innermost (accumulators
    over the kv block).  ``delta = rowsum(dO ∘ O)`` and the saved lse are
    lane-broadcast to 128 so their blocks satisfy TPU (8, 128) tiling;
    pass ``lse_delta_b`` (from :func:`_lse_delta_lanes`) to reuse them
    across calls that share (o, lse, do).
    """
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    if t_q % block_q or t_k % block_k:
        raise ValueError(
            f"block sizes ({block_q}, {block_k}) must divide the "
            f"sequence lengths ({t_q}, {t_k})"
        )
    if lse_delta_b is None:
        lse_delta_b = _lse_delta_lanes(o, lse, do)
    lse_b, delta_b = lse_delta_b

    common = dict(
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
        kv_offset=kv_offset,
        window=window,
    )
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(bh, t_q // block_q, t_k // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_q, d), out_dtype or q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(bh, t_k // block_k, t_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_k, d), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((bh, t_k, d), out_dtype or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b)

    return dq, dk, dv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10)
)
def _flash_bthd(
    q, k, v, scale, causal, block_q, block_k, q_offset, kv_offset, interpret,
    window,
):
    out, _ = _flash_fwd_bthd(
        q, k, v, scale, causal, block_q, block_k, q_offset, kv_offset,
        interpret, window,
    )
    return out


def _bthd_to_bht(x):  # [B,T,H,D] -> [B*H, T, D]
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _bht_to_bthd(x, b, h):  # [B*H, T, D] -> [B,T,H,D]
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd_bthd(
    q, k, v, scale, causal, block_q, block_k, q_offset, kv_offset, interpret,
    window,
):
    b, t, h, d = q.shape
    o, lse = _flash_forward(
        _bthd_to_bht(q),
        _bthd_to_bht(k),
        _bthd_to_bht(v),
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
        kv_offset=kv_offset,
        interpret=interpret,
        window=window,
    )
    out = _bht_to_bthd(o, b, h)
    return out, (q, k, v, out, lse)


def _flash_bwd_bthd(
    scale, causal, block_q, block_k, q_offset, kv_offset, interpret, window,
    res, g,
):
    q, k, v, out, lse = res
    b, t, h, d = q.shape
    dq, dk, dv = _flash_backward_pallas(
        _bthd_to_bht(q),
        _bthd_to_bht(k),
        _bthd_to_bht(v),
        _bthd_to_bht(out),
        lse,
        _bthd_to_bht(g),
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        q_offset=q_offset,
        kv_offset=kv_offset,
        interpret=interpret,
        window=window,
    )
    return _bht_to_bthd(dq, b, h), _bht_to_bthd(dk, b, h), _bht_to_bthd(dv, b, h)


_flash_bthd.defvjp(_flash_fwd_bthd, _flash_bwd_bthd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    # Defaults from an on-chip sweep (v5e, b=4 T=2048 h=16 dh=64 bf16,
    # fwd+bwd, min-of-3 over a 60-iter scan delta): 1024/1024 = 4.0 ms vs
    # 512/1024 = 4.3, 512/512 = 5.1, 128/512 = 8.8, dense = 15.6.  Large
    # tiles amortize per-step overhead; bigger (1024/2048) exceeds the
    # 16 MB scoped-VMEM limit in the dkv kernel.
    block_q: int = 1024,
    block_k: int = 1024,
    q_offset: int = 0,
    kv_offset: int = 0,
    mask: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Tiled flash attention, BTHD layout — drop-in for
    :func:`rayfed_tpu.ops.attention.dot_product_attention` (also as the
    ``attn_fn`` of Ulysses attention).

    ``q_offset``/``kv_offset`` are *static* global positions of the first
    q/kv token (sharded-causal use).  Arbitrary dense ``mask`` is not
    supported by the tiled kernel — use ``dot_product_attention``.
    ``interpret=None`` auto-selects the pallas interpreter off-TPU so the
    same code path runs on the CPU test mesh.

    ``window`` (static, requires ``causal=True``): sliding-window
    attention — query q sees keys in ``(q − window, q]`` (Mistral
    style).  kv blocks entirely outside the band are skipped, so FLOPs
    scale with O(T·window) instead of the causal triangle.
    """
    if mask is not None:
        raise ValueError(
            "flash_attention does not support a dense mask; use "
            "dot_product_attention (or causal=True with offsets)"
        )
    if window is not None:
        if not causal:
            raise ValueError("window= requires causal=True (Mistral SWA)")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if interpret is None:
        interpret = not _on_tpu()
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    # Blocks must divide the sequence lengths: shrink the requested size
    # to the largest 8-aligned divisor (e.g. T=1280 with block_k=1024 →
    # 640) instead of erroring on any non-multiple length.
    block_q = _fit_block(q.shape[1], block_q)
    block_k = _fit_block(k.shape[1], block_k)
    if not interpret and (block_q % 8 or block_k % 8):
        # No 8-aligned divisor exists (e.g. prime T): fail here with an
        # actionable message instead of a Mosaic tiling error downstream.
        raise ValueError(
            f"sequence lengths ({q.shape[1]}, {k.shape[1]}) admit no "
            f"8-aligned block split for the compiled TPU kernel — pad the "
            f"sequence to a multiple of 8 or use dot_product_attention"
        )
    return _flash_bthd(
        q, k, v, scale, causal, block_q, block_k,
        int(q_offset), int(kv_offset), interpret,
        None if window is None else int(window),
    )


def _fit_block(t: int, want: int) -> int:
    """Largest block <= want that divides t (8-aligned when possible)."""
    b = min(want, t)
    while b > 8 and (t % b or b % 8):
        b -= 8
    if t % b == 0:
        return b
    while b > 1 and t % b:  # tiny/odd sequence lengths (tests)
        b -= 1
    return max(b, 1)
