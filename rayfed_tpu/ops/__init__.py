"""TPU-native ops: attention family + pallas kernels.

The reference (fengsp/rayfed) contains **no** attention or compute ops at
all (SURVEY §5.7) — it is model-agnostic and delegates compute to user
code inside Ray tasks.  For a TPU-first framework the compute layer is
part of the framework: long-context sequence parallelism (ring attention,
Ulysses all-to-all) and MXU-friendly kernels are first-class citizens
consumed by the model family in :mod:`rayfed_tpu.models`.
"""

from rayfed_tpu.ops.attention import dot_product_attention, mha
from rayfed_tpu.ops.flash_attention import flash_attention
from rayfed_tpu.ops.ring_attention import (
    make_ring_attention,
    ring_attention,
    ring_flash_attention,
    zigzag_ring_flash_attention,
)
from rayfed_tpu.ops.ulysses import ulysses_attention, make_ulysses_attention

__all__ = [
    "dot_product_attention",
    "mha",
    "flash_attention",
    "ring_attention",
    "ring_flash_attention",
    "zigzag_ring_flash_attention",
    "make_ring_attention",
    "ulysses_attention",
    "make_ulysses_attention",
]
