"""Ulysses (DeepSpeed-style) sequence parallelism via all-to-all.

Alternative to ring attention for long sequences: instead of rotating K/V
around a ring, one ``lax.all_to_all`` re-shards the activations from
sequence-sharded [B, T/n, H, D] to head-sharded [B, T, H/n, D]; each
device then runs *dense* attention for its head group over the full
sequence (one big MXU-friendly matmul chain, no per-step collectives) and
a second all-to-all restores sequence sharding.  Communication volume is
O(T·H·D/n) per device and independent of the number of ring steps; it
wins over ring attention when heads are plentiful and ICI all-to-all
bandwidth is good (the usual TPU case for H ≥ n).

Requires ``num_heads % axis_size == 0``.  Absent from the reference
(SURVEY §5.7); first-class here.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from rayfed_tpu.utils.jax_compat import shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rayfed_tpu.ops.attention import as_attn_fn, dot_product_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    attn_fn=None,
) -> jax.Array:
    """Collective Ulysses attention over ``axis_name`` (inside shard_map).

    Inputs are sequence shards [B, T_local, H, D]; output likewise.
    ``attn_fn`` runs the dense per-head-group attention (defaults to
    :func:`dot_product_attention`; a pallas flash kernel drops in here).
    """
    n = lax.psum(1, axis_name)
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses requires heads ({q.shape[2]}) divisible by axis size ({n})"
        )
    attn_fn = attn_fn or dot_product_attention

    def seq_to_heads(x):  # [B, T/n, H, D] -> [B, T, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = attn_fn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    # [B, T, H/n, D] -> [B, T/n, H, D]
    return lax.all_to_all(oh, axis_name, split_axis=1, concat_axis=2, tiled=True)


def make_ulysses_attention(
    mesh: Mesh,
    seq_axis: str = "sp",
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    attn_fn=None,
):
    """Global-view Ulysses attention sharded over ``mesh[seq_axis]``.

    Returned fn maps [B, T, H, D] → [B, T, H, D], T sharded over
    ``seq_axis``; H must divide by the axis size.
    """
    spec = P(None, seq_axis, None, None)
    fn = functools.partial(
        ulysses_attention,
        axis_name=seq_axis,
        causal=causal,
        sm_scale=sm_scale,
        attn_fn=attn_fn,
    )
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return as_attn_fn(sharded, causal, sm_scale, "make_ulysses_attention")
