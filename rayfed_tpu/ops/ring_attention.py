"""Ring attention: sequence parallelism over the ``sp`` mesh axis.

Long-context attention where the sequence is sharded across devices and
K/V shards rotate around the ring via ``lax.ppermute`` while each device
accumulates its queries' attention with the online-softmax recurrence
(:func:`rayfed_tpu.ops.attention.blockwise_accumulate`).  Per step the
ppermute overlaps ICI transfer of the *next* K/V block with compute on the
current one — XLA schedules the collective-permute asynchronously, which
is the whole point of the ring formulation (Liu et al., Ring Attention
with Blockwise Transformers, 2023).

Absent from the reference by design (SURVEY §5.7: "no ring attention,
context parallel, blockwise, or Ulysses anywhere") — here it is a
party-local sharding strategy of the compute layer.

Two inner-step implementations:

- ``blockwise`` — the XLA online-softmax recurrence
  (:func:`rayfed_tpu.ops.attention.blockwise_accumulate`); runs anywhere.
- ``flash`` (:func:`ring_flash_attention`) — each ring step runs the
  Pallas flash kernel on the resident K/V block and the per-step
  (o, lse) partials merge by log-sum-exp; backward rings the K/V blocks
  a second time, accumulating dK/dV *onto the rotating buffers* so each
  block arrives home carrying its full gradient.  This is the TPU path:
  the MXU sees the same tiled kernel as single-device flash attention.

Entry points:

- :func:`ring_attention` / :func:`ring_flash_attention` — collective
  forms, call *inside* ``shard_map`` with sequence-sharded
  [B, T_local, H, D] blocks.
- :func:`make_ring_attention` — wraps either in ``shard_map`` over a
  mesh axis; takes/returns global [B, T, H, D] arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rayfed_tpu.ops.attention import (
    as_attn_fn,
    blockwise_accumulate,
    blockwise_finalize,
    init_blockwise_state,
)
from rayfed_tpu.ops.flash_attention import (
    NEG_INF,
    _bht_to_bthd,
    _bthd_to_bht,
    _fit_block,
    _flash_backward_pallas,
    _flash_forward,
    _lse_delta_lanes,
    _on_tpu,
)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Collective ring attention over ``axis_name`` (call inside shard_map).

    ``q``/``k``/``v``: this device's sequence shard, [B, T_local, H, D].
    Shard *i* holds global positions ``[i*T_local, (i+1)*T_local)``.
    Returns the attention output for the local queries, same shape/dtype.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    # Rotate kv "forward" (device d hands its block to d+1), so at step i
    # device d holds the kv block originally owned by (d - i) mod n.
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    q_offset = my_idx * t_local

    def body(carry, step):
        o, m, l, k_cur, v_cur = carry
        src = jnp.mod(my_idx - step, axis_size)
        o, m, l = blockwise_accumulate(
            q,
            k_cur,
            v_cur,
            o,
            m,
            l,
            scale=scale,
            q_offset=q_offset,
            kv_offset=src * t_local,
            causal=causal,
        )
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_cur, v_cur), None

    state = init_blockwise_state(q) + (k, v)
    (o, _m, l, _k, _v), _ = lax.scan(body, state, jnp.arange(axis_size))
    return blockwise_finalize(o, l, q.dtype)


# ---------------------------------------------------------------------------
# Flash-inner ring: pallas kernels per step, lse-merge across steps
# ---------------------------------------------------------------------------


def _merge_partial(o_acc, lse_acc, o_i, lse_i):
    """Log-sum-exp merge of two *normalized* partial attention results.

    ``o_acc`` f32 [BH, T, D] with normalizer ``lse_acc`` [BH, T]; fully
    absent partials carry ``lse == NEG_INF`` and contribute nothing.
    """
    m = jnp.maximum(lse_acc, lse_i)
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    w_acc = jnp.exp(jnp.where(lse_acc <= NEG_INF / 2, NEG_INF, lse_acc) - m_safe)
    w_i = jnp.exp(jnp.where(lse_i <= NEG_INF / 2, NEG_INF, lse_i) - m_safe)
    denom = w_acc + w_i
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    o = (
        o_acc * (w_acc / denom_safe)[..., None]
        + o_i.astype(jnp.float32) * (w_i / denom_safe)[..., None]
    )
    lse = m + jnp.log(denom_safe)
    return o, lse


def _ring_flash_fwd_inner(
    q, k, v, axis_name, causal, scale, block_q, block_k, interpret
):
    """[BH, T, D] ring forward → (o f32, lse f32)."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    flash = functools.partial(
        _flash_forward,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        q_offset=0,
        kv_offset=0,
        interpret=interpret,
        # f32 partials: rounding each step's o to bf16 before the merge
        # would accumulate error with ring size; round once at the end.
        out_dtype=jnp.float32,
    )

    # Step 0 is every device's own (diagonal) block — the only one that
    # needs in-kernel causal masking, so it runs unrolled.  Later blocks
    # are either entirely visible (owner before me in the ring) or
    # entirely masked; visibility is applied to the partial's lse, so
    # one causal=False kernel instance serves every scanned step.
    o_acc, lse_0 = flash(q, k, v, causal=causal)

    def body(carry, step):
        o_acc, lse_acc, k_cur, v_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        o_i, lse_i = flash(q, k_cur, v_cur, causal=False)
        if causal:
            src = jnp.mod(my_idx - step, axis_size)
            lse_i = jnp.where(src < my_idx, lse_i, NEG_INF)
        o_acc, lse_acc = _merge_partial(o_acc, lse_acc, o_i, lse_i)
        return (o_acc, lse_acc, k_cur, v_cur), None

    (o_acc, lse_acc, _, _), _ = lax.scan(
        body, (o_acc, lse_0, k, v), jnp.arange(1, axis_size)
    )
    return o_acc, lse_acc


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash_bht(
    q, k, v, axis_name, causal, scale, block_q, block_k, interpret
):
    out, _ = _ring_flash_fwd(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    return out


def _ring_flash_fwd(
    q, k, v, axis_name, causal, scale, block_q, block_k, interpret
):
    o_acc, lse = _ring_flash_fwd_inner(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    out = o_acc.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(
    axis_name, causal, scale, block_q, block_k, interpret, res, do
):
    """Backward ring: K/V make a second full loop, dK/dV ride along.

    Each step runs the standard flash backward kernels (dQ and dK/dV)
    against the resident K/V block using the *final* lse/delta — the
    global-softmax weights — and the dK/dV partials accumulate onto
    buffers that rotate with their block; after ``axis_size`` rotations
    every block (and its gradient) is back on its owner.
    """
    q, k, v, out, lse = res
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    # lse/delta lane-broadcasts depend only on (out, lse, do): hoist them
    # out of the ring loop instead of recomputing per step.
    lse_delta_b = _lse_delta_lanes(out, lse, do)
    bwd = functools.partial(
        _flash_backward_pallas,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        q_offset=0,
        kv_offset=0,
        interpret=interpret,
        lse_delta_b=lse_delta_b,
        # f32 partials — see the forward's out_dtype note.
        out_dtype=jnp.float32,
    )

    # Step 0: the diagonal block, in-kernel causal mask (see fwd).
    dq_0, dk_0, dv_0 = bwd(q, k, v, out, lse, do, causal=causal)

    def body(carry, step):
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        # Rotate gradients WITH their block so each block accumulates
        # its contributions as it tours the ring.
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        dq_i, dk_i, dv_i = bwd(q, k_cur, v_cur, out, lse, do, causal=False)
        if causal:
            # jnp.where, not a multiply: an invisible block's kernel
            # output is exp(s - lse) of scores the softmax never saw —
            # potentially inf, and inf·0 would poison the sum with NaN.
            src = jnp.mod(my_idx - step, axis_size)
            visible = src < my_idx
            dq_i = jnp.where(visible, dq_i, 0)
            dk_i = jnp.where(visible, dk_i, 0)
            dv_i = jnp.where(visible, dv_i, 0)
        dq_acc = dq_acc + dq_i.astype(jnp.float32)
        dk_cur = dk_cur + dk_i.astype(jnp.float32)
        dv_cur = dv_cur + dv_i.astype(jnp.float32)
        return (dq_acc, k_cur, v_cur, dk_cur, dv_cur), None

    carry0 = (
        dq_0.astype(jnp.float32),
        k,
        v,
        dk_0.astype(jnp.float32),
        dv_0.astype(jnp.float32),
    )
    (dq_acc, _, _, dk_cur, dv_cur), _ = lax.scan(
        body, carry0, jnp.arange(1, axis_size)
    )
    # One final hop delivers each block's accumulated gradient home.
    dk_cur = lax.ppermute(dk_cur, axis_name, perm)
    dv_cur = lax.ppermute(dv_cur, axis_name, perm)
    return (
        dq_acc.astype(q.dtype),
        dk_cur.astype(k.dtype),
        dv_cur.astype(v.dtype),
    )


_ring_flash_bht.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Ring attention with the Pallas flash kernel as the inner step.

    Same contract as :func:`ring_attention` (call inside ``shard_map``
    with [B, T_local, H, D] sequence shards; shard *i* holds global
    positions ``[i·T_local, (i+1)·T_local)``) — but each step's block
    attention runs the tiled MXU kernel and the per-step results merge
    by log-sum-exp, so per-block throughput matches single-device
    :func:`rayfed_tpu.ops.flash_attention.flash_attention`.
    """
    if interpret is None:
        interpret = not _on_tpu()
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    t_local = q.shape[1]
    block_q = _fit_block(t_local, block_q)
    block_k = _fit_block(k.shape[1], block_k)
    qh, kh, vh = _bthd_to_bht(q), _bthd_to_bht(k), _bthd_to_bht(v)
    oh = _ring_flash_bht(
        qh, kh, vh, axis_name, causal, scale, block_q, block_k, interpret
    )
    return _bht_to_bthd(oh, q.shape[0], q.shape[2])


def make_ring_attention(
    mesh: Mesh,
    seq_axis: str = "sp",
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    use_flash: bool = False,
    block_q: int = 1024,
    block_k: int = 1024,
):
    """Build a global-view ring attention fn sharded over ``mesh[seq_axis]``.

    Returned fn maps [B, T, H, D] → [B, T, H, D] with T sharded over
    ``seq_axis`` (T must divide evenly).  Batch stays replicated here;
    compose with dp by vmapping/sharding outside.  ``use_flash=True``
    runs the Pallas flash kernel per ring step (the TPU-fast path;
    interpreted off-TPU so the CPU test mesh exercises it too).
    """
    spec = P(None, seq_axis, None, None)
    if use_flash:
        fn = functools.partial(
            ring_flash_attention,
            axis_name=seq_axis,
            causal=causal,
            sm_scale=sm_scale,
            block_q=block_q,
            block_k=block_k,
        )
    else:
        fn = functools.partial(
            ring_attention, axis_name=seq_axis, causal=causal, sm_scale=sm_scale
        )
    sharded = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return as_attn_fn(sharded, causal, sm_scale, "make_ring_attention")
