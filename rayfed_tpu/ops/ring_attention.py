"""Ring attention: sequence parallelism over the ``sp`` mesh axis.

Long-context attention where the sequence is sharded across devices and
K/V shards rotate around the ring via ``lax.ppermute`` while each device
accumulates its queries' attention with the online-softmax recurrence
(:func:`rayfed_tpu.ops.attention.blockwise_accumulate`).  Per step the
ppermute overlaps ICI transfer of the *next* K/V block with compute on the
current one — XLA schedules the collective-permute asynchronously, which
is the whole point of the ring formulation (Liu et al., Ring Attention
with Blockwise Transformers, 2023).

Absent from the reference by design (SURVEY §5.7: "no ring attention,
context parallel, blockwise, or Ulysses anywhere") — here it is a
party-local sharding strategy of the compute layer.

Two inner-step implementations:

- ``blockwise`` — the XLA online-softmax recurrence
  (:func:`rayfed_tpu.ops.attention.blockwise_accumulate`); runs anywhere.
- ``flash`` (:func:`ring_flash_attention`) — each ring step runs the
  Pallas flash kernel on the resident K/V block and the per-step
  (o, lse) partials merge by log-sum-exp; backward rings the K/V blocks
  a second time, accumulating dK/dV *onto the rotating buffers* so each
  block arrives home carrying its full gradient.  This is the TPU path:
  the MXU sees the same tiled kernel as single-device flash attention.

Entry points:

- :func:`ring_attention` / :func:`ring_flash_attention` — collective
  forms, call *inside* ``shard_map`` with sequence-sharded
  [B, T_local, H, D] blocks.
- :func:`make_ring_attention` — wraps either in ``shard_map`` over a
  mesh axis; takes/returns global [B, T, H, D] arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from rayfed_tpu.utils.jax_compat import shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rayfed_tpu.ops.attention import (
    as_attn_fn,
    blockwise_accumulate,
    blockwise_finalize,
    init_blockwise_state,
)
from rayfed_tpu.ops.flash_attention import (
    NEG_INF,
    _bht_to_bthd,
    _bthd_to_bht,
    _fit_block,
    _flash_backward_pallas,
    _flash_forward,
    _lse_delta_lanes,
    _on_tpu,
)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Collective ring attention over ``axis_name`` (call inside shard_map).

    ``q``/``k``/``v``: this device's sequence shard, [B, T_local, H, D].
    Shard *i* holds global positions ``[i*T_local, (i+1)*T_local)``.
    Returns the attention output for the local queries, same shape/dtype.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    # Rotate kv "forward" (device d hands its block to d+1), so at step i
    # device d holds the kv block originally owned by (d - i) mod n.
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    q_offset = my_idx * t_local

    def body(carry, step):
        o, m, l, k_cur, v_cur = carry
        src = jnp.mod(my_idx - step, axis_size)
        o, m, l = blockwise_accumulate(
            q,
            k_cur,
            v_cur,
            o,
            m,
            l,
            scale=scale,
            q_offset=q_offset,
            kv_offset=src * t_local,
            causal=causal,
        )
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_cur, v_cur), None

    state = init_blockwise_state(q) + (k, v)
    (o, _m, l, _k, _v), _ = lax.scan(body, state, jnp.arange(axis_size))
    return blockwise_finalize(o, l, q.dtype)


# ---------------------------------------------------------------------------
# Flash-inner ring: pallas kernels per step, lse-merge across steps
# ---------------------------------------------------------------------------


def _merge_partial(o_acc, lse_acc, o_i, lse_i):
    """Log-sum-exp merge of two *normalized* partial attention results.

    ``o_acc`` f32 [BH, T, D] with normalizer ``lse_acc`` [BH, T]; fully
    absent partials carry ``lse == NEG_INF`` and contribute nothing.
    """
    m = jnp.maximum(lse_acc, lse_i)
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    w_acc = jnp.exp(jnp.where(lse_acc <= NEG_INF / 2, NEG_INF, lse_acc) - m_safe)
    w_i = jnp.exp(jnp.where(lse_i <= NEG_INF / 2, NEG_INF, lse_i) - m_safe)
    denom = w_acc + w_i
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    o = (
        o_acc * (w_acc / denom_safe)[..., None]
        + o_i.astype(jnp.float32) * (w_i / denom_safe)[..., None]
    )
    lse = m + jnp.log(denom_safe)
    return o, lse


def _ring_flash_fwd_inner(
    q, k, v, axis_name, causal, scale, block_q, block_k, interpret
):
    """[BH, T, D] ring forward → (o f32, lse f32)."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    flash = functools.partial(
        _flash_forward,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        q_offset=0,
        kv_offset=0,
        interpret=interpret,
        # f32 partials: rounding each step's o to bf16 before the merge
        # would accumulate error with ring size; round once at the end.
        out_dtype=jnp.float32,
    )

    # Step 0 is every device's own (diagonal) block — the only one that
    # needs in-kernel causal masking, so it runs unrolled.  Later blocks
    # are either entirely visible (owner before me in the ring) or
    # entirely masked; visibility is applied to the partial's lse, so
    # one causal=False kernel instance serves every scanned step.
    o_acc, lse_0 = flash(q, k, v, causal=causal)

    def body(carry, step):
        o_acc, lse_acc, k_cur, v_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        o_i, lse_i = flash(q, k_cur, v_cur, causal=False)
        if causal:
            src = jnp.mod(my_idx - step, axis_size)
            lse_i = jnp.where(src < my_idx, lse_i, NEG_INF)
        o_acc, lse_acc = _merge_partial(o_acc, lse_acc, o_i, lse_i)
        return (o_acc, lse_acc, k_cur, v_cur), None

    (o_acc, lse_acc, _, _), _ = lax.scan(
        body, (o_acc, lse_0, k, v), jnp.arange(1, axis_size)
    )
    return o_acc, lse_acc


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash_bht(
    q, k, v, axis_name, causal, scale, block_q, block_k, interpret
):
    out, _ = _ring_flash_fwd(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    return out


def _ring_flash_fwd(
    q, k, v, axis_name, causal, scale, block_q, block_k, interpret
):
    o_acc, lse = _ring_flash_fwd_inner(
        q, k, v, axis_name, causal, scale, block_q, block_k, interpret
    )
    out = o_acc.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(
    axis_name, causal, scale, block_q, block_k, interpret, res, do
):
    """Backward ring: K/V make a second full loop, dK/dV ride along.

    Each step runs the standard flash backward kernels (dQ and dK/dV)
    against the resident K/V block using the *final* lse/delta — the
    global-softmax weights — and the dK/dV partials accumulate onto
    buffers that rotate with their block; after ``axis_size`` rotations
    every block (and its gradient) is back on its owner.
    """
    q, k, v, out, lse = res
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    # lse/delta lane-broadcasts depend only on (out, lse, do): hoist them
    # out of the ring loop instead of recomputing per step.
    lse_delta_b = _lse_delta_lanes(out, lse, do)
    bwd = functools.partial(
        _flash_backward_pallas,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        q_offset=0,
        kv_offset=0,
        interpret=interpret,
        lse_delta_b=lse_delta_b,
        # f32 partials — see the forward's out_dtype note.
        out_dtype=jnp.float32,
    )

    # Step 0: the diagonal block, in-kernel causal mask (see fwd).
    dq_0, dk_0, dv_0 = bwd(q, k, v, out, lse, do, causal=causal)

    def body(carry, step):
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        # Rotate gradients WITH their block so each block accumulates
        # its contributions as it tours the ring.
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        dq_i, dk_i, dv_i = bwd(q, k_cur, v_cur, out, lse, do, causal=False)
        if causal:
            # jnp.where, not a multiply: an invisible block's kernel
            # output is exp(s - lse) of scores the softmax never saw —
            # potentially inf, and inf·0 would poison the sum with NaN.
            src = jnp.mod(my_idx - step, axis_size)
            visible = src < my_idx
            dq_i = jnp.where(visible, dq_i, 0)
            dk_i = jnp.where(visible, dk_i, 0)
            dv_i = jnp.where(visible, dv_i, 0)
        dq_acc = dq_acc + dq_i.astype(jnp.float32)
        dk_cur = dk_cur + dk_i.astype(jnp.float32)
        dv_cur = dv_cur + dv_i.astype(jnp.float32)
        return (dq_acc, k_cur, v_cur, dk_cur, dv_cur), None

    carry0 = (
        dq_0.astype(jnp.float32),
        k,
        v,
        dk_0.astype(jnp.float32),
        dv_0.astype(jnp.float32),
    )
    (dq_acc, _, _, dk_cur, dv_cur), _ = lax.scan(
        body, carry0, jnp.arange(1, axis_size)
    )
    # One final hop delivers each block's accumulated gradient home.
    dk_cur = lax.ppermute(dk_cur, axis_name, perm)
    dv_cur = lax.ppermute(dv_cur, axis_name, perm)
    return (
        dq_acc.astype(q.dtype),
        dk_cur.astype(k.dtype),
        dv_cur.astype(v.dtype),
    )


_ring_flash_bht.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Ring attention with the Pallas flash kernel as the inner step.

    Same contract as :func:`ring_attention` (call inside ``shard_map``
    with [B, T_local, H, D] sequence shards; shard *i* holds global
    positions ``[i·T_local, (i+1)·T_local)``) — but each step's block
    attention runs the tiled MXU kernel and the per-step results merge
    by log-sum-exp, so per-block throughput matches single-device
    :func:`rayfed_tpu.ops.flash_attention.flash_attention`.
    """
    if interpret is None:
        interpret = not _on_tpu()
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    t_local = q.shape[1]
    block_q = _fit_block(t_local, block_q)
    block_k = _fit_block(k.shape[1], block_k)
    qh, kh, vh = _bthd_to_bht(q), _bthd_to_bht(k), _bthd_to_bht(v)
    oh = _ring_flash_bht(
        qh, kh, vh, axis_name, causal, scale, block_q, block_k, interpret
    )
    return _bht_to_bthd(oh, q.shape[0], q.shape[2])


# ---------------------------------------------------------------------------
# Zigzag layout: load-balanced causal ring
# ---------------------------------------------------------------------------
#
# A contiguous causal ring wastes compute: at step s device d's resident
# K/V block is fully masked whenever its owner sits *after* d, so about
# half of all (device, step) kernels contribute nothing (they still run —
# ppermute keeps the devices in lockstep).  The zigzag layout (T split
# into 2n chunks; device d holds chunks (d, 2n−1−d)) balances the causal
# triangle instead:
#
#   - (q_lo, kv_hi): the peer's high chunk is always in q_lo's future —
#     statically skipped, no kernel at all;
#   - (q_hi, kv_lo): the peer's low chunk is always in q_hi's past —
#     statically a full (unmasked) kernel;
#   - (q_lo, kv_lo) is visible iff src < my and (q_hi, kv_hi) iff
#     src > my — exactly one per step, so ONE kernel on operands
#     selected by that predicate covers both.
#
# Per step every device runs exactly two half-chunk kernels of useful
# work; total causal FLOPs match the T²/2 triangle with no waste — 2×
# the effective throughput of the contiguous causal ring.


def _zigzag_flash_fwd_inner(q, k, v, axis_name, scale, block_q, block_k, interpret):
    """[BH, 2·Tc, D] zigzag forward → (o f32, lse f32), halves stacked."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    tc = q.shape[1] // 2
    flash = functools.partial(
        _flash_forward,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        q_offset=0,
        kv_offset=0,
        interpret=interpret,
        out_dtype=jnp.float32,
    )
    q_lo, q_hi = q[:, :tc], q[:, tc:]

    # Step 0: both within-chunk diagonals (causal kernels) plus the
    # always-full (q_hi, kv_lo) block.
    o_lo, lse_lo = flash(q_lo, k[:, :tc], v[:, :tc], causal=True)
    o_hi, lse_hi = flash(q_hi, k[:, tc:], v[:, tc:], causal=True)
    o_f, lse_f = flash(q_hi, k[:, :tc], v[:, :tc], causal=False)
    o_hi, lse_hi = _merge_partial(o_hi, lse_hi, o_f, lse_f)

    def body(carry, step):
        o_lo, lse_lo, o_hi, lse_hi, k_cur, v_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        src = jnp.mod(my_idx - step, axis_size)
        k_lo, k_hi = k_cur[:, :tc], k_cur[:, tc:]
        v_lo, v_hi = v_cur[:, :tc], v_cur[:, tc:]

        # Static full block: the peer's low chunk is always visible to
        # my high chunk.
        o_f, lse_f = flash(q_hi, k_lo, v_lo, causal=False)
        o_hi, lse_hi = _merge_partial(o_hi, lse_hi, o_f, lse_f)

        # Gated block: exactly one of (q_lo, kv_lo) / (q_hi, kv_hi) is
        # visible; select the operands instead of computing both.
        pred = src < my_idx
        o_g, lse_g = flash(
            jnp.where(pred, q_lo, q_hi),
            jnp.where(pred, k_lo, k_hi),
            jnp.where(pred, v_lo, v_hi),
            causal=False,
        )
        m_lo = _merge_partial(o_lo, lse_lo, o_g, lse_g)
        m_hi = _merge_partial(o_hi, lse_hi, o_g, lse_g)
        o_lo = jnp.where(pred, m_lo[0], o_lo)
        lse_lo = jnp.where(pred, m_lo[1], lse_lo)
        o_hi = jnp.where(pred, o_hi, m_hi[0])
        lse_hi = jnp.where(pred, lse_hi, m_hi[1])
        return (o_lo, lse_lo, o_hi, lse_hi, k_cur, v_cur), None

    carry0 = (o_lo, lse_lo, o_hi, lse_hi, k, v)
    (o_lo, lse_lo, o_hi, lse_hi, _, _), _ = lax.scan(
        body, carry0, jnp.arange(1, axis_size)
    )
    return (
        jnp.concatenate([o_lo, o_hi], axis=1),
        jnp.concatenate([lse_lo, lse_hi], axis=1),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _zigzag_flash_bht(q, k, v, axis_name, scale, block_q, block_k, interpret):
    out, _ = _zigzag_flash_fwd(
        q, k, v, axis_name, scale, block_q, block_k, interpret
    )
    return out


def _zigzag_flash_fwd(q, k, v, axis_name, scale, block_q, block_k, interpret):
    o, lse = _zigzag_flash_fwd_inner(
        q, k, v, axis_name, scale, block_q, block_k, interpret
    )
    out = o.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _zigzag_flash_bwd(
    axis_name, scale, block_q, block_k, interpret, res, do
):
    """Backward mirrors the forward's block schedule; dK/dV ride the ring."""
    q, k, v, out, lse = res
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    tc = q.shape[1] // 2
    q_lo, q_hi = q[:, :tc], q[:, tc:]
    do_lo, do_hi = do[:, :tc], do[:, tc:]
    out_lo, out_hi = out[:, :tc], out[:, tc:]
    lse_lo, lse_hi = lse[:, :tc], lse[:, tc:]
    ld_lo = _lse_delta_lanes(out_lo, lse_lo, do_lo)
    ld_hi = _lse_delta_lanes(out_hi, lse_hi, do_hi)

    def bwd(qb, kb, vb, ob, lseb, dob, causal, ld):
        return _flash_backward_pallas(
            qb, kb, vb, ob, lseb, dob,
            scale=scale,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            q_offset=0,
            kv_offset=0,
            interpret=interpret,
            lse_delta_b=ld,
            out_dtype=jnp.float32,
        )

    # Step 0: the two diagonals + the static full block, all local.
    dq_lo, dk_lo, dv_lo = bwd(
        q_lo, k[:, :tc], v[:, :tc], out_lo, lse_lo, do_lo, True, ld_lo
    )
    dq_hi, dk_hi, dv_hi = bwd(
        q_hi, k[:, tc:], v[:, tc:], out_hi, lse_hi, do_hi, True, ld_hi
    )
    dq_f, dk_f, dv_f = bwd(
        q_hi, k[:, :tc], v[:, :tc], out_hi, lse_hi, do_hi, False, ld_hi
    )
    dq_hi = dq_hi + dq_f
    dk_lo = dk_lo + dk_f
    dv_lo = dv_lo + dv_f

    def body(carry, step):
        dq_lo, dq_hi, k_cur, v_cur, dk_cur, dv_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        src = jnp.mod(my_idx - step, axis_size)
        k_l, k_h = k_cur[:, :tc], k_cur[:, tc:]
        v_l, v_h = v_cur[:, :tc], v_cur[:, tc:]

        # Static full block (q_hi, kv_lo of the resident pair).
        dq_f, dk_f, dv_f = bwd(
            q_hi, k_l, v_l, out_hi, lse_hi, do_hi, False, ld_hi
        )
        dq_hi = dq_hi + dq_f
        dk_cur = dk_cur.at[:, :tc].add(dk_f)
        dv_cur = dv_cur.at[:, :tc].add(dv_f)

        # Gated block on selected operands (see forward).
        pred = src < my_idx
        dq_g, dk_g, dv_g = bwd(
            jnp.where(pred, q_lo, q_hi),
            jnp.where(pred, k_l, k_h),
            jnp.where(pred, v_l, v_h),
            jnp.where(pred, out_lo, out_hi),
            jnp.where(pred, lse_lo, lse_hi),
            jnp.where(pred, do_lo, do_hi),
            False,
            tuple(jnp.where(pred, a, b) for a, b in zip(ld_lo, ld_hi)),
        )
        dq_lo = dq_lo + jnp.where(pred, dq_g, 0)
        dq_hi = dq_hi + jnp.where(pred, 0, dq_g)
        dk_cur = dk_cur.at[:, :tc].add(jnp.where(pred, dk_g, 0))
        dk_cur = dk_cur.at[:, tc:].add(jnp.where(pred, 0, dk_g))
        dv_cur = dv_cur.at[:, :tc].add(jnp.where(pred, dv_g, 0))
        dv_cur = dv_cur.at[:, tc:].add(jnp.where(pred, 0, dv_g))
        return (dq_lo, dq_hi, k_cur, v_cur, dk_cur, dv_cur), None

    carry0 = (
        dq_lo,
        dq_hi,
        k,
        v,
        jnp.concatenate([dk_lo, dk_hi], axis=1),
        jnp.concatenate([dv_lo, dv_hi], axis=1),
    )
    (dq_lo, dq_hi, _, _, dk_cur, dv_cur), _ = lax.scan(
        body, carry0, jnp.arange(1, axis_size)
    )
    # Final hop delivers each pair's accumulated gradient home.
    dk_cur = lax.ppermute(dk_cur, axis_name, perm)
    dv_cur = lax.ppermute(dv_cur, axis_name, perm)
    dq = jnp.concatenate([dq_lo, dq_hi], axis=1)
    return dq.astype(q.dtype), dk_cur.astype(k.dtype), dv_cur.astype(v.dtype)


_zigzag_flash_bht.defvjp(_zigzag_flash_fwd, _zigzag_flash_bwd)


def zigzag_ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    sm_scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Collective zigzag causal ring attention (call inside shard_map).

    Shard layout: with 2n chunks of the global sequence, this device's
    [B, T_local, H, D] block is ``concat(chunk_d, chunk_{2n−1−d})`` —
    :func:`make_ring_attention` with ``layout="zigzag"`` applies the
    chunk permutation on global arrays.  Always causal (a non-causal
    ring has no imbalance to fix).
    """
    if interpret is None:
        interpret = not _on_tpu()
    if q.shape[1] % 2:
        raise ValueError(
            f"zigzag shards hold a (low, high) chunk pair — T_local "
            f"({q.shape[1]}) must be even"
        )
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    tc = q.shape[1] // 2
    block_q = _fit_block(tc, block_q)
    block_k = _fit_block(tc, block_k)
    qh, kh, vh = _bthd_to_bht(q), _bthd_to_bht(k), _bthd_to_bht(v)
    oh = _zigzag_flash_bht(
        qh, kh, vh, axis_name, scale, block_q, block_k, interpret
    )
    return _bht_to_bthd(oh, q.shape[0], q.shape[2])


def _zigzag_perm(t: int, n_shards: int):
    """(perm, inv): chunk reorder so contiguous shard d = chunks
    (d, 2n−1−d) of the original sequence."""
    import numpy as np

    chunks = 2 * n_shards
    if t % chunks:
        raise ValueError(
            f"zigzag layout needs T ({t}) divisible by 2·axis_size "
            f"({chunks})"
        )
    tc = t // chunks
    order = []
    for d in range(n_shards):
        order.extend([d, chunks - 1 - d])
    idx = np.concatenate(
        [np.arange(c * tc, (c + 1) * tc) for c in order]
    )
    inv = np.argsort(idx)
    return idx, inv


def make_ring_attention(
    mesh: Mesh,
    seq_axis: str = "sp",
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    use_flash: bool = False,
    block_q: int = 1024,
    block_k: int = 1024,
    layout: str = "contiguous",
):
    """Build a global-view ring attention fn sharded over ``mesh[seq_axis]``.

    Returned fn maps [B, T, H, D] → [B, T, H, D] with T sharded over
    ``seq_axis`` (T must divide evenly).  Batch stays replicated here;
    compose with dp by vmapping/sharding outside.  ``use_flash=True``
    runs the Pallas flash kernel per ring step (the TPU-fast path;
    interpreted off-TPU so the CPU test mesh exercises it too).

    ``layout="zigzag"`` (requires ``causal=True, use_flash=True``)
    balances the causal triangle across devices — each shard holds
    chunks (d, 2n−1−d) of the sequence, applied/undone here by a static
    chunk permutation — 2× the effective throughput of the contiguous
    causal ring (see the layout note above
    :func:`_zigzag_flash_fwd_inner`).
    """
    spec = P(None, seq_axis, None, None)
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    if layout == "zigzag":
        if not (causal and use_flash):
            raise ValueError(
                "layout='zigzag' requires causal=True and use_flash=True "
                "(a non-causal ring has no imbalance to fix)"
            )
        n_shards = mesh.shape[seq_axis]
        sharded = shard_map(
            functools.partial(
                zigzag_ring_flash_attention,
                axis_name=seq_axis,
                sm_scale=sm_scale,
                block_q=block_q,
                block_k=block_k,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )

        def apply_zigzag(qg, kg, vg):
            idx, inv = _zigzag_perm(qg.shape[1], n_shards)
            out = sharded(
                jnp.take(qg, idx, axis=1),
                jnp.take(kg, idx, axis=1),
                jnp.take(vg, idx, axis=1),
            )
            return jnp.take(out, inv, axis=1)

        return as_attn_fn(
            apply_zigzag, causal, sm_scale, "make_ring_attention"
        )
    if use_flash:
        fn = functools.partial(
            ring_flash_attention,
            axis_name=seq_axis,
            causal=causal,
            sm_scale=sm_scale,
            block_q=block_q,
            block_k=block_k,
        )
    else:
        fn = functools.partial(
            ring_attention, axis_name=seq_axis, causal=causal, sm_scale=sm_scale
        )
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return as_attn_fn(sharded, causal, sm_scale, "make_ring_attention")
