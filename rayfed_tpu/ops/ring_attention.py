"""Ring attention: sequence parallelism over the ``sp`` mesh axis.

Long-context attention where the sequence is sharded across devices and
K/V shards rotate around the ring via ``lax.ppermute`` while each device
accumulates its queries' attention with the online-softmax recurrence
(:func:`rayfed_tpu.ops.attention.blockwise_accumulate`).  Per step the
ppermute overlaps ICI transfer of the *next* K/V block with compute on the
current one — XLA schedules the collective-permute asynchronously, which
is the whole point of the ring formulation (Liu et al., Ring Attention
with Blockwise Transformers, 2023).

Absent from the reference by design (SURVEY §5.7: "no ring attention,
context parallel, blockwise, or Ulysses anywhere") — here it is a
party-local sharding strategy of the compute layer.

Two entry points:

- :func:`ring_attention` — collective form, call *inside* ``shard_map``
  with sequence-sharded [B, T_local, H, D] blocks.
- :func:`make_ring_attention` — wraps it in ``shard_map`` over a mesh
  axis; takes/returns global [B, T, H, D] arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from rayfed_tpu.ops.attention import (
    blockwise_accumulate,
    blockwise_finalize,
    init_blockwise_state,
)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Collective ring attention over ``axis_name`` (call inside shard_map).

    ``q``/``k``/``v``: this device's sequence shard, [B, T_local, H, D].
    Shard *i* holds global positions ``[i*T_local, (i+1)*T_local)``.
    Returns the attention output for the local queries, same shape/dtype.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    # Rotate kv "forward" (device d hands its block to d+1), so at step i
    # device d holds the kv block originally owned by (d - i) mod n.
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    q_offset = my_idx * t_local

    def body(carry, step):
        o, m, l, k_cur, v_cur = carry
        src = jnp.mod(my_idx - step, axis_size)
        o, m, l = blockwise_accumulate(
            q,
            k_cur,
            v_cur,
            o,
            m,
            l,
            scale=scale,
            q_offset=q_offset,
            kv_offset=src * t_local,
            causal=causal,
        )
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_cur, v_cur), None

    state = init_blockwise_state(q) + (k, v)
    (o, _m, l, _k, _v), _ = lax.scan(body, state, jnp.arange(axis_size))
    return blockwise_finalize(o, l, q.dtype)


def make_ring_attention(
    mesh: Mesh,
    seq_axis: str = "sp",
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
):
    """Build a global-view ring attention fn sharded over ``mesh[seq_axis]``.

    Returned fn maps [B, T, H, D] → [B, T, H, D] with T sharded over
    ``seq_axis`` (T must divide evenly).  Batch stays replicated here;
    compose with dp by vmapping/sharding outside.
    """
    spec = P(None, seq_axis, None, None)
    fn = functools.partial(
        ring_attention, axis_name=seq_axis, causal=causal, sm_scale=sm_scale
    )
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
