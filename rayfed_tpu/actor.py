"""Cross-party stateful actors.

Capability parity with reference ``fed/_private/fed_actor.py``: a
:class:`FedActorHandle` whose ``__getattr__`` manufactures a
:class:`FedActorMethod` per method; construction executes only in the
owning party; every method call flows through the shared
:class:`~rayfed_tpu.call_holder.FedCallHolder` so seq ids stay aligned on
all parties.

TPU-native difference: the actor body lives in-process on a dedicated
serial executor (:class:`~rayfed_tpu.executor.ActorInstance`), so sharded
``jax.Array`` state stays resident on the party's devices between calls.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from rayfed_tpu.call_holder import FedCallHolder
from rayfed_tpu.executor import ActorInstance
from rayfed_tpu.runtime import Runtime

logger = logging.getLogger(__name__)


class FedActorHandle:
    def __init__(
        self,
        runtime: Runtime,
        fed_class_task_id: int,
        cls: type,
        node_party: str,
        options: Optional[dict] = None,
    ) -> None:
        self._runtime = runtime
        self._fed_class_task_id = fed_class_task_id
        self._body = cls
        self._party = runtime.party
        self._node_party = node_party
        self._options = dict(options or {})
        self._actor_instance: Optional[ActorInstance] = None

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        # Validate the method exists on the user class (ref fed_actor.py:46).
        getattr(self._body, method_name)
        # Creation options propagate to method call nodes (ref fed_actor.py:47-55).
        return FedActorMethod(
            self._runtime, self._node_party, self, method_name
        ).options(**self._options)

    def _execute_impl(self, cls_args: tuple, cls_kwargs: dict) -> None:
        """Construct the actor — only in the owning party (ref :57-70)."""
        if self._node_party == self._party:
            self._actor_instance = ActorInstance(
                self._body,
                cls_args,
                cls_kwargs,
                bind_runtime_fn=self._runtime._bind_to_current_thread,
                name=f"{self._body.__name__}-{self._fed_class_task_id}",
            )
            self._runtime.register_actor(self._actor_instance)

    def _execute_remote_method(
        self, method_name: str, options: dict, args: tuple, kwargs: dict
    ):
        num_returns = int(options.get("num_returns", 1)) if options else 1
        assert self._actor_instance is not None, (
            "actor methods can only execute in the owning party"
        )
        return self._actor_instance.call_method(
            method_name, args, kwargs, num_returns=num_returns
        )

    def _kill(self) -> None:
        if self._actor_instance is not None:
            self._actor_instance.kill()


class FedActorMethod:
    def __init__(
        self,
        runtime: Runtime,
        node_party: str,
        fed_actor_handle: FedActorHandle,
        method_name: str,
    ) -> None:
        self._runtime = runtime
        self._node_party = node_party
        self._fed_actor_handle = fed_actor_handle
        self._method_name = method_name
        self._options: dict = {}
        self._fed_call_holder = FedCallHolder(
            runtime, node_party, self._execute_impl
        )

    def remote(self, *args, **kwargs):
        return self._fed_call_holder.internal_remote(*args, **kwargs)

    def options(self, **options):
        self._options = options
        self._fed_call_holder.options(**options)
        return self

    def _execute_impl(self, args: tuple, kwargs: dict):
        return self._fed_actor_handle._execute_remote_method(
            self._method_name, self._options, args, kwargs
        )
