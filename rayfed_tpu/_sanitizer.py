"""Runtime lock-order sanitizer — the dynamic half of fedlint FED007.

The static pass (``tool/fedlint`` FED007) sees only *lexically* nested
``with <lock>:`` pairs.  The orderings that actually bite are dynamic:
a callback fired while a lock is held takes another lock three modules
away, two subsystems nest the same pair in opposite orders on different
threads.  This module catches those at test time:

- enabled via ``RAYFED_SANITIZE=1`` (``tests/conftest.py`` exports it,
  so every tier-1 test — party subprocesses included, env is inherited —
  runs under it); **near-zero cost when disabled**: nothing is patched.
- :func:`install` wraps ``threading.Lock`` / ``threading.RLock`` /
  ``threading.Condition`` *construction*.  Only locks constructed by
  code inside this repo are tracked — jax/stdlib/grpc locks get the
  real primitive untouched, keeping overhead bounded and the graph
  free of third-party noise.
- every tracked acquire records the per-thread acquisition stack and
  adds an acquired-before edge (previous innermost held → acquiring)
  to one process-global graph; the edge that closes a cycle raises
  :class:`LockOrderError` **at the moment the second ordering appears**
  — before blocking, i.e. before the interleaving that would actually
  deadlock has to occur.
- guard-lock refinement: orderings that disagree but always run under a
  common outer lock are serialized by that guard and not reported (the
  classic false positive of naive detectors).

The wrappers preserve ``threading.Condition`` compatibility
(``_is_owned`` / ``_release_save`` / ``_acquire_restore``), re-entrant
RLock semantics (re-acquiring a held lock records no edge), and treat
non-blocking ``acquire(blocking=False)`` as unable to deadlock (held
tracking only, no cycle check).
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
import weakref
from typing import Dict, List, Optional

ENV_VAR = "RAYFED_SANITIZE"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The real primitives, captured at import (before any patching).
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition


class LockOrderError(RuntimeError):
    """Two tracked locks were acquired in conflicting orders.

    Raised at the acquire that would *create* the cycle — the report
    names both orderings with the thread and stack that recorded the
    first one, so the fix (pick one global order) is mechanical.
    """


class _Edge:
    __slots__ = ("guards", "thread", "stack")

    def __init__(self, guards: frozenset, thread: str, stack: str) -> None:
        self.guards = guards
        self.thread = thread
        self.stack = stack


class _Graph:
    """Process-global acquired-before graph.

    Guarded by a REAL (untracked) lock; no user code ever runs while it
    is held, so the sanitizer cannot deadlock the program it watches.
    """

    def __init__(self) -> None:
        self._lock = _REAL_LOCK()
        # edge uid→uid2 means "uid held when uid2 was acquired".
        self._edges: Dict[int, Dict[int, _Edge]] = {}
        self._labels: Dict[int, str] = {}
        self._uid = 0
        # uids of GC'd locks, appended by weakref finalizers.  A
        # finalizer can fire via cyclic GC triggered by an allocation
        # made INSIDE `with self._lock` (record's frozensets, stack
        # capture...) on the same thread — taking the non-reentrant
        # lock there would self-deadlock, so finalizers only do a
        # lock-free list.append and the next graph operation drains it.
        self._pending_forget: List[int] = []

    def new_uid(self, label: str) -> int:
        with self._lock:
            self._uid += 1
            self._labels[self._uid] = label
            return self._uid

    def label(self, uid: int) -> str:
        return self._labels.get(uid, f"<lock #{uid}>")

    def reset(self) -> None:
        with self._lock:
            self._drain_forgotten_locked()
            self._edges.clear()

    def forget(self, uid: int) -> None:
        """Mark a garbage-collected lock for removal from the graph.

        Per-object locks (one per FedObject, per connection, ...) would
        otherwise grow the graph without bound over a long sanitized
        soak.  Nothing is lost semantically: a dead instance can never
        participate in a future deadlock, and fresh instances get fresh
        uids.  MUST stay lock-free — called from a weakref finalizer,
        potentially mid-GC on a thread already inside ``self._lock``.
        """
        self._pending_forget.append(uid)

    def _drain_forgotten_locked(self) -> None:
        while self._pending_forget:
            uid = self._pending_forget.pop()
            self._labels.pop(uid, None)
            self._edges.pop(uid, None)
            for targets in self._edges.values():
                targets.pop(uid, None)

    def snapshot(self) -> Dict[str, List[str]]:
        with self._lock:
            self._drain_forgotten_locked()
            return {
                self.label(a): sorted(self.label(b) for b in bs)
                for a, bs in self._edges.items()
            }

    def record(self, prev: int, new: int, guards: frozenset,
               thread_name: str) -> None:
        """Add edge prev→new; raise LockOrderError if it closes an
        unguarded cycle.  The cycle check runs BEFORE the edge is
        stored and before the caller blocks on the real acquire."""
        with self._lock:
            self._drain_forgotten_locked()
            known = self._edges.setdefault(prev, {})
            existing = known.get(new)
            # The edge's effective guard set is the weakest seen across
            # occurrences — a later occurrence under FEWER guards can
            # turn a previously-serialized cycle into a real one, so the
            # cycle check re-runs whenever the set shrinks (an
            # unchanged/superset occurrence carries no new information).
            eff_guards = guards if existing is None \
                else existing.guards & guards
            if existing is not None and eff_guards == existing.guards:
                return
            path = self._find_path(new, prev)
            if path is not None:
                common = eff_guards
                for a, b in path:
                    common = common & self._edges[a][b].guards
                if not common:
                    # Raise WITHOUT storing: the cycle stays on record
                    # as unresolved, so every recurrence re-raises.
                    raise LockOrderError(self._render(prev, new, path,
                                                      thread_name))
            if existing is not None:
                existing.guards = eff_guards
            else:
                known[new] = _Edge(
                    eff_guards, thread_name,
                    "".join(
                        traceback.format_stack(sys._getframe(3), limit=5)
                    ),
                )

    def _find_path(self, start: int, goal: int) -> Optional[List]:
        """BFS start→goal over recorded edges; returns the edge list."""
        if start not in self._edges:
            return None
        seen = {start}
        frontier = [(start, [])]
        while frontier:
            node, path = frontier.pop(0)
            for nxt in self._edges.get(node, ()):
                if nxt == goal:
                    return path + [(node, nxt)]
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append((nxt, path + [(node, nxt)]))
        return None

    def _render(self, prev: int, new: int, path, thread_name: str) -> str:
        lines = [
            "lock-order cycle detected (RAYFED_SANITIZE): thread "
            f"{thread_name!r} is acquiring {self.label(new)} while "
            f"holding {self.label(prev)}, but the REVERSE ordering is "
            "already on record:",
        ]
        for a, b in path:
            e = self._edges[a][b]
            lines.append(
                f"  {self.label(a)} acquired-before {self.label(b)} "
                f"on thread {e.thread!r} at:\n{e.stack.rstrip()}"
            )
        lines.append(
            "pick one global acquisition order (or guard both orderings "
            "with a common outer lock)."
        )
        return "\n".join(lines)


_GRAPH = _Graph()
_TLS = threading.local()
_installed = False


def _held() -> List[int]:
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    return held


class _TrackedBase:
    """Shared acquire/release bookkeeping around a real primitive."""

    __slots__ = ("_inner", "_uid", "_owner_held", "__weakref__")

    def __init__(self, inner, label: str) -> None:
        self._inner = inner
        self._uid = _GRAPH.new_uid(label)
        # The held-list of the thread that last acquired this lock —
        # plain Locks may legally be RELEASED on a different thread
        # (signaling idiom), and the release must fix up the ACQUIRER's
        # bookkeeping, not the releaser's.
        self._owner_held: Optional[List[int]] = None
        # Bound memory: a GC'd lock leaves the global graph.
        weakref.finalize(self, _GRAPH.forget, self._uid)

    # -- ordering hooks ------------------------------------------------------

    def _before_blocking_acquire(self) -> None:
        # Snapshot: a cross-thread release (_pop's owner-list scrub) may
        # shrink the live list between the emptiness check and the
        # [-1] read — bookkeeping must never crash the acquiring thread.
        held = list(_held())
        if not held or self._uid in held:
            return  # first lock on this thread / re-entrant re-acquire
        _GRAPH.record(
            held[-1], self._uid,
            frozenset(held[:-1]),
            threading.current_thread().name,
        )

    def _push(self) -> None:
        held = _held()
        held.append(self._uid)
        self._owner_held = held

    def _pop(self) -> Optional[List[int]]:
        """Remove this lock's bookkeeping entry; returns the list it was
        removed from (for rollback), or None when no entry was found."""
        held = _held()
        # Out-of-order releases are legal for plain locks — remove the
        # LAST occurrence of this uid, wherever it sits.
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self._uid:
                del held[i]
                return held
        # Released on a DIFFERENT thread than the acquirer (legal for
        # plain Locks): scrub the acquirer's held list instead, or every
        # later acquire on that thread would record bogus edges from
        # this stale entry.  Best-effort under the GIL; bookkeeping must
        # never crash the program it watches.
        owner = self._owner_held
        if owner is not None and owner is not held:
            try:
                for i in range(len(owner) - 1, -1, -1):
                    if owner[i] == self._uid:
                        del owner[i]
                        return owner
            except (IndexError, ValueError):  # pragma: no cover - racy scrub
                pass
        return None

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            self._before_blocking_acquire()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._push()
        return ok

    def release(self) -> None:
        # Order matters per subclass: plain Locks pop BEFORE the real
        # release (SanitizedLock overrides) — releasing first opens a
        # window where a racing acquirer overwrites _owner_held and the
        # cross-thread scrub deletes the NEW holder's entry.  RLocks
        # keep release-first: a cross-thread RLock release is illegal
        # and must raise from the inner lock WITHOUT any scrub running.
        self._inner.release()
        self._pop()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<sanitized {self._inner!r} as {_GRAPH.label(self._uid)}>"


class SanitizedLock(_TrackedBase):
    __slots__ = ()

    def release(self) -> None:
        # Pop while the lock is STILL HELD: after the real release a
        # blocked acquirer can win the lock and repoint _owner_held at
        # its own list before our cross-thread scrub runs, which would
        # strip the new holder's entry and leave the old one stale.
        removed_from = self._pop()
        try:
            self._inner.release()
        except BaseException:
            if removed_from is not None:  # release didn't happen: undo
                removed_from.append(self._uid)
            raise


class SanitizedRLock(_TrackedBase):
    """Tracked RLock — also speaks ``threading.Condition``'s private
    protocol so a repo ``Condition()`` tracks its underlying lock."""

    __slots__ = ()

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        # Condition.wait: full release regardless of recursion depth —
        # drop every held entry for this uid.
        held = _held()
        held[:] = [u for u in held if u != self._uid]
        return self._inner._release_save()

    def _acquire_restore(self, state) -> None:
        # Reacquire on wakeup blocks like a fresh acquire — but the
        # Condition's lock state must be RESTORED even when the order
        # check trips: raising un-held would make the enclosing `with
        # cond:` exit fail with 'cannot release un-acquired lock',
        # masking the cycle report.  So: restore first, then check (the
        # pre-push held list gives the same edges a fresh acquire would
        # record), and push in a finally so the bookkeeping matches the
        # actually-held lock even while the report propagates.
        self._inner._acquire_restore(state)
        try:
            self._before_blocking_acquire()
        finally:
            self._push()


def _caller_is_tracked(depth: int) -> bool:
    """True when the construction site is repo code (rayfed_tpu, tests,
    bench) — third-party and stdlib construction sites get real locks."""
    try:
        frame = sys._getframe(depth)
    except ValueError:  # pragma: no cover - shallow stack
        return False
    filename = frame.f_code.co_filename
    return (
        filename.startswith(_REPO_ROOT)
        and "site-packages" not in filename
    )


def _site_label(depth: int) -> str:
    frame = sys._getframe(depth)
    rel = os.path.relpath(frame.f_code.co_filename, _REPO_ROOT)
    return f"{rel}:{frame.f_lineno}"


def _lock_factory():
    if _caller_is_tracked(2):
        return SanitizedLock(_REAL_LOCK(), _site_label(2))
    return _REAL_LOCK()


def _rlock_factory():
    if _caller_is_tracked(2):
        return SanitizedRLock(_REAL_RLOCK(), _site_label(2))
    return _REAL_RLOCK()


def _condition_factory(lock=None):
    # A repo Condition() with no explicit lock gets a TRACKED RLock, so
    # `with cond:` participates in the ordering graph (fl/streaming's
    # _cond is exactly this shape).
    if lock is None and _caller_is_tracked(2):
        lock = SanitizedRLock(_REAL_RLOCK(), _site_label(2) + " (Condition)")
    return _REAL_CONDITION(lock)


def install() -> bool:
    """Patch lock construction process-wide.  Idempotent.  Call BEFORE
    the modules whose locks you want tracked are imported (rayfed_tpu's
    ``__init__`` does, when ``RAYFED_SANITIZE=1``)."""
    global _installed
    if _installed:
        return False
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _installed = True
    return True


def uninstall() -> None:
    global _installed
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    """Clear the acquired-before graph (test isolation)."""
    _GRAPH.reset()


def graph_snapshot() -> Dict[str, List[str]]:
    """{lock label: [labels it was acquired before]} — debugging aid."""
    return _GRAPH.snapshot()


def maybe_install_from_env() -> bool:
    return os.environ.get(ENV_VAR) == "1" and install()
