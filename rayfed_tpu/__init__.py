"""rayfed_tpu — a TPU-native cross-silo federated execution engine.

A brand-new JAX/XLA-first framework with the capabilities of RayFed
(reference: fengsp/rayfed): multi-controller execution where every party
runs the same driver program, party-pinned ``@remote`` tasks/actors, and a
push-based transport where the data owner initiates cross-party transfers.

Unlike the reference (a thin shim over Ray + gRPC + cloudpickle), this
framework is designed for TPUs from the start:

- per-party compute dispatches to (optionally pjit-compiled) JAX callables
  on the party's local device mesh instead of Ray GPU workers;
- cross-party payloads travel as raw array bytes (zero-copy tensor wire
  format) over an asyncio DCN socket transport, not pickle-of-host-copy;
- intra-party scaling is first-class: DP/FSDP/TP/SP/EP/PP sharding
  strategies, ring attention and Ulysses sequence parallelism live in
  :mod:`rayfed_tpu.parallel`;
- model families (logistic regression, ResNet, BERT, Llama + LoRA) and
  federated algorithms (FedAvg, split/vertical FL) are included.

Public API surface mirrors the reference (``fed/__init__.py:15-29``):
``init``, ``shutdown``, ``remote``, ``get``, ``kill``, ``send``, ``recv``,
``FedObject``.
"""

# Lock-order sanitizer (RAYFED_SANITIZE=1): must install BEFORE the
# submodules below run — their module/instance locks are constructed at
# import time and only locks built after install() are tracked.  No-op
# (one env read) when the flag is unset.
from rayfed_tpu import _sanitizer as _sanitizer

_sanitizer.maybe_install_from_env()

from rayfed_tpu.api import (
    init,
    shutdown,
    remote,
    get,
    kill,
    join,
    leave,
    set_max_message_length,
    trace_collect,
    metrics_snapshot,
)
from rayfed_tpu.exceptions import RemoteError
from rayfed_tpu.fed_object import FedObject
from rayfed_tpu.metrics import get_stats
from rayfed_tpu.proxy import send, recv
from rayfed_tpu import telemetry, tree_util

__version__ = "0.4.0"

__all__ = [
    "init",
    "shutdown",
    "remote",
    "get",
    "kill",
    "join",
    "leave",
    "send",
    "recv",
    "set_max_message_length",
    "FedObject",
    "RemoteError",
    "tree_util",
    "get_stats",
    "trace_collect",
    "metrics_snapshot",
    "telemetry",
    "__version__",
]
