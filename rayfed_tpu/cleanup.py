"""Send watchdog — delivery tracking + exit-on-failure.

Capability parity with reference ``fed/cleanup.py``: a background thread
drains the queue of in-flight send results; a failed send (False result or
exception) optionally SIGTERMs the process; a monitor thread joins the
main thread so pending sends are flushed at interpreter exit; and
``wait_sending`` blocks shutdown until the queue is drained.

Unlike the reference's module globals, state lives on a per-Runtime
:class:`CleanupManager` so multiple in-process parties don't share a queue.
"""

from __future__ import annotations

import logging
import os
import queue
import signal
import threading
from typing import Optional, Union

from rayfed_tpu.executor import LocalRef

logger = logging.getLogger(__name__)

_SENTINEL = object()


class CleanupManager:
    def __init__(self, exit_on_failure_sending: bool = False) -> None:
        self._q: "queue.Queue[Union[LocalRef, object]]" = queue.Queue()
        self._exit_on_failure = exit_on_failure_sending
        self._check_thread: Optional[threading.Thread] = None
        self._monitor_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def set_exit_on_failure_sending(self, flag: bool) -> None:
        self._exit_on_failure = flag

    @property
    def check_thread_alive(self) -> bool:
        t = self._check_thread
        return t is not None and t.is_alive()

    def _signal_exit(self) -> None:
        os.kill(os.getpid(), signal.SIGTERM)

    def _check_sending_objs(self) -> None:
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                break
            assert isinstance(item, LocalRef)
            try:
                res = item.resolve()
            except Exception as e:
                logger.warning("Failed to send %s with error: %s", item, e)
                res = False
            if not res and self._exit_on_failure:
                logger.warning("Signal self to exit.")
                self._signal_exit()
                break
        logger.debug("Check sending thread exited.")

    def _main_thread_monitor(self) -> None:
        threading.main_thread().join()
        self.notify_to_exit()

    def start(self) -> None:
        with self._lock:
            if self._check_thread is None or not self._check_thread.is_alive():
                self._check_thread = threading.Thread(
                    target=self._check_sending_objs, name="rayfed-send-watchdog"
                )
                self._check_thread.start()
            if self._monitor_thread is None or not self._monitor_thread.is_alive():
                self._monitor_thread = threading.Thread(
                    target=self._main_thread_monitor,
                    name="rayfed-main-monitor",
                    daemon=True,
                )
                self._monitor_thread.start()

    def push_to_sending(self, ref: LocalRef) -> None:
        self.start()
        self._q.put(ref)

    def notify_to_exit(self) -> None:
        self._q.put(_SENTINEL)

    def wait_sending(self) -> None:
        """Block until every tracked send completed (ref ``cleanup.py:115-119``)."""
        with self._lock:
            thread = self._check_thread
        if thread is not None and thread.is_alive():
            self.notify_to_exit()
            thread.join()
        with self._lock:
            self._check_thread = None
