"""Content-addressed object handles — the fingerprint-passing half of
the pull-on-demand object plane.

Large immutable objects (base weights, join welcomes, checkpoint
snapshots) used to be eagerly pushed by their owner on every transfer,
even when the receiver already held the identical bytes — RayFed's
transport is purely push-based.  The object plane splits "name the
bytes" from "move the bytes", per "Transparent Object Proxies":

- the OWNER serializes once, fingerprints the wire bytes
  (:func:`rayfed_tpu.transport.wire.blob_fingerprint` — the single
  producer, built on the delta-cache's chunk-CRC machinery) and passes
  a small **handle** ``{fingerprint, nbytes, holders}``;
- the RECEIVER resolves the handle lazily: a content-cache hit costs
  zero payload bytes; a miss issues a ``BLOB_GET`` pull to any named
  holder and caches the verified bytes by content
  (:class:`rayfed_tpu.transport.objectstore.ObjectPlane`).

This module is the schema + resolve layer: the single producers of the
handle / request / reply-metadata shapes (fingerprinted as cross-party
contracts by ``tool/check_wire_format.py``), plus the helpers the
``fed.get`` receive path and ``fed.join`` use to turn a handle back
into the object it names.  The transport half — the bounded
content-addressed store and the pull protocol — lives in
:mod:`rayfed_tpu.transport.objectstore`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

# Version of the handle / pull-protocol semantics (what a fingerprint
# covers, how holders are tried, the request/reply schemas).  Like
# RING_STRIPE_VERSION / SECAGG_VERSION this is a payload-level contract
# knob: bumping it re-pins tool/wire_format.lock WITHOUT a
# WIRE_FORMAT_VERSION bump — the frame layout itself is untouched.
OBJECT_PLANE_VERSION = 1

# The sentinel key marking a dict payload as a blob handle (its value
# is the protocol version).  A receiver that decodes a handle but has
# no object plane MUST fail loudly rather than hand the dict to user
# code as if it were the object.
BLOB_HANDLE_MARK = "__rayfed_blob__"


class ObjectPlaneError(RuntimeError):
    """A blob pull could not complete (no holder had the bytes, every
    holder was dead/corrupt, or the resolver has no object plane)."""


# ---------------------------------------------------------------------------
# Schemas — single producers, fingerprinted by tool/check_wire_format.py
# ---------------------------------------------------------------------------


def make_blob_handle(
    fp: str, nbytes: int, holders: Sequence[str]
) -> Dict[str, Any]:
    """The handle passed IN PLACE of a large immutable object: content
    fingerprint, payload size, and the parties known to hold the bytes
    (tried in order by the puller, with dead-holder failover)."""
    holders = [str(h) for h in holders]
    if not holders:
        raise ValueError("a blob handle must name at least one holder")
    return {
        BLOB_HANDLE_MARK: int(OBJECT_PLANE_VERSION),
        "fp": str(fp),
        "n": int(nbytes),
        "holders": holders,
    }


def is_blob_handle(value: Any) -> bool:
    return isinstance(value, dict) and BLOB_HANDLE_MARK in value


def check_blob_handle(handle: Any) -> Dict[str, Any]:
    """Validate a received handle; loud errors, never silent garbage."""
    if not is_blob_handle(handle):
        raise ObjectPlaneError(f"not a blob handle: {type(handle).__name__}")
    ver = handle.get(BLOB_HANDLE_MARK)
    if int(ver) > OBJECT_PLANE_VERSION:
        raise ObjectPlaneError(
            f"blob handle uses object-plane protocol v{ver}; this party "
            f"understands up to v{OBJECT_PLANE_VERSION} — upgrade the "
            f"receiving party"
        )
    fp, n, holders = handle.get("fp"), handle.get("n"), handle.get("holders")
    if not isinstance(fp, str) or not fp:
        raise ObjectPlaneError(f"blob handle carries no fingerprint: {handle!r}")
    if not isinstance(n, int) or n < 0:
        raise ObjectPlaneError(f"blob handle carries a bad size: {handle!r}")
    if not isinstance(holders, list) or not holders:
        raise ObjectPlaneError(f"blob handle names no holders: {handle!r}")
    return {
        BLOB_HANDLE_MARK: int(ver),
        "fp": fp,
        "n": n,
        "holders": [str(h) for h in holders],
    }


def make_blob_request(fp: str, reply_key: str) -> Dict[str, Any]:
    """The ``wire.BLOB_GET_KEY`` frame-metadata value: a pull request
    naming the wanted fingerprint and the reply rendezvous key the
    requester is already parked on (so the holder's reply needs no
    negotiation)."""
    return {
        "v": int(OBJECT_PLANE_VERSION),
        "fp": str(fp),
        "rk": str(reply_key),
    }


def check_blob_request(req: Any) -> Dict[str, Any]:
    if not isinstance(req, dict):
        raise ObjectPlaneError(f"malformed blob request: {req!r}")
    fp, rk = req.get("fp"), req.get("rk")
    if not isinstance(fp, str) or not fp or not isinstance(rk, str) or not rk:
        raise ObjectPlaneError(f"malformed blob request: {req!r}")
    return {"v": int(req.get("v", 1)), "fp": fp, "rk": rk}


def make_blob_reply_meta(
    fp: str, nbytes: Optional[int] = None, miss: bool = False
) -> Dict[str, Any]:
    """The ``wire.BLOB_PUT_KEY`` frame-metadata value: stamps a pull
    reply with the fingerprint it answers.  ``miss=True`` marks a
    payload-less "I don't hold these bytes" notice — the requester
    fails over to the next named holder immediately instead of waiting
    out the recv backstop."""
    d: Dict[str, Any] = {"v": int(OBJECT_PLANE_VERSION), "fp": str(fp)}
    if miss:
        d["miss"] = 1
    else:
        d["n"] = int(nbytes if nbytes is not None else 0)
    return d


def check_blob_reply_meta(rep: Any) -> Dict[str, Any]:
    if not isinstance(rep, dict) or not isinstance(rep.get("fp"), str):
        raise ObjectPlaneError(f"malformed blob reply metadata: {rep!r}")
    return rep


# ---------------------------------------------------------------------------
# Serialize / deserialize — the wire codec applied to one standalone blob
# ---------------------------------------------------------------------------


def canonical_host(value: Any) -> Any:
    """Residency-normalized copy of a pytree: every array leaf fetched
    to host numpy.

    The wire codec stamps a leaf's manifest with WHERE it lived
    (``dev``) — so two controllers holding the same VALUES at different
    residencies (the coordinator's freshly finalized device array vs a
    member's decoded host view) would serialize to different bytes and
    derive DIFFERENT fingerprints, silently splitting the content
    space.  Every publish site that needs cross-controller fingerprint
    agreement (the quorum loop's round-model slot, welcome-carried
    server-opt state) canonicalizes first; owner-scoped publishes
    (fed.get offers — only the owner ever fingerprints) don't need to.
    """
    import jax
    import numpy as np

    return jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x))
        if isinstance(x, (jax.Array, np.ndarray))
        else x,
        value,
    )


def serialize_blob(value: Any) -> bytes:
    """One contiguous wire-payload byte string for ``value`` — exactly
    the bytes an eager push of the same object would put on the wire
    (``wire.encode_payload`` framing), so a handle-resolved object
    decodes BYTE-identically to the eager-push path.  Lazy shard
    encoding is off: a stored blob must be self-contained bytes."""
    from rayfed_tpu.transport import wire

    bufs = wire.encode_payload(value, lazy_shards=False)
    return b"".join(
        bytes(b) if not isinstance(b, (bytes, bytearray)) else b
        for b in bufs
    )


def fingerprint_value(value: Any) -> tuple:
    """``(fingerprint, serialized bytes)`` of one object — fingerprint
    determinism across controllers is what makes handle equality mean
    content equality (tested in tests/test_objectstore.py)."""
    from rayfed_tpu.transport import wire

    data = serialize_blob(value)
    return wire.blob_fingerprint(data), data


def deserialize_blob(
    data,
    allowed: Optional[Dict[str, Any]] = None,
    device_put: bool = False,
    mesh: Any = None,
    zero_copy: bool = False,
) -> Any:
    from rayfed_tpu.transport import wire

    return wire.decode_payload(
        data, allowed=allowed, device_put=device_put, mesh=mesh,
        zero_copy=zero_copy,
    )


# ---------------------------------------------------------------------------
# Resolve — turn a received handle back into the object it names
# ---------------------------------------------------------------------------


def maybe_resolve_handle(
    transport: Any, value: Any, timeout: Optional[float] = None
) -> Any:
    """If ``value`` is a blob handle, pull/decode the object it names
    through ``transport``'s object plane; otherwise return it
    unchanged.  The ``fed.get`` receive path chains this after decode,
    so handle-passing is transparent to callers.

    A handle arriving at a transport WITHOUT an object plane (e.g. a
    multi-host non-leader bridge) raises loudly — handing user code
    the raw handle dict as if it were the object would be the silent
    failure mode this layer refuses.
    """
    if not is_blob_handle(value):
        return value
    handle = check_blob_handle(value)
    plane = getattr(transport, "objects", None)
    if plane is None:
        raise ObjectPlaneError(
            f"received a blob handle for {handle['fp']} but this "
            f"transport has no object plane to resolve it (multi-host "
            f"non-leader bridges cannot pull; disable handle offers on "
            f"the sender with blob_broadcast_min_bytes=None)"
        )
    return plane.fetch(handle, timeout_s=timeout)


def holders_for(handle: Dict[str, Any], exclude: Sequence[str] = ()) -> List[str]:
    """The handle's holders minus ``exclude`` (typically the local
    party), order preserved — the pull's failover order."""
    skip = set(exclude)
    return [h for h in handle["holders"] if h not in skip]
