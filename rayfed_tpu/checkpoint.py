"""Per-party checkpoint / resume for federated training state.

The reference has **no** checkpointing (SURVEY §5.4); the closest
artifact is seq-id determinism making reruns reproduce the same DAG.
Here checkpoint/resume is first-class: each party snapshots its local
state (params, optimizer, FL round counter, anything pytree-shaped)
under its own directory; on restart the parties restore the latest
common round and the deterministic seq-id contract takes care of the
rest (all parties re-enter the same rendezvous sequence).

Orbax-backed when available (it is in the standard environment), with a
plain ``.npz`` fallback.  Device arrays are fetched to host on save and
restored as numpy — callers re-place them onto their mesh
(``ShardingStrategy.shard_params``) so checkpoints are portable across
mesh shapes (reshard-on-restore).
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import time
from typing import Any, Optional, Tuple

import jax
import numpy as np

from rayfed_tpu import telemetry

logger = logging.getLogger(__name__)

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    ocp = None
    _HAVE_ORBAX = False


def _to_host(tree: Any) -> Any:
    # Delegates to the object plane's residency normalizer: checkpoint
    # fingerprints and welcome/model fingerprints must agree on ONE
    # canonical host form, or value-equal trees would silently split
    # the content space (see objects.canonical_host).
    from rayfed_tpu.objects import canonical_host

    return canonical_host(tree)


class FedCheckpointer:
    """Round-indexed checkpoints for one party.

    Layout: ``{directory}/{party}/round_{n}/`` (+ ``meta.json``).
    """

    def __init__(
        self,
        directory: str,
        party: str,
        *,
        max_to_keep: int = 3,
        use_orbax: Optional[bool] = None,
        object_plane: Any = None,
    ) -> None:
        self._dir = os.path.join(os.path.abspath(directory), party)
        os.makedirs(self._dir, exist_ok=True)
        self._party = party
        self._max_to_keep = max_to_keep
        self._use_orbax = _HAVE_ORBAX if use_orbax is None else use_orbax
        if self._use_orbax and not _HAVE_ORBAX:  # pragma: no cover
            raise RuntimeError("orbax requested but not importable")
        # Content-addressed fast path (transport/objectstore.py): save
        # stamps each snapshot's wire-bytes fingerprint into meta.json
        # and publishes the bytes into the party's object plane;
        # restore resolves the fingerprint against the content cache
        # BEFORE touching disk — a warm restore (same process, or the
        # blob still cached from the round loop) decodes from memory.
        # Explicit object_plane= overrides the runtime discovery (tests
        # and standalone tooling).
        self._object_plane = object_plane

    def _plane(self):
        if self._object_plane is not None:
            return self._object_plane
        from rayfed_tpu.runtime import get_runtime_or_none

        runtime = get_runtime_or_none()
        transport = getattr(runtime, "transport", None) if runtime else None
        return getattr(transport, "objects", None)

    # -- paths ---------------------------------------------------------------

    def _round_dir(self, round_num: int) -> str:
        return os.path.join(self._dir, f"round_{round_num:08d}")

    def _recover(self) -> None:
        """Finish an interrupted save: a ``round_N.old`` left behind by a
        crash is promoted back to ``round_N`` if the canonical dir is
        missing, or deleted if the canonical dir completed."""
        for name in os.listdir(self._dir):
            m = re.fullmatch(r"(round_\d+)\.old", name)
            if not m:
                continue
            old_path = os.path.join(self._dir, name)
            canonical = os.path.join(self._dir, m.group(1))
            if os.path.exists(os.path.join(canonical, "meta.json")):
                shutil.rmtree(old_path)
            else:
                if os.path.exists(canonical):
                    shutil.rmtree(canonical)  # incomplete promote
                os.replace(old_path, canonical)

    def rounds(self) -> list[int]:
        self._recover()
        out = []
        for name in os.listdir(self._dir):
            m = re.fullmatch(r"round_(\d+)", name)
            if m and os.path.exists(os.path.join(self._dir, name, "meta.json")):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_round(self) -> Optional[int]:
        rounds = self.rounds()
        return rounds[-1] if rounds else None

    # -- save / restore ------------------------------------------------------

    def save(self, round_num: int, state: Any, *, metadata: Optional[dict] = None):
        """Snapshot ``state`` (any pytree) as round ``round_num``.

        Beside the on-disk snapshot, the state's serialized wire bytes
        are fingerprinted (``wire.blob_fingerprint`` — the same single
        producer welcome handles use) and published into the party's
        object plane when one is available; ``meta.json`` carries the
        stamp so :meth:`restore` can resolve the snapshot by CONTENT
        before touching disk."""
        t0_wall, t0 = time.time(), time.perf_counter()
        host_state = _to_host(state)
        blob_stamp: dict = {}
        plane = self._plane()
        if plane is not None:
            try:
                from rayfed_tpu import objects as _objects

                fp, data = _objects.fingerprint_value(host_state)
                # Unpinned: the cached snapshot is a warm-restore
                # OPTIMIZATION with a durable disk fallback — it must
                # never permanently consume budget the live round
                # state (pinned models, broadcast offers) needs.
                plane.publish(data=data)
                blob_stamp = {"blob_fp": fp, "blob_n": len(data)}
            except Exception:  # pragma: no cover - plane must not
                logger.exception(  # break the durable disk path
                    "[%s] checkpoint blob publish failed; disk "
                    "snapshot proceeds without a fingerprint stamp",
                    self._party,
                )
        path = self._round_dir(round_num)
        tmp = path + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp, exist_ok=True)
        if self._use_orbax:
            ckpt = ocp.PyTreeCheckpointer()
            ckpt.save(os.path.join(tmp, "state"), host_state)
        else:
            leaves, _treedef = jax.tree_util.tree_flatten(host_state)
            np.savez(
                os.path.join(tmp, "state.npz"),
                **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)},
            )
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(
                {"round": round_num, "party": self._party, **blob_stamp,
                 **(metadata or {})}, f
            )
        # Keep a complete checkpoint under SOME name at every instant: move
        # the old round aside, promote the new one, then drop the old copy —
        # a crash mid-sequence leaves either round_N or round_N.old intact
        # (never only an undiscoverable .tmp).
        old = path + ".old"
        if os.path.exists(old):
            shutil.rmtree(old)
        if os.path.exists(path):
            os.replace(path, old)
        os.replace(tmp, path)
        if os.path.exists(old):
            shutil.rmtree(old)
        self._gc()
        telemetry.emit(
            "ckpt.save", party=self._party, round=round_num,
            t_start=t0_wall, dur_s=time.perf_counter() - t0,
            nbytes=int(blob_stamp.get("blob_n", 0)),
            detail=blob_stamp or None,
        )
        logger.info("[%s] checkpoint saved: round %d", self._party, round_num)

    def restore(
        self, round_num: Optional[int] = None, *, target: Any = None
    ) -> Tuple[int, Any]:
        """Restore (round, state).  ``round_num=None`` → latest.

        ``target``: example pytree giving the structure (required for the
        npz fallback; with orbax it restores the saved structure and
        ``target`` is optional).
        """
        # Finish any interrupted save first: an explicit round_num must
        # also find a checkpoint the crash left as ``round_N.old``
        # (rounds()/latest_round() already recover; this path must too).
        self._recover()
        if round_num is None:
            round_num = self.latest_round()
            if round_num is None:
                raise FileNotFoundError(f"no checkpoints under {self._dir}")
        # Content-addressed fast path: resolve the snapshot by its
        # fingerprint stamp BEFORE touching the state files — a cache
        # hit decodes the exact saved bytes from memory (the meta.json
        # stamp is still read from disk: it is what names the content).
        t0_wall, t0 = time.time(), time.perf_counter()
        cached = self._restore_from_blob(round_num)
        if cached is not None:
            telemetry.emit(
                "ckpt.restore", party=self._party, round=round_num,
                t_start=t0_wall, dur_s=time.perf_counter() - t0,
                detail={"source": "blob"},
            )
            return round_num, cached
        path = self._round_dir(round_num)
        if self._use_orbax:
            ckpt = ocp.PyTreeCheckpointer()
            state = ckpt.restore(os.path.join(path, "state"))
            if target is not None:
                # Re-attach the target's container types (orbax returns
                # plain dicts/lists).
                t_leaves, t_def = jax.tree_util.tree_flatten(target)
                s_leaves = jax.tree_util.tree_leaves(state)
                if len(t_leaves) == len(s_leaves):
                    state = jax.tree_util.tree_unflatten(t_def, s_leaves)
        else:
            if target is None:
                raise ValueError("npz fallback restore requires target=")
            data = np.load(os.path.join(path, "state.npz"))
            t_leaves, t_def = jax.tree_util.tree_flatten(target)
            leaves = [data[f"leaf_{i}"] for i in range(len(t_leaves))]
            state = jax.tree_util.tree_unflatten(t_def, leaves)
        telemetry.emit(
            "ckpt.restore", party=self._party, round=round_num,
            t_start=t0_wall, dur_s=time.perf_counter() - t0,
            detail={"source": "disk"},
        )
        return round_num, state

    def _restore_from_blob(self, round_num: int) -> Optional[Any]:
        """The state pytree for ``round_num`` decoded from the object
        plane's content cache, or ``None`` (no plane, no stamp, cache
        miss, or a decode problem — every miss falls back to disk).

        The decode restores the EXACT saved container structure (the
        wire codec's skeleton), so no ``target`` re-attachment is
        needed, and the bytes are the fingerprinted ones — content
        equality is structural, not trusted."""
        plane = self._plane()
        if plane is None:
            return None
        try:
            meta_path = os.path.join(self._round_dir(round_num), "meta.json")
            with open(meta_path) as f:
                fp = json.load(f).get("blob_fp")
        except OSError:
            return None
        if not fp:
            return None
        data = plane.fetch_local_bytes(fp)
        if data is None:
            return None
        try:
            from rayfed_tpu import objects as _objects

            state = _objects.deserialize_blob(data)
        except Exception:  # pragma: no cover - corrupt cache entry
            logger.exception(
                "[%s] checkpoint blob %s failed to decode; falling "
                "back to the disk snapshot", self._party, fp,
            )
            return None
        logger.info(
            "[%s] checkpoint round %d restored from the content cache "
            "(%s) — disk state untouched", self._party, round_num, fp,
        )
        return state

    def load_metadata(self, round_num: Optional[int] = None) -> dict:
        """The ``meta.json`` of one round's snapshot (latest by
        default): the ``metadata=`` dict passed to :meth:`save` plus the
        ``round``/``party`` stamps.  Quorum runs store their roster
        epoch, member set, per-round member log and rendezvous session
        here — everything a deterministic resume needs beyond the
        params pytree."""
        self._recover()
        if round_num is None:
            round_num = self.latest_round()
            if round_num is None:
                raise FileNotFoundError(f"no checkpoints under {self._dir}")
        with open(os.path.join(self._round_dir(round_num), "meta.json")) as f:
            return json.load(f)

    def _gc(self) -> None:
        rounds = self.rounds()
        for stale in rounds[: -self._max_to_keep]:
            shutil.rmtree(self._round_dir(stale), ignore_errors=True)
