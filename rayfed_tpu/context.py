"""Global sequence-id context — the determinism backbone.

Every party deterministically walks the same logical DAG; task N on alice
*is* task N on bob because both allocate ids from this monotonic counter in
the shared code path only (capability of reference
``fed/_private/global_context.py:16-22``).  Any party-conditional counter
allocation would desync cross-party rendezvous keys, so the counter must be
bumped exactly once per logical call site on every party.
"""

from __future__ import annotations

import threading


class GlobalContext:
    """Monotonic per-job sequence counter.

    Thread-safe: task bodies may submit sub-calls from worker threads in
    simulation mode, so allocation takes a lock (the reference relied on the
    GIL; we make it explicit).
    """

    def __init__(self) -> None:
        self._seq_count = 0
        self._lock = threading.Lock()

    def next_seq_id(self) -> int:
        with self._lock:
            self._seq_count += 1
            return self._seq_count

    def current_seq_id(self) -> int:
        with self._lock:
            return self._seq_count
