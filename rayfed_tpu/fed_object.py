"""FedObject — the distributed future that crosses party boundaries.

Capability parity with reference ``fed/fed_object.py``: an owning party +
fed task id + an optional *local* handle (here a :class:`~rayfed_tpu.executor.LocalRef`
future into the party's executor instead of a ``ray.ObjectRef``), plus
exactly-once sending bookkeeping and recv-side caching.
"""

from __future__ import annotations

import threading
from typing import Any, Optional


class FedObjectSendingContext:
    """Tracks which parties this object was already (or is being) pushed to.

    The exactly-once dedup here is what makes broadcast-on-get and repeated
    cross-party arg use safe with >2 parties (reference
    ``fed/fed_object.py:18-31``).
    """

    def __init__(self) -> None:
        self._is_sending_or_sent: dict[str, bool] = {}
        self._lock = threading.Lock()

    def mark_is_sending_to_party(self, target_party: str) -> None:
        with self._lock:
            self._is_sending_or_sent[target_party] = True

    def was_sending_or_sent_to_party(self, target_party: str) -> bool:
        with self._lock:
            return target_party in self._is_sending_or_sent

    def mark_if_not_sending_to_party(self, target_party: str) -> bool:
        """Atomically test-and-set; returns True if WE should do the send."""
        with self._lock:
            if target_party in self._is_sending_or_sent:
                return False
            self._is_sending_or_sent[target_party] = True
            return True


class FedObject:
    """Handle for the result of a fed task.

    If ``node_party`` is the current party, ``local_ref`` is a live
    :class:`LocalRef`; otherwise it is ``None`` until (and unless) the value
    is received from the owner, at which point the received ref is cached
    (reference ``fed/fed_object.py:76-78``).
    """

    def __init__(
        self,
        node_party: str,
        fed_task_id: int,
        local_ref: Optional[Any],
        idx_in_task: int = 0,
    ) -> None:
        self._node_party = node_party
        self._local_ref = local_ref
        self._fed_task_id = fed_task_id
        self._idx_in_task = idx_in_task
        self._sending_context = FedObjectSendingContext()

    def get_local_ref(self):
        return self._local_ref

    # Reference-compatible alias (``fed/fed_object.py:54``).
    get_ray_object_ref = get_local_ref

    def get_fed_task_id(self) -> str:
        """Rendezvous-key half: ``"{seq}#{idx}"`` (reference ``fed_object.py:62-63``)."""
        return f"{self._fed_task_id}#{self._idx_in_task}"

    def get_party(self) -> str:
        return self._node_party

    def _mark_is_sending_to_party(self, target_party: str) -> None:
        self._sending_context.mark_is_sending_to_party(target_party)

    def _was_sending_or_sent_to_party(self, target_party: str) -> bool:
        return self._sending_context.was_sending_or_sent_to_party(target_party)

    def _mark_if_not_sending_to_party(self, target_party: str) -> bool:
        return self._sending_context.mark_if_not_sending_to_party(target_party)

    def _cache_local_ref(self, local_ref) -> None:
        """Cache the received local ref so repeated consumption skips recv."""
        self._local_ref = local_ref

    # Reference-compatible alias.
    _cache_ray_object_ref = _cache_local_ref

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "bound" if self._local_ref is not None else "placeholder"
        return (
            f"FedObject(party={self._node_party!r}, "
            f"task_id={self.get_fed_task_id()!r}, {state})"
        )
