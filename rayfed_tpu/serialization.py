"""Restricted deserialization — anti-pickle-attack allowlist.

Capability parity with reference ``fed/_private/serialization_utils.py``:
cross-silo payload bytes are untrusted, so any pickled sub-payload is
deserialized through a :class:`RestrictedUnpickler` whose ``find_class``
only admits allowlisted modules/classes.  The allowlist format matches the
reference (``serialization_utils.py:63-77``): a dict mapping module name →
list of attribute names, with ``"*"`` admitting every attribute of the
module, e.g. ``{"numpy": ["float64"], "pandas": "*"}``.

Unlike the reference (which monkey-patches ``cloudpickle.loads`` inside the
recv proxy, ``barriers.py:342-345``), the allowlist here is threaded
explicitly through the wire codec — no global mutation, safe with multiple
in-process parties.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Dict, Optional

import cloudpickle

# Internal types the wire codec itself needs; always admitted.  The
# packed-tree wire form (fl.compression.PackedTree) rides the pickled
# container skeleton, and its static spec carries a jax PyTreeDef —
# whose pickle references the jaxlib PyTreeDef class and the jax
# default registry.
_INTERNAL_ALLOWED = {
    ("rayfed_tpu.transport.wire", "_Skeleton"),
    ("rayfed_tpu.transport.wire", "_LeafSlot"),
    ("rayfed_tpu.fl.compression", "PackedTree"),
    ("rayfed_tpu.fl.compression", "PackSpec"),
    # Shared-grid integer wire form (compressed-domain aggregation):
    # the coded skeleton carries the class + its static grid descriptor.
    ("rayfed_tpu.fl.quantize", "QuantizedPackedTree"),
    ("rayfed_tpu.fl.quantize", "QuantMeta"),
    # Secure aggregation: the masked wire form (i32 codes on the shared
    # grid — rayfed_tpu.fl.secagg).
    ("rayfed_tpu.fl.secagg", "MaskedCodeTree"),
    # Hierarchical aggregation: a region's integer partial sum on the
    # shared grid (rayfed_tpu.fl.hierarchy).
    ("rayfed_tpu.fl.hierarchy", "RegionSumTree"),
    # Server-optimizer replicated state (rayfed_tpu.fl.server_opt):
    # travels the wire exactly once per joiner, inside the object-plane
    # blob a welcome's server_state handle names.
    ("rayfed_tpu.fl.server_opt", "PackedServerState"),
    ("jax._src.tree_util", "default_registry"),
}


def _is_internal_allowed(module: str, name: str) -> bool:
    if (module, name) in _INTERNAL_ALLOWED:
        return True
    # PyTreeDef's defining module moved across jaxlib versions
    # (jaxlib.xla_extension.pytree → jaxlib._jax.pytree); admit the class
    # by name from any jax-owned module rather than pinning one path.
    # Dot-anchored so e.g. "jaxlib_evil" does not slip through.
    if name == "PyTreeDef" and (
        module == "jaxlib" or module.startswith(("jaxlib.", "jax."))
    ):
        return True
    return False


def _compose_whitelist(allowed: Dict[str, Any]) -> tuple[set, set]:
    """Returns (exact {(module, name)}, wildcard {module})."""
    exact: set = set()
    wildcard: set = set()
    for module, names in (allowed or {}).items():
        if names == "*" or names is None:
            wildcard.add(module)
            continue
        if isinstance(names, str):
            names = [names]
        for name in names:
            if name == "*":
                wildcard.add(module)
            else:
                exact.add((module, name))
    return exact, wildcard


class RestrictedUnpickler(pickle.Unpickler):
    def __init__(self, file, allowed: Dict[str, Any], **kw) -> None:
        super().__init__(file, **kw)
        self._exact, self._wildcard = _compose_whitelist(allowed)

    def find_class(self, module: str, name: str):
        if _is_internal_allowed(module, name):
            return super().find_class(module, name)
        if (module, name) in self._exact:
            return super().find_class(module, name)
        # Wildcard admits the module and any of its submodules
        # (reference admits e.g. "numpy.core.numeric" under "numpy": "*").
        parts = module.split(".")
        for i in range(len(parts), 0, -1):
            if ".".join(parts[:i]) in self._wildcard:
                return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"global '{module}.{name}' is forbidden by the serializing allowed list"
        )


def restricted_loads(data: bytes, allowed: Dict[str, Any]) -> Any:
    return RestrictedUnpickler(io.BytesIO(data), allowed).load()


def loads(data: bytes, allowed: Optional[Dict[str, Any]] = None) -> Any:
    """Deserialize with the allowlist if one is configured, else plain loads.

    Matches reference behavior: the restriction is applied only when
    ``serializing_allowed_list`` was passed to ``fed.init``
    (``barriers.py:342-345``).
    """
    if allowed:
        return restricted_loads(data, allowed)
    return cloudpickle.loads(data)


def dumps(obj: Any) -> bytes:
    return cloudpickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
