"""Cross-party failure types.

The reference swallows a failed cross-silo send into ``False`` plus a log
line (``fed/barriers.py:244-248``) and the consumer side never learns why
its ``recv`` hangs.  SURVEY §7 sets "replicate, then improve (surfacing
errors on ``get``)" as the goal; :class:`RemoteError` is the improvement:
when a producer party's task raises (or its payload fails to encode), the
producer pushes a compact poison message to every rendezvous key it had
promised, and the consumer's ``fed.get`` raises this error within the
transport round-trip time instead of parking until the recv backstop.
"""

from __future__ import annotations

from typing import Optional, Sequence


class PartyWaitTimeout(TimeoutError):
    """A bounded wait on other parties expired, naming who was missing.

    Raised by deadline-bounded cross-party waits (streaming-aggregation
    sinks, quorum cutoffs that cannot reach *k*, parked recvs) instead
    of a bare ``TimeoutError`` — the first question at 3am is always
    "which party", so the exception answers it.
    """

    def __init__(self, message: str,
                 missing_parties: Optional[Sequence[str]] = None) -> None:
        self.missing_parties = sorted(missing_parties or [])
        if self.missing_parties:
            message = f"{message} (missing parties: {self.missing_parties})"
        super().__init__(message)


class RemoteError(RuntimeError):
    """A task in another party failed; raised on the consumer's ``fed.get``.

    Attributes:
        party: the party whose task (or encode step) failed.
        exc_type: the remote exception's class name, e.g. ``"ValueError"``.
        message: the remote exception's ``str()``.
    """

    def __init__(self, party: str, exc_type: str, message: str,
                 traceback_str: Optional[str] = None) -> None:
        self.party = party
        self.exc_type = exc_type
        self.message = message
        self.traceback_str = traceback_str
        detail = f"[{party}] {exc_type}: {message}"
        if traceback_str:
            detail += f"\n--- remote traceback ({party}) ---\n{traceback_str}"
        super().__init__(detail)

    def to_wire(self) -> dict:
        d = {"party": self.party, "type": self.exc_type, "msg": self.message}
        if self.traceback_str:
            d["tb"] = self.traceback_str
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "RemoteError":
        return cls(
            party=str(d.get("party", "?")),
            exc_type=str(d.get("type", "Exception")),
            message=str(d.get("msg", "")),
            traceback_str=d.get("tb"),
        )

    @classmethod
    def from_exception(cls, party: str, exc: BaseException) -> "RemoteError":
        import traceback

        tb = None
        if exc.__traceback__ is not None:
            tb = "".join(
                traceback.format_exception(type(exc), exc, exc.__traceback__)
            )
            # Bound the wire size: a deep traceback is diagnostics, not data.
            if len(tb) > 16384:
                tb = tb[-16384:]
        return cls(party, type(exc).__name__, str(exc), tb)
