"""Cluster / job configuration.

The reference round-trips two cloudpickled dicts through Ray's internal KV
(``fed/api.py:179-195`` → ``fed/config.py:54-79``) because its proxies live
in separate Ray worker processes.  Our process model is one controller per
party, so config is a plain in-process struct attached to the Runtime; the
*shape* of the config (cluster addresses, per-party overrides, TLS, retry
policy, serialization allowlist, message caps, timeouts) is preserved.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


DEFAULT_MAX_MESSAGE_SIZE = 500 * 1024 * 1024  # parity: grpc_options.py:27-28
DEFAULT_CROSS_SILO_TIMEOUT_S = 60  # parity: api.py:49


@dataclasses.dataclass
class RetryPolicy:
    """Client retry policy for cross-silo sends.

    Defaults mirror the reference's gRPC service config
    (``fed/_private/grpc_options.py:17-23``): 5 attempts, 5s initial
    backoff, 30s max, ×2 multiplier, retry on transport unavailability.

    ``jitter`` (default on) decorrelates the delays: N parties that all
    hit the same dead peer otherwise retry in lockstep — every backoff
    wave lands the reconnect storm at the same instant the peer comes
    back.  Uses the "decorrelated jitter" recurrence
    ``sleep = min(cap, U(base, 3·prev))`` rather than plain
    ``exp × U(0,1)``: successive delays still grow toward the cap, but
    two clients' sequences diverge after the first draw.
    """

    max_attempts: int = 5
    initial_backoff_s: float = 5.0
    max_backoff_s: float = 30.0
    backoff_multiplier: float = 2.0
    jitter: bool = True

    def next_backoff(
        self, prev: Optional[float], rng: Optional[Any] = None
    ) -> float:
        """Delay before the next attempt given the previous delay
        (``None`` for the first retry).  With ``jitter=False`` this is
        the exact legacy exponential sequence."""
        if not self.jitter:
            if prev is None:
                return self.initial_backoff_s
            return min(
                prev * self.backoff_multiplier, self.max_backoff_s
            )
        import random

        rng = rng if rng is not None else random
        lo = self.initial_backoff_s
        hi = max(lo, 3.0 * (prev if prev is not None else lo))
        return min(self.max_backoff_s, rng.uniform(lo, hi))

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "RetryPolicy":
        if not d:
            return cls()

        def _dur(v, default):
            # Accept gRPC-style "5s" strings for drop-in compat.
            if v is None:
                return default
            if isinstance(v, str) and v.endswith("s"):
                return float(v[:-1])
            return float(v)

        return cls(
            max_attempts=int(d.get("maxAttempts", d.get("max_attempts", 5))),
            initial_backoff_s=_dur(
                d.get("initialBackoff", d.get("initial_backoff_s")), 5.0
            ),
            max_backoff_s=_dur(d.get("maxBackoff", d.get("max_backoff_s")), 30.0),
            backoff_multiplier=float(
                d.get("backoffMultiplier", d.get("backoff_multiplier", 2.0))
            ),
            jitter=bool(d.get("retryJitter", d.get("jitter", True))),
        )


@dataclasses.dataclass
class PartyConfig:
    """Per-party entry in the cluster map (reference ``api.py:61-96``)."""

    address: str
    listen_addr: Optional[str] = None  # bind addr if different from advertised
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)
    transport_options: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PartyConfig":
        return cls(
            address=d["address"],
            listen_addr=d.get("listen_addr"),
            metadata=dict(d.get("metadata") or d.get("grpc_metadata") or {}),
            transport_options=dict(
                d.get("transport_options") or d.get("grpc_options") or {}
            ),
        )


@dataclasses.dataclass
class ClusterConfig:
    """Resolved cluster topology + security config for one job."""

    parties: Dict[str, PartyConfig]
    current_party: str
    tls_config: Optional[Dict[str, str]] = None
    serializing_allowed_list: Optional[Dict[str, Any]] = None

    @property
    def cluster_addresses(self) -> Dict[str, str]:
        return {p: c.address for p, c in self.parties.items()}

    def other_parties(self) -> List[str]:
        return [p for p in self.parties if p != self.current_party]

    def party_config(self, party: str) -> PartyConfig:
        return self.parties[party]


@dataclasses.dataclass
class JobConfig:
    """Job-wide knobs (reference ``fed/config.py:17-51``)."""

    cross_silo_timeout_s: float = DEFAULT_CROSS_SILO_TIMEOUT_S
    cross_silo_messages_max_size: int = DEFAULT_MAX_MESSAGE_SIZE
    retry_policy: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)
    exit_on_failure_sending: bool = False
    wait_for_ready: bool = False
    # TPU-native: put received array payloads on local devices eagerly.
    device_put_received: bool = True
    # With device_put_received=False, decode shard-streamed leaves as
    # READONLY views aliasing the wire buffer when their layout allows
    # (no assembly copy).  Opt-in: consumers that mutate received host
    # arrays in place need the default writable copies.
    zero_copy_host_arrays: bool = False
    # Per-link transport backend (transport/local.py): "auto" upgrades
    # a link to the peer's AF_UNIX listener (same host, proven via the
    # HELLO colocation advertisement) or the in-process shared-memory
    # handoff (same interpreter); "uds"/"shm" force one backend (loud
    # TCP fallback when it can't hold); "off" pins TCP.  Default off:
    # existing topologies keep their exact wire behavior unless opted
    # in here or per-party via transport_options={"local_link": ...}.
    local_link: str = "off"
    # Backstop deadline for a parked recv and TTL for unclaimed pushes.
    # Deliberately generous (peer *compute* time between rounds is
    # unbounded by the per-RPC timeout above); bounds leaked state from
    # desynced/dead peers without gating slow-but-healthy ones.
    recv_backstop_s: float = 3600.0
    mailbox_ttl_s: float = 3600.0
    # Peer-death fail-fast: while recvs are parked on a party, ping it
    # every peer_health_interval_s; after peer_death_pings consecutive
    # failures the pending recvs raise RemoteError naming the party
    # instead of parking until the backstop.  Pings probe the peer's
    # transport loop, not its task queue — slow compute can't trip this,
    # and a party only becomes eligible after it was reachable once
    # (startup skew parks, it doesn't kill).
    peer_failfast: bool = True
    peer_health_interval_s: float = 2.0
    peer_death_pings: int = 3
    # Content-addressed pull-on-demand object plane (transport/
    # objectstore.py).  blob_cache_budget_bytes bounds the per-party
    # content cache (pinned live-round state may exceed it; unpinned
    # entries evict LRU-first).  blob_broadcast_min_bytes: a fed.get
    # broadcast of a plain PackedTree at/above this size sends a
    # fingerprint HANDLE instead of the payload — receivers with a
    # content-cache hit transfer zero payload bytes, misses pull via
    # BLOB_GET.  None disables handle offers (required when any
    # RECEIVING party is a multi-host group: non-leader bridge
    # processes cannot pull).
    blob_cache_budget_bytes: int = 256 * 1024 * 1024
    blob_broadcast_min_bytes: Optional[int] = 8 * 1024 * 1024
    # Quorum rounds: publish each round's broadcast model into the
    # content cache on EVERY controller (one host copy + chunk-CRC +
    # sha256 per round) — what makes every member a named welcome
    # holder and a graceful leaver's rejoin warm.  Turn off for very
    # large models where that per-round cost outweighs rejoin savings:
    # welcomes still work (the coordinator publishes at welcome time;
    # member holders just reply miss → failover).
    blob_publish_round_models: bool = True
    # Federated flight recorder (rayfed_tpu/telemetry.py): arm the
    # bounded span ring for this party at fed.init (the RAYFED_TRACE=1
    # env var arms it too, like RAYFED_CHAOS).  Disarmed, every
    # emission site costs one module-global read; armed, a span write
    # is a ring append — never a sleep, never I/O — so tracing adds
    # ~zero to the round wall (bench-gated: trace_overhead_frac
    # <= 0.03).  trace_capacity bounds the ring (records, not bytes).
    trace: bool = False
    trace_capacity: int = 16384
