"""Pytree helpers built on ``jax.tree_util``.

The reference vendors PyTorch's ``_pytree`` (``fed/tree_util.py:15``) to
find FedObjects nested in containers.  On TPU the right substrate is JAX's
own registry-backed C++ pytree, which already handles dict / list / tuple /
namedtuple / OrderedDict and every user-registered JAX container, and is
what the compute layer uses for params — one tree language everywhere.

``FedObject`` and ``LocalRef`` are unregistered types, so they are leaves
automatically.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax


def tree_flatten(
    tree: Any, is_leaf: Optional[Callable[[Any], bool]] = None
) -> Tuple[list, Any]:
    """Flatten ``tree``; returns ``(leaves, treedef)``."""
    return jax.tree_util.tree_flatten(tree, is_leaf=is_leaf)


def tree_unflatten(leaves: list, treedef: Any) -> Any:
    """Inverse of :func:`tree_flatten` (note: leaves first, like the reference)."""
    return jax.tree_util.tree_unflatten(treedef, leaves)


def tree_map(fn: Callable, tree: Any, *rest: Any, **kw) -> Any:
    return jax.tree_util.tree_map(fn, tree, *rest, **kw)


def tree_leaves(tree: Any, is_leaf: Optional[Callable[[Any], bool]] = None) -> list:
    return jax.tree_util.tree_leaves(tree, is_leaf=is_leaf)
