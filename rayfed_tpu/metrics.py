"""Metrics, per-transfer instrumentation, and profiler hooks.

The reference keeps only two op counters on its proxy actors
(``_stats["send_op_count"]`` / ``_stats["receive_op_count"]``,
``barriers.py:200,296``) exposed via ``_get_stats``.  Here observability
is a real subsystem:

- :func:`get_stats` — aggregate runtime stats (op counts, bytes,
  seconds, effective GB/s, pending recvs, crc errors) from the party's
  transport; superset of the reference's counters.
- :class:`TransferLog` — optional per-transfer records (peer, seq ids,
  bytes, seconds) with a bounded ring buffer, for the GB/s north-star
  analysis.
- :func:`trace_span` — ``jax.profiler.TraceAnnotation`` context manager
  so framework phases (encode/send/recv/decode, fedavg rounds) show up
  on TPU profiler timelines.
- :func:`start_profile` / :func:`stop_profile` — thin wrappers over
  ``jax.profiler`` trace capture.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Any, Dict, Optional

import jax

from rayfed_tpu.runtime import get_runtime_or_none

TransferRecord = collections.namedtuple(
    "TransferRecord", ["direction", "peer", "up_id", "down_id", "nbytes", "seconds"]
)


class TransferLog:
    """Bounded ring of per-transfer records (thread-safe)."""

    def __init__(self, capacity: int = 1024) -> None:
        self._records: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._total = 0  # monotonic count of all records ever appended

    def record(self, direction, peer, up_id, down_id, nbytes, seconds) -> None:
        with self._lock:
            self._records.append(
                TransferRecord(direction, peer, str(up_id), str(down_id),
                               int(nbytes), float(seconds))
            )
            self._total += 1

    def records(self):
        with self._lock:
            return list(self._records)

    @property
    def total_recorded(self) -> int:
        """Monotonic append count — unlike ``len(records())``, never
        capped by the ring, so windows can be delimited correctly."""
        with self._lock:
            return self._total

    def records_since(self, total_before: int):
        """(records appended after the ``total_recorded`` snapshot,
        complete_flag).  ``complete_flag`` is False when the ring evicted
        part of the window — callers must not present a partial window
        as a full decomposition."""
        with self._lock:
            delta = self._total - total_before
            recs = list(self._records)
        if delta <= 0:
            return [], True
        if delta > len(recs):
            return recs, False
        return recs[-delta:], True

    def throughput_gbps(self, direction: Optional[str] = None) -> float:
        recs = [
            r for r in self.records()
            if (direction is None or r.direction == direction) and r.seconds > 0
        ]
        if not recs:
            return 0.0
        return sum(r.nbytes for r in recs) / sum(r.seconds for r in recs) / 1e9


_global_transfer_log = TransferLog()


def get_transfer_log() -> TransferLog:
    return _global_transfer_log


def get_stats() -> Dict[str, Any]:
    """Aggregate stats for the current party's runtime.

    Superset of the reference's proxy ``_get_stats``: send/receive op
    counts plus bytes, wall seconds, and effective send GB/s.
    """
    runtime = get_runtime_or_none()
    if runtime is None or getattr(runtime, "transport", None) is None:
        return {}
    stats = dict(runtime.transport.get_stats())
    secs = stats.get("send_seconds", 0.0)
    stats["send_gbps"] = (stats.get("send_bytes", 0) / secs / 1e9) if secs else 0.0
    return stats


@contextlib.contextmanager
def trace_span(name: str, **kwargs):
    """Annotate a block on the jax profiler timeline (no-op cost when no
    trace is being captured)."""
    with jax.profiler.TraceAnnotation(name, **kwargs):
        yield


def start_profile(log_dir: str) -> None:
    """Begin a jax profiler capture (TensorBoard-viewable)."""
    jax.profiler.start_trace(log_dir)


def stop_profile() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def timed(out: Dict[str, float], key: str):
    """Accumulate wall time of a block into ``out[key]``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        out[key] = out.get(key, 0.0) + (time.perf_counter() - t0)
