"""Metrics, per-transfer instrumentation, and profiler hooks.

The reference keeps only two op counters on its proxy actors
(``_stats["send_op_count"]`` / ``_stats["receive_op_count"]``,
``barriers.py:200,296``) exposed via ``_get_stats``.  Here observability
is a real subsystem, in three layers:

- **counters** — :func:`get_stats` (aggregate runtime stats: op counts,
  bytes, seconds, effective GB/s, pending recvs, crc errors, the
  send-path stage breakdown, plus the ``secagg`` / ``object_plane`` /
  ``telemetry`` sections) and :func:`metrics_snapshot`, which gathers
  every subsystem's counters under ONE documented schema
  (:data:`METRICS_SCHEMA` — schema drift fails CI the way wire drift
  does, see ``tests/test_telemetry.py``);
- **per-transfer records** — :class:`TransferLog`, a bounded ring of
  (peer, seq ids, bytes, seconds) per transfer.  One log lives on each
  ``TransportManager`` (``transport.transfer_log``) so in-process
  multi-party tests/benches don't conflate parties;
  :func:`get_transfer_log` resolves the current runtime's log and
  keeps the module-global ring only as a documented runtime-less
  fallback;
- **span traces** — the federated flight recorder
  (:mod:`rayfed_tpu.telemetry`): structured cross-party span/event
  records, merged timelines (Perfetto export), and critical-path round
  reports (``tool/trace_report.py``).  :func:`trace_span` /
  :func:`start_profile` / :func:`stop_profile` remain the thin
  ``jax.profiler`` hooks for on-device (XLA) timelines — the flight
  recorder covers the cross-party protocol layer those never see.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Any, Dict, Optional

import jax

from rayfed_tpu.runtime import get_runtime_or_none

TransferRecord = collections.namedtuple(
    "TransferRecord", ["direction", "peer", "up_id", "down_id", "nbytes", "seconds"]
)


class TransferLog:
    """Bounded ring of per-transfer records (thread-safe)."""

    def __init__(self, capacity: int = 1024) -> None:
        self._records: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._total = 0  # monotonic count of all records ever appended

    def record(self, direction, peer, up_id, down_id, nbytes, seconds) -> None:
        with self._lock:
            self._records.append(
                TransferRecord(direction, peer, str(up_id), str(down_id),
                               int(nbytes), float(seconds))
            )
            self._total += 1

    def records(self):
        with self._lock:
            return list(self._records)

    @property
    def total_recorded(self) -> int:
        """Monotonic append count — unlike ``len(records())``, never
        capped by the ring, so windows can be delimited correctly."""
        with self._lock:
            return self._total

    def records_since(self, total_before: int):
        """(records appended after the ``total_recorded`` snapshot,
        complete_flag).  ``complete_flag`` is False when the ring evicted
        part of the window — callers must not present a partial window
        as a full decomposition."""
        with self._lock:
            delta = self._total - total_before
            recs = list(self._records)
        if delta <= 0:
            return [], True
        if delta > len(recs):
            return recs, False
        return recs[-delta:], True

    def throughput_gbps(self, direction: Optional[str] = None) -> float:
        recs = [
            r for r in self.records()
            if (direction is None or r.direction == direction) and r.seconds > 0
        ]
        if not recs:
            return 0.0
        return sum(r.nbytes for r in recs) / sum(r.seconds for r in recs) / 1e9


# Runtime-less fallback ONLY: every TransportManager owns its own
# TransferLog (``transport.transfer_log``), so in-process multi-party
# tests/benches record each party's transfers into its own ring.  This
# module-global ring is what :func:`get_transfer_log` returns when no
# runtime (or no transport) exists in the process — e.g. unit tests of
# the log itself.
_global_transfer_log = TransferLog()


def get_transfer_log() -> TransferLog:
    """The CURRENT runtime's per-manager transfer log, falling back to
    the documented module-global ring when no runtime/transport exists.

    In-process simulations holding several managers should read each
    manager's ``transfer_log`` attribute directly — this accessor is
    the one-party (one runtime per process) convenience."""
    runtime = get_runtime_or_none()
    transport = getattr(runtime, "transport", None)
    log = getattr(transport, "transfer_log", None)
    if log is not None:
        return log
    return _global_transfer_log


def get_stats() -> Dict[str, Any]:
    """Aggregate stats for the current party's runtime.

    Superset of the reference's proxy ``_get_stats``: send/receive op
    counts plus bytes, wall seconds, and effective send GB/s.
    """
    runtime = get_runtime_or_none()
    if runtime is None or getattr(runtime, "transport", None) is None:
        return {}
    stats = dict(runtime.transport.get_stats())
    secs = stats.get("send_seconds", 0.0)
    stats["send_gbps"] = (stats.get("send_bytes", 0) / secs / 1e9) if secs else 0.0
    return stats


# The documented shape of :func:`metrics_snapshot`: section → {key →
# type}.  A key listed here MUST exist in the section with that type —
# ``tests/test_telemetry.py::test_metrics_snapshot_schema`` asserts it,
# so renaming/retyping a counter fails CI the way wire-format drift
# does.  Sections may carry ADDITIONAL keys freely; only removals and
# retypes of the documented surface break the contract.
METRICS_SCHEMA: Dict[str, Dict[str, type]] = {
    "transport": {
        "send_op_count": int,
        "send_bytes": int,
        "send_seconds": float,
        "send_gbps": float,
        "pending_recvs": int,
        "send_path_breakdown_ms": dict,
        "delta_bytes_saved_frac": float,
        "send_dest_seconds": dict,
        "dead_parties": list,
    },
    "secagg": {
        "kex": str,
        "prg": str,
        "peers": dict,
    },
    "object_plane": {
        "blob_cache_hits": int,
        "blob_cache_misses": int,
        "blob_fetches": int,
        "blob_fetch_bytes": int,
        "blob_serves": int,
        "blob_cache_bytes": int,
        "blob_pinned_bytes": int,
    },
    "quorum": {
        "coordinator_failovers": int,
        "graceful_handovers": int,
    },
    "async": {
        # fl.async_rounds: buffered asynchronous rounds.  The
        # histogram maps decay shift (min(staleness, cap)) -> folds;
        # decay_shift_total is the summed shifts (how much weight the
        # fleet's staleness cost, in halvings).
        "versions_emitted": int,
        "folds": int,
        "buffer_occupancy": int,
        "staleness_hist": dict,
        "decay_shift_total": int,
        "dropped_decayed_out": int,
        "recoded_stale": int,
    },
    "telemetry": {
        "trace_armed": bool,
    },
}


def metrics_snapshot() -> Dict[str, Any]:
    """Every subsystem's counters under ONE documented schema
    (:data:`METRICS_SCHEMA`): ``transport`` (the :func:`get_stats`
    surface), ``secagg`` / ``object_plane`` / ``telemetry`` (hoisted
    from their get_stats sections), ``quorum``
    (``fl.quorum.QUORUM_STATS``) and ``async``
    (``fl.async_rounds.ASYNC_STATS``) — the last two live per process,
    not on the transport.  Returns ``{}`` before ``fed.init`` — a
    snapshot of nothing is not an error."""
    stats = get_stats()
    if not stats:
        return {}
    from rayfed_tpu.fl.async_rounds import ASYNC_STATS
    from rayfed_tpu.fl.quorum import QUORUM_STATS

    out: Dict[str, Any] = {
        "transport": {
            k: v for k, v in stats.items()
            if k not in ("secagg", "object_plane", "telemetry")
        },
        "secagg": dict(stats.get("secagg") or {}),
        "object_plane": dict(stats.get("object_plane") or {}),
        "telemetry": dict(stats.get("telemetry") or {}),
        "quorum": dict(QUORUM_STATS),
        # Deep-copy the histogram: a snapshot must not alias the live
        # counter dict the async driver keeps mutating.
        "async": {
            **ASYNC_STATS,
            "staleness_hist": dict(ASYNC_STATS["staleness_hist"]),
        },
    }
    return out


@contextlib.contextmanager
def trace_span(name: str, **kwargs):
    """Annotate a block on the jax profiler timeline (no-op cost when no
    trace is being captured)."""
    with jax.profiler.TraceAnnotation(name, **kwargs):
        yield


def start_profile(log_dir: str) -> None:
    """Begin a jax profiler capture (TensorBoard-viewable)."""
    jax.profiler.start_trace(log_dir)


def stop_profile() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def timed(out: Dict[str, float], key: str):
    """Accumulate wall time of a block into ``out[key]``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        out[key] = out.get(key, 0.0) + (time.perf_counter() - t0)
