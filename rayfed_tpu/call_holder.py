"""Submit-time brain: party-pinned call dispatch + dependency resolution.

Capability parity with reference ``fed/_private/fed_call_holder.py`` and
``fed/utils.py:26-61``:

- allocate one seq id per logical call on *every* party (determinism);
- same-party path: deep-substitute FedObject leaves with local refs
  (mine → its LocalRef; theirs → a ``recv`` future, cached), then submit
  the real task to the party executor;
- other-party path: push any locally-owned FedObject args to the task's
  party (exactly-once per (object, dest) pair), then return placeholder
  FedObject(s) without executing anything.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Optional

from rayfed_tpu import tree_util
from rayfed_tpu.fed_object import FedObject
from rayfed_tpu.runtime import Runtime

logger = logging.getLogger(__name__)


def resolve_dependencies(
    runtime: Runtime, current_fed_task_id: int, args: tuple, kwargs: dict
):
    """Swap FedObject leaves for local/received refs (ref ``utils.py:26-61``)."""
    from rayfed_tpu.proxy import recv_on_runtime

    current_party = runtime.party
    flattened_args, tree = tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, FedObject)
    )
    for idx, arg in enumerate(flattened_args):
        if not isinstance(arg, FedObject):
            continue
        if arg.get_party() == current_party:
            flattened_args[idx] = arg.get_local_ref()
        else:
            cached = arg.get_local_ref()
            if cached is not None:
                # Already received in this party; don't recv again
                # (reference utils.py:44-47).
                flattened_args[idx] = cached
            else:
                received = recv_on_runtime(
                    runtime,
                    src_party=arg.get_party(),
                    upstream_seq_id=arg.get_fed_task_id(),
                    curr_seq_id=current_fed_task_id,
                )
                arg._cache_local_ref(received)
                flattened_args[idx] = received
    resolved_args, resolved_kwargs = tree_util.tree_unflatten(flattened_args, tree)
    return resolved_args, resolved_kwargs


def push_arguments_to_party(
    runtime: Runtime, dest_party: str, downstream_seq_id: int, args: tuple, kwargs: dict
) -> None:
    """Owner-initiated push of locally-owned args consumed by ``dest_party``.

    The demander never pulls — the data owner holds transmission authority
    (reference ``fed_call_holder.py:75-91``, README "push-based").
    """
    from rayfed_tpu.proxy import send_on_runtime

    flattened_args, _ = tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, FedObject)
    )
    for arg in flattened_args:
        if isinstance(arg, FedObject) and arg.get_party() == runtime.party:
            # Atomic test-and-set: exactly-once per (object, dest).
            if arg._mark_if_not_sending_to_party(dest_party):
                send_on_runtime(
                    runtime,
                    dest_party=dest_party,
                    data=arg.get_local_ref(),
                    upstream_seq_id=arg.get_fed_task_id(),
                    downstream_seq_id=downstream_seq_id,
                )


class FedCallHolder:
    """Holder for one party-pinned call site: ``f.party("alice")``.

    ``submit_task_fn(resolved_args, resolved_kwargs)`` executes the real
    work on the local executor and returns LocalRef(s) — it plays the role
    of the reference's ``submit_ray_task_func`` (``api.py:294-297``).
    """

    def __init__(
        self,
        runtime: Runtime,
        node_party: str,
        submit_task_fn: Callable[[tuple, dict], Any],
        options: Optional[dict] = None,
    ) -> None:
        self._runtime = runtime
        self._party = runtime.party
        self._node_party = node_party
        self._options = dict(options or {})
        self._submit_task_fn = submit_task_fn

    def options(self, **options):
        self._options = options
        return self

    def internal_remote(self, *args, **kwargs):
        runtime = self._runtime
        fed_task_id = runtime.next_seq_id()
        if runtime.sequence_tracer is not None:
            runtime.sequence_tracer.record_call(fed_task_id, self._node_party)
        if self._party == self._node_party:
            resolved_args, resolved_kwargs = resolve_dependencies(
                runtime, fed_task_id, args, kwargs
            )
            refs = self._submit_task_fn(resolved_args, resolved_kwargs)
            if isinstance(refs, list):
                return [
                    FedObject(self._node_party, fed_task_id, ref, i)
                    for i, ref in enumerate(refs)
                ]
            return FedObject(self._node_party, fed_task_id, refs)
        else:
            push_arguments_to_party(
                runtime, self._node_party, fed_task_id, args, kwargs
            )
            num_returns = self._options.get("num_returns", 1)
            if num_returns > 1:
                return [
                    FedObject(self._node_party, fed_task_id, None, i)
                    for i in range(num_returns)
                ]
            return FedObject(self._node_party, fed_task_id, None)
