"""Deterministic chaos injection for the transport and round drivers.

Every fault story this framework claims to survive — stragglers past the
round deadline, a party crashing mid-round, a dropped or corrupted frame,
a dead rail, a leader dying under a multi-host party — must be
*exercisable on demand* or the claim rots.  This module is the single
switchboard: a **seeded fault schedule** installed per process (or per
in-process simulated party) fires at **named hook points** threaded
through the transport client/server/manager and the federated round
driver.  With no schedule installed every hook is one ``is None`` check —
production pays nothing.

Activation:

- ``RAYFED_CHAOS`` environment variable holding the JSON schedule —
  picked up by :func:`maybe_install_from_env` (called from ``fed.init``);
- or :func:`install` directly from tests/benches (supports multiple
  in-process simulated parties because every rule carries a ``party``
  filter and every hook site reports the acting party).

Schedule format::

    {
      "seed": 0,
      "rules": [
        {"hook": "round", "party": "carol", "match": {"round": 1},
         "op": "delay_ms", "value": 4000},
        {"hook": "round", "party": "dave", "match": {"round": 1},
         "op": "crash_party"},
        {"hook": "frame", "party": "alice", "match": {"dest": "bob"},
         "count": 1, "op": "corrupt_crc"}
      ]
    }

Rule fields:

- ``hook``: one of the :data:`HOOKS` catalog below.
- ``party``: only fire in the party named (omit = any).  In-process
  multi-party simulations pass the acting party at every hook site, so
  one process-global schedule drives all simulated parties.
- ``match``: exact-match filters against the hook's context fields
  (``round``, ``dest``, ``src``, ``up`` ...); ``stream`` matches by
  ``fnmatch`` glob.  Omitted fields match anything.
- ``after``: skip the first N matching events (default 0).
- ``count``: fire at most N times (default 1; ``null`` = unbounded).
- ``op`` + ``value``: the fault (see below).

Ops:

- ``delay_ms`` — sleep ``value`` ms (or draw uniformly from a two-element
  ``[lo, hi]`` with the schedule's seeded rng: deterministic per rule).
  At async hook sites the sleep is awaited, so only the injected path
  stalls, not the whole event loop.
- ``drop_frame`` — raise :class:`ChaosFault` (a ``ConnectionError``
  subclass, so client retry arms treat it exactly like a lost wire).
- ``corrupt_crc`` — flip the low bit of the frame's declared checksum
  (``ctx["header"]``: ``crc``/``ccrc``) so the receiver's verification
  fails and the sender's retry path runs.  The payload bytes are never
  touched — injected corruption must not poison a reused send arena.
- ``kill_rail`` — raise ``ConnectionResetError`` (connection-open and
  per-frame sites: one rail dies, the payload-as-a-unit retry runs).
- ``crash_party`` — raise :class:`ChaosPartyCrash`.  Only meaningful at
  driver-level hooks (``round``, ``announce``): the test/bench harness
  turns it into a hard process exit (or, in-process, an abrupt
  transport stop) so peers see sockets die, not a graceful goodbye.
- ``local_slowdown`` — a per-party COMPUTE-delay **multiplier** at the
  ``local_step`` hook: the hook site reports how long the party's local
  step actually took (``baseline_s``), and the rule stretches it to
  ``value`` times that (sleeping ``baseline_s * (value - 1)``).
  ``value`` is the multiplier (or a two-element ``[lo, hi]`` drawn
  uniformly from the rule's seeded rng — deterministic per rule, so a
  "2-10x straggler spread" schedule replays identically).  Unlike
  ``delay_ms`` (an absolute stall), a multiplier scales with the real
  compute, which is what heterogeneous-device fleets look like — the
  async round gate and the quorum/hierarchy straggler tests share one
  schedule format.  Persists by default (``count`` unbounded): a slow
  device stays slow.
- ``partition`` — bidirectional frame drop between the two parties
  named by ``value: [a, b]``.  Fires at the ``wire`` hook (every
  client-side frame incl. health pings and handshakes, and every
  server-side received frame), so to BOTH endpoints the partner looks
  exactly dead — pings time out, sends fail, arriving frames are
  discarded without a reply — while both processes stay alive.  Unlike
  the other ops a partition persists (``count`` defaults to
  unbounded); scope it with ``after``/``count`` to heal it.

Hook catalog (:data:`HOOKS`) — ``hook name: (site, context fields)``:

- ``connect`` — ``TransportClient._open_conn`` before dialing
  (``dest``): ``delay_ms``, ``kill_rail``.
- ``send`` — ``TransportClient.send_data`` entry (``dest``, ``stream``,
  ``up``, ``down``): ``delay_ms``, ``drop_frame``.
- ``frame`` — ``TransportClient._roundtrip`` before a DATA frame's bytes
  hit the socket (``dest``, ``header`` mutable): ``delay_ms``,
  ``drop_frame``, ``corrupt_crc``, ``kill_rail``.
- ``wire`` — EVERY client-side frame (``TransportClient._roundtrip``
  entry: data, pings, handshakes; ``dest``, ``type``) and every
  server-side received frame (``src``, ``type``): ``partition``,
  ``drop_frame``, ``delay_ms`` (client side only — the receive side is
  a sync event-loop callback, so a matched delay there is logged and
  SKIPPED rather than stalling every peer's frames).  The
  asymmetric-connectivity hook — a rule here starves the health
  monitor's pings too, which ``frame`` (data frames only) cannot.
- ``server_frame`` — ``TransportServer`` dispatch of a received DATA
  frame (``src``, ``up``, ``down``): ``drop_frame`` (frame discarded
  without an ACK — the sender times out and retries).
- ``round`` — the federated round driver at each round boundary
  (``round``): ``delay_ms`` (a straggler), ``crash_party``.
- ``announce`` — the quorum coordinator between the round cutoff and
  its result/announce broadcast (``round``, ``epoch``): ``delay_ms``,
  ``crash_party``.  The nastiest failover window: the round is decided
  but nobody has heard — killing the coordinator HERE forces the
  successor to re-establish the round from re-pushed contributions.
- ``republish`` — the multi-host leader's bridge republish
  (``pid``, ``up``, ``down``): ``drop_frame``, ``delay_ms``.
- ``local_step`` — a party's local-compute step boundary (the async
  round loop's virtual parties, reusable by any driver that measures
  its own compute): context carries ``round`` (or ``version``) and
  ``baseline_s`` — the measured duration of the step just taken.
  ``local_slowdown`` (multiplier), ``delay_ms``, ``crash_party``.
"""

from __future__ import annotations

import fnmatch
import json
import logging
import random
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

ENV_VAR = "RAYFED_CHAOS"

HOOKS = (
    "connect", "send", "frame", "wire", "server_frame", "round",
    "announce", "republish",
    # Secure aggregation (fl.secagg): fires on the quorum coordinator
    # between the cutoff pinning the member set and the mask-recovery
    # announcement — killing it there leaves the survivors parked on
    # the recovery round trip with no poison coming, the nastiest
    # secure-round window (only failover can finish the round, and the
    # successor must re-run recovery on its own stream).
    "secagg_recovery",
    # A party's local-compute step boundary (async virtual parties and
    # any driver that measures its own compute) — the hook that makes
    # deterministic heterogeneous-speed fleets (2-10x straggler spread)
    # first-class via the local_slowdown multiplier op.
    "local_step",
)

_OPS = (
    "delay_ms", "drop_frame", "corrupt_crc", "kill_rail", "crash_party",
    "partition", "local_slowdown",
)


class ChaosFault(ConnectionError):
    """An injected transport fault (retryable, like a lost wire)."""


class ChaosPartyCrash(BaseException):
    """An injected party crash.

    Subclasses ``BaseException`` so no retry ladder or broad
    ``except Exception`` swallows it — a crash must unwind the whole
    driver, the way a real SIGKILL would.  Raised only from driver-level
    hooks (``round``); the harness decides how hard to die
    (``os._exit`` in subprocess harnesses, an abrupt transport stop
    in-process).
    """


class _Rule:
    __slots__ = (
        "hook", "party", "match", "after", "count", "op", "value",
        "fired", "seen", "rng",
    )

    def __init__(self, spec: Dict[str, Any], index: int, seed: int) -> None:
        self.hook = spec["hook"]
        if self.hook not in HOOKS:
            raise ValueError(
                f"unknown chaos hook {self.hook!r}; known: {HOOKS}"
            )
        self.op = spec["op"]
        if self.op not in _OPS:
            raise ValueError(
                f"unknown chaos op {self.op!r}; known: {_OPS}"
            )
        self.party = spec.get("party")
        self.match = dict(spec.get("match") or {})
        self.after = int(spec.get("after", 0))
        # A partition is a standing condition, not an event — it stays
        # up until explicitly bounded (count) or uninstalled.  So is a
        # local_slowdown: a slow device stays slow.
        count = spec.get(
            "count",
            None if self.op in ("partition", "local_slowdown") else 1,
        )
        self.count = None if count is None else int(count)
        self.value = spec.get("value")
        if self.op == "partition":
            if (
                not isinstance(self.value, (list, tuple))
                or len(self.value) != 2
                or len(set(map(str, self.value))) != 2
            ):
                raise ValueError(
                    "partition op needs value=[party_a, party_b] naming "
                    f"two distinct parties, got {self.value!r}"
                )
            self.value = [str(p) for p in self.value]
        if self.op == "local_slowdown":
            v = self.value
            ok = (
                isinstance(v, (int, float)) and float(v) >= 1.0
            ) or (
                isinstance(v, (list, tuple)) and len(v) == 2
                and all(isinstance(x, (int, float)) for x in v)
                and 1.0 <= float(v[0]) <= float(v[1])
            )
            if not ok:
                raise ValueError(
                    "local_slowdown op needs value=<multiplier >= 1> or "
                    f"value=[lo, hi] with 1 <= lo <= hi, got {v!r}"
                )
        self.seen = 0
        self.fired = 0
        # Rule-local deterministic rng (e.g. delay drawn from [lo, hi]):
        # independent of firing order across rules.
        self.rng = random.Random((int(seed) << 8) ^ index)

    def matches(self, party: Optional[str], ctx: Dict[str, Any]) -> bool:
        if self.party is not None and party != self.party:
            return False
        if self.op == "partition":
            # Bidirectional: the event is on the cut link iff the acting
            # party and its wire partner (dest on the client side, src on
            # the server side) are exactly the named pair.
            partner = ctx.get("dest", ctx.get("src"))
            if partner is None or {party, partner} != set(self.value):
                return False
        for key, want in self.match.items():
            got = ctx.get(key)
            if key == "stream":
                if not isinstance(got, str) or not fnmatch.fnmatch(
                    got, str(want)
                ):
                    return False
            elif got != want:
                return False
        return True

    def delay_s(self) -> float:
        v = self.value
        if isinstance(v, (list, tuple)) and len(v) == 2:
            v = self.rng.uniform(float(v[0]), float(v[1]))
        return float(v or 0) / 1e3

    def slowdown(self) -> float:
        """The compute-delay multiplier (seeded draw for [lo, hi])."""
        v = self.value
        if isinstance(v, (list, tuple)) and len(v) == 2:
            v = self.rng.uniform(float(v[0]), float(v[1]))
        return max(1.0, float(v))


class ChaosSchedule:
    """A parsed, counter-tracking fault schedule (thread-safe)."""

    def __init__(self, spec: Dict[str, Any]) -> None:
        seed = int(spec.get("seed", 0))
        self.seed = seed
        self.rules: List[_Rule] = [
            _Rule(r, i, seed) for i, r in enumerate(spec.get("rules", []))
        ]
        self._lock = threading.Lock()

    def pick(self, hook: str, party: Optional[str], ctx: Dict[str, Any]):
        """The first armed rule matching this event, advancing counters."""
        with self._lock:
            for rule in self.rules:
                if rule.hook != hook or not rule.matches(party, ctx):
                    continue
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if rule.count is not None and rule.fired >= rule.count:
                    continue
                rule.fired += 1
                return rule
        return None


_ACTIVE: Optional[ChaosSchedule] = None


def install(spec: Any) -> ChaosSchedule:
    """Install a schedule process-wide (dict or JSON string)."""
    global _ACTIVE
    if isinstance(spec, str):
        spec = json.loads(spec)
    sched = spec if isinstance(spec, ChaosSchedule) else ChaosSchedule(spec)
    _ACTIVE = sched
    logger.warning(
        "CHAOS schedule installed (%d rules, seed %d) — fault injection "
        "is ACTIVE in this process", len(sched.rules), sched.seed,
    )
    return sched


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def installed() -> Optional[ChaosSchedule]:
    return _ACTIVE


def maybe_install_from_env() -> Optional[ChaosSchedule]:
    """Install from ``RAYFED_CHAOS`` if set (idempotent; ``fed.init``
    calls this so subprocess harnesses configure chaos via env)."""
    import os

    if _ACTIVE is not None:
        return _ACTIVE
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return None
    return install(raw)


def _apply(rule: _Rule, hook: str, party: Optional[str],
           ctx: Dict[str, Any]) -> Optional[float]:
    """Apply a rule's non-sleep effect; returns seconds to sleep (the
    caller sleeps — sync sites block the thread, async sites await)."""
    label = f"chaos[{hook}:{rule.op}]"
    # Flight recorder: every FIRED fault lands on the same timeline as
    # the failover/cutoff it causes (rayfed_tpu/telemetry.py) — an
    # injected partition appears NEXT to the death declaration it
    # triggered.  Cost: this runs only when a rule actually fires, and
    # the emit is a nonblocking ring append (standing partitions fire
    # per frame; their event is ring-bounded like any other record).
    from rayfed_tpu import telemetry as _telemetry

    _rec = _telemetry.active()
    if _rec is not None:
        _rec.emit(
            f"chaos.{rule.op}", party=party,
            t_start=time.time(),
            round=ctx.get("round"), epoch=ctx.get("epoch"),
            peer=ctx.get("dest", ctx.get("src")),
            stream=ctx.get("stream"),
            outcome="injected",
            detail={"hook": hook, **_ctx_brief(ctx)},
        )
    if rule.op == "delay_ms":
        delay = rule.delay_s()
        logger.warning("%s party=%s delaying %.0f ms (ctx=%s)",
                       label, party, delay * 1e3, _ctx_brief(ctx))
        return delay
    if rule.op == "local_slowdown":
        # Multiplier semantics: the hook site reports how long the local
        # step ACTUALLY took (baseline_s); stretching it to m x means
        # sleeping the remaining (m - 1) share.  A site that passes no
        # baseline gets no stall (logged) — absolute stalls are what
        # delay_ms is for.
        mult = rule.slowdown()
        base = float(ctx.get("baseline_s") or 0.0)
        stall = max(0.0, base * (mult - 1.0))
        if rule.fired <= 3 or base <= 0.0:
            logger.warning(
                "%s party=%s x%.2f over baseline %.3fs -> stalling "
                "%.3fs (ctx=%s)", label, party, mult, base, stall,
                _ctx_brief(ctx),
            )
        return stall
    if rule.op == "partition":
        # A standing partition fires on every frame — log its onset, not
        # a warning per dropped ping.
        if rule.fired == 1:
            logger.warning("%s party=%s up (ctx=%s)", label, party,
                           _ctx_brief(ctx))
        raise ChaosFault(
            f"{label}: link between {rule.value[0]!r} and "
            f"{rule.value[1]!r} is partitioned"
        )
    logger.warning("%s party=%s firing (ctx=%s)", label, party,
                   _ctx_brief(ctx))
    if rule.op == "drop_frame":
        raise ChaosFault(f"{label}: injected frame drop")
    if rule.op == "kill_rail":
        raise ConnectionResetError(f"{label}: injected rail death")
    if rule.op == "crash_party":
        raise ChaosPartyCrash(f"{label}: injected crash of {party!r}")
    if rule.op == "corrupt_crc":
        header = ctx.get("header")
        if isinstance(header, dict):
            if isinstance(header.get("ccrc"), list) and header["ccrc"]:
                header["ccrc"] = [header["ccrc"][0] ^ 1] + header["ccrc"][1:]
            elif "crc" in header:
                header["crc"] = int(header["crc"]) ^ 1
            else:
                # No checksum on this frame — declare a wrong one so the
                # receiver still exercises its mismatch path.
                header["crc"] = 1
    return None


def _ctx_brief(ctx: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in ctx.items() if k != "header"}


def fire(hook: str, party: Optional[str] = None, **ctx: Any) -> None:
    """Synchronous hook point.  No-op (one attribute read) without an
    installed schedule.  May sleep the calling thread, mutate
    ``ctx["header"]``, or raise the injected fault."""
    sched = _ACTIVE
    if sched is None:
        return
    rule = sched.pick(hook, party, ctx)
    if rule is None:
        return
    delay = _apply(rule, hook, party, ctx)
    if delay:
        # fedlint: disable=FED001 — sleeping is this hook's PURPOSE (injected stall on the calling worker thread); every event-loop call site uses fire_async (awaited) or fire_nonblocking (delay skipped), the split FED001 itself polices
        time.sleep(delay)


def fire_nonblocking(hook: str, party: Optional[str] = None,
                     **ctx: Any) -> None:
    """:func:`fire` for SYNCHRONOUS event-loop callbacks that must never
    sleep (the server's frame dispatch): drop/partition faults raise as
    usual, but a matched ``delay_ms`` is counted, logged and SKIPPED —
    sleeping there would stall every peer sharing the loop, injecting
    cascading faults the schedule never specified."""
    sched = _ACTIVE
    if sched is None:
        return
    rule = sched.pick(hook, party, ctx)
    if rule is None:
        return
    delay = _apply(rule, hook, party, ctx)
    if delay:
        logger.warning(
            "chaos[%s:delay_ms] party=%s matched a non-blocking hook "
            "site — the delay is SKIPPED (this site runs on the "
            "receiver's event loop; inject delays on the sender side "
            "instead)", hook, party,
        )


async def fire_async(hook: str, party: Optional[str] = None,
                     **ctx: Any) -> None:
    """Awaitable twin of :func:`fire` for event-loop hook sites — an
    injected delay parks only this coroutine, never the loop."""
    sched = _ACTIVE
    if sched is None:
        return
    rule = sched.pick(hook, party, ctx)
    if rule is None:
        return
    delay = _apply(rule, hook, party, ctx)
    if delay:
        import asyncio

        await asyncio.sleep(delay)
