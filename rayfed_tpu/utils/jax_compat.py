"""Version-portability shims for jax APIs that moved between releases.

The compute layer targets current jax (``jax.shard_map``,
``jax.sharding.set_mesh``, ``check_vma``), but deployment containers pin
older jaxlib builds where those names live under ``jax.experimental`` or
don't exist.  These wrappers keep ONE call-site spelling and translate:

- :func:`shard_map` — ``jax.shard_map`` when present, else
  ``jax.experimental.shard_map.shard_map`` with ``check_vma`` mapped to
  its older ``check_rep`` spelling.
- :func:`set_mesh` — ``jax.sharding.set_mesh`` when present, else the
  classic ``with mesh:`` context (the implicit-mesh mechanism those
  releases used).
"""

from __future__ import annotations

import contextlib

import jax

_HAS_NEW_SHARD_MAP = hasattr(jax, "shard_map")
if not _HAS_NEW_SHARD_MAP:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _legacy_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """``jax.shard_map`` across jax versions (``check_vma``⇄``check_rep``)."""
    if _HAS_NEW_SHARD_MAP:
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    if check_vma is not None:
        kw["check_rep"] = check_vma
    return _legacy_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


@contextlib.contextmanager
def set_mesh(mesh):
    """``jax.sharding.set_mesh`` across jax versions."""
    if hasattr(jax.sharding, "set_mesh"):
        with jax.sharding.set_mesh(mesh):
            yield mesh
    else:  # pragma: no cover - version-dependent
        with mesh:
            yield mesh
