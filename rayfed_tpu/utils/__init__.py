from rayfed_tpu.utils.validation import validate_address, validate_cluster_info
from rayfed_tpu.utils.logging_utils import setup_logger

__all__ = ["validate_address", "validate_cluster_info", "setup_logger"]
