from rayfed_tpu.utils.validation import validate_address, validate_cluster_info
from rayfed_tpu.utils.logging_utils import setup_logger
from rayfed_tpu.utils.platform import force_cpu_devices

__all__ = [
    "validate_address",
    "validate_cluster_info",
    "setup_logger",
    "force_cpu_devices",
]
