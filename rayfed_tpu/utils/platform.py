"""Platform pinning helpers.

The test/bench environment may register a real-accelerator PJRT plugin
from ``sitecustomize`` and pin ``jax_platforms`` via ``jax.config`` at
interpreter start — plain env vars don't win by then, so any process
that wants a virtual CPU mesh must override through ``jax.config``
*before* the first backend initialization.  This is the single home for
that workaround (used by ``tests/multiproc.py``, ``bench.py`` party
children, and the ``__graft_entry__`` dry-run re-exec).
"""

from __future__ import annotations

import os


def force_cpu_devices(n: int = 8) -> None:
    """Pin JAX to the CPU platform with ``n`` virtual devices.

    Must run before any JAX backend initialization (e.g. first
    ``jax.devices()`` / jit execution) in the calling process.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)
