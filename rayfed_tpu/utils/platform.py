"""Platform pinning helpers.

The test/bench environment may register a real-accelerator PJRT plugin
from ``sitecustomize`` and pin ``jax_platforms`` via ``jax.config`` at
interpreter start — plain env vars don't win by then, so any process
that wants a virtual CPU mesh must override through ``jax.config``
*before* the first backend initialization.  This is the single home for
that workaround (used by ``tests/multiproc.py``, ``bench.py`` party
children, and the ``__graft_entry__`` dry-run re-exec).
"""

from __future__ import annotations

import os


def force_cpu_devices(n: int = 8) -> None:
    """Pin JAX to the CPU platform with ``n`` virtual devices.

    Must run before any JAX backend initialization (e.g. first
    ``jax.devices()`` / jit execution) in the calling process.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    # REPLACE any inherited device-count flag rather than keeping it: a
    # child asking for 4 devices must not silently run with the parent's
    # 8 (on older jax this flag is the only mechanism — see below).
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # Older jax: the option doesn't exist — the XLA_FLAGS override
        # above (set before the first backend init) provides the mesh.
        pass
