"""Party-tagged logging (parity: reference ``fed/utils.py:77-111``,
``fed/_private/constants.py:34-36``)."""

from __future__ import annotations

import logging
import threading
from typing import Optional

RAYFED_LOG_FORMAT = (
    "%(asctime)s %(levelname)s %(filename)s:%(lineno)s"
    " [%(party)s] -- %(message)s"
)

_tls = threading.local()


def set_thread_party(party: Optional[str]) -> None:
    _tls.party = party


class PartyRecordFilter(logging.Filter):
    """Injects the current party into every record.

    The reference pins one party per process; we additionally consult a
    thread-local so multi-party-in-one-process simulation logs correctly.
    """

    def __init__(self, party: Optional[str] = None) -> None:
        super().__init__()
        self._party = party

    def filter(self, record: logging.LogRecord) -> bool:
        if not hasattr(record, "party"):
            record.party = getattr(_tls, "party", None) or self._party or "-"
        return True


def setup_logger(
    logging_level: str = "info",
    logging_format: str = RAYFED_LOG_FORMAT,
    date_format: Optional[str] = None,
    party: Optional[str] = None,
) -> None:
    root = logging.getLogger()
    root.setLevel(getattr(logging, str(logging_level).upper(), logging.INFO))
    formatter = logging.Formatter(logging_format, datefmt=date_format)
    filt = PartyRecordFilter(party)
    has_handler = False
    for handler in root.handlers:
        if getattr(handler, "_rayfed_handler", False):
            has_handler = True
            handler.setFormatter(formatter)
    if not has_handler:
        handler = logging.StreamHandler()
        handler._rayfed_handler = True  # type: ignore[attr-defined]
        handler.setFormatter(formatter)
        handler.addFilter(filt)
        root.addHandler(handler)
    else:
        for handler in root.handlers:
            if getattr(handler, "_rayfed_handler", False):
                for f in list(handler.filters):
                    handler.removeFilter(f)
                handler.addFilter(filt)
