"""Address / cluster validation (parity: reference ``fed/utils.py:162-198``)."""

from __future__ import annotations

from typing import Dict, Optional


def validate_address(address: Optional[str]) -> None:
    """Accepts None, 'local', or 'host:port'-shaped strings.

    The reference forwards this to ``ray.init``; here 'local' (or None)
    simply means in-process execution — there is no external cluster to
    join, the party controller *is* the runtime.
    """
    if address is None or address == "local":
        return
    if not isinstance(address, str):
        raise ValueError(f"address must be a string, got {type(address).__name__}")
    if address.count(":") < 1:
        raise ValueError(
            f"Invalid address {address!r}: expected 'local' or '<host>:<port>'."
        )


def _validate_party_addr(party: str, addr: str) -> None:
    if not isinstance(addr, str) or ":" not in addr:
        raise ValueError(
            f"Invalid address {addr!r} for party {party!r}: "
            "expected '<host>:<port>'."
        )
    host, _, port = addr.rpartition(":")
    if not host:
        raise ValueError(f"Invalid address {addr!r} for party {party!r}: no host.")
    try:
        p = int(port)
    except ValueError:
        raise ValueError(
            f"Invalid address {addr!r} for party {party!r}: port must be an int."
        ) from None
    if not (0 < p < 65536):
        raise ValueError(
            f"Invalid address {addr!r} for party {party!r}: port out of range."
        )


def validate_cluster_info(cluster: Dict) -> None:
    if not isinstance(cluster, dict) or not cluster:
        raise ValueError("cluster must be a non-empty dict of party -> config")
    for party, cfg in cluster.items():
        if not isinstance(cfg, dict) or "address" not in cfg:
            raise ValueError(
                f"cluster entry for party {party!r} must be a dict with 'address'"
            )
        _validate_party_addr(party, cfg["address"])
        if cfg.get("listen_addr"):
            _validate_party_addr(party, cfg["listen_addr"])
