// Native data plane for the rayfed_tpu wire transport.
//
// The reference gets its native transport from third-party wheels (gRPC
// C-core + Ray's C++ core, SURVEY §2.9); this framework's equivalent is
// first-party: the byte-level hot path of the DCN push transport lives
// here — checksums, frame assembly, and large scatter-gather copies —
// callable from Python via ctypes with the GIL released, so the asyncio
// loop and codec threads never serialize on big memcpys.
//
// Build: g++ -O3 -march=native -shared -fPIC wirecodec.cc -o libwirecodec.so
// (see build.py; pure-Python fallbacks exist for every entry point).

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32-C (Castagnoli), slicing-by-8.  Table generated at first use.
// ---------------------------------------------------------------------------

static uint32_t crc_table[8][256];

static bool crc_init() {
  const uint32_t poly = 0x82f63b78u;  // reflected CRC32-C polynomial
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    crc_table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = crc_table[0][i];
    for (int s = 1; s < 8; s++) {
      crc = (crc >> 8) ^ crc_table[0][crc & 0xff];
      crc_table[s][i] = crc;
    }
  }
  return true;
}

// Built at dlopen — see shift_init_done for why not lazily.
static const bool crc_init_done = crc_init();

#if defined(__SSE4_2__)
#include <nmmintrin.h>

// --- GF(2) crc-shift operator: advance a raw CRC register over N zero
// bytes, used to combine independent streams (zlib crc32_combine
// technique).  op is a 32x32 bit-matrix as 32 column words.
static inline uint32_t gf2_times(const uint32_t* mat, uint32_t vec) {
  uint32_t sum = 0;
  while (vec) {
    if (vec & 1) sum ^= *mat;
    vec >>= 1;
    mat++;
  }
  return sum;
}

static void gf2_square(uint32_t* dst, const uint32_t* mat) {
  for (int i = 0; i < 32; i++) dst[i] = gf2_times(mat, mat[i]);
}

// Build the operator matrix for shifting a (reflected) CRC32-C register
// by len_bytes of zeros: matrix exponentiation by squaring of the
// one-zero-bit operator.
static void crc_shift_op(uint32_t* out, uint64_t len_bytes) {
  uint32_t op[32], sq[32], t[32];
  op[0] = 0x82f63b78u;  // reflected polynomial: effect of one zero bit
  uint32_t row = 1;
  for (int i = 1; i < 32; i++) {
    op[i] = row;
    row <<= 1;
  }
  for (int i = 0; i < 32; i++) out[i] = 1u << i;  // identity
  uint64_t n = len_bytes * 8;                     // zero BITS to shift by
  while (n) {
    if (n & 1) {
      for (int i = 0; i < 32; i++) t[i] = gf2_times(op, out[i]);
      std::memcpy(out, t, sizeof t);
    }
    n >>= 1;
    if (!n) break;
    gf2_square(sq, op);
    std::memcpy(op, sq, sizeof sq);
  }
}

// 6-way interleaved kernel: this host's crc32q sustains ~5 GB/s on one
// chain (3-cycle latency) but ~14 GB/s with 6 independent streams.
// Streams are combined with precomputed shift operators, applied via
// 4x256 byte-lookup tables (built once).
static const uint64_t kLane = 8192;  // bytes per lane
static const int kNL = 6;            // lanes
static uint32_t shift_tab[kNL - 1][4][256];  // [s]: shift by (s+1)*kLane

static bool shift_init() {
  uint32_t mat[32];
  for (int s = 0; s < kNL - 1; s++) {
    crc_shift_op(mat, (uint64_t)(s + 1) * kLane);
    for (int b = 0; b < 4; b++)
      for (int v = 0; v < 256; v++)
        shift_tab[s][b][v] = gf2_times(mat, (uint32_t)v << (8 * b));
  }
  return true;
}

// Built at dlopen (single-threaded): rf_crc32c runs with the GIL
// released from many executor threads, and a lazy flag-guarded init
// would be an unsynchronized data race.
static const bool shift_init_done = shift_init();

static inline uint32_t shift_apply(const uint32_t tab[4][256], uint32_t crc) {
  return tab[0][crc & 0xff] ^ tab[1][(crc >> 8) & 0xff] ^
         tab[2][(crc >> 16) & 0xff] ^ tab[3][(crc >> 24) & 0xff];
}

uint32_t rf_crc32c(uint32_t seed, const uint8_t* data, uint64_t len) {
  uint32_t crc = ~seed;
  while (len && (reinterpret_cast<uintptr_t>(data) & 7)) {
    crc = _mm_crc32_u8(crc, *data++);
    len--;
  }
  if (len >= kNL * kLane) {
    (void)shift_init_done;
    while (len >= kNL * kLane) {
      const uint64_t* p0 = reinterpret_cast<const uint64_t*>(data);
      const uint64_t* p1 = reinterpret_cast<const uint64_t*>(data + kLane);
      const uint64_t* p2 = reinterpret_cast<const uint64_t*>(data + 2 * kLane);
      const uint64_t* p3 = reinterpret_cast<const uint64_t*>(data + 3 * kLane);
      const uint64_t* p4 = reinterpret_cast<const uint64_t*>(data + 4 * kLane);
      const uint64_t* p5 = reinterpret_cast<const uint64_t*>(data + 5 * kLane);
      uint64_t c0 = crc, c1 = 0, c2 = 0, c3 = 0, c4 = 0, c5 = 0;
      for (uint64_t i = 0; i < kLane / 8; i++) {
        c0 = _mm_crc32_u64(c0, p0[i]);
        c1 = _mm_crc32_u64(c1, p1[i]);
        c2 = _mm_crc32_u64(c2, p2[i]);
        c3 = _mm_crc32_u64(c3, p3[i]);
        c4 = _mm_crc32_u64(c4, p4[i]);
        c5 = _mm_crc32_u64(c5, p5[i]);
      }
      crc = shift_apply(shift_tab[4], (uint32_t)c0) ^
            shift_apply(shift_tab[3], (uint32_t)c1) ^
            shift_apply(shift_tab[2], (uint32_t)c2) ^
            shift_apply(shift_tab[1], (uint32_t)c3) ^
            shift_apply(shift_tab[0], (uint32_t)c4) ^ (uint32_t)c5;
      data += kNL * kLane;
      len -= kNL * kLane;
    }
  }
  uint64_t crc64 = crc;
  while (len >= 8) {
    uint64_t chunk;
    std::memcpy(&chunk, data, 8);
    crc64 = _mm_crc32_u64(crc64, chunk);
    data += 8;
    len -= 8;
  }
  crc = static_cast<uint32_t>(crc64);
  while (len--) crc = _mm_crc32_u8(crc, *data++);
  return ~crc;
}
#else
uint32_t rf_crc32c(uint32_t seed, const uint8_t* data, uint64_t len) {
  (void)crc_init_done;
  uint32_t crc = ~seed;
  // Align to 8 bytes.
  while (len && (reinterpret_cast<uintptr_t>(data) & 7)) {
    crc = (crc >> 8) ^ crc_table[0][(crc ^ *data++) & 0xff];
    len--;
  }
  while (len >= 8) {
    uint64_t chunk;
    std::memcpy(&chunk, data, 8);
    chunk ^= crc;  // little-endian assumption (x86-64 / aarch64)
    crc = crc_table[7][chunk & 0xff] ^ crc_table[6][(chunk >> 8) & 0xff] ^
          crc_table[5][(chunk >> 16) & 0xff] ^
          crc_table[4][(chunk >> 24) & 0xff] ^
          crc_table[3][(chunk >> 32) & 0xff] ^
          crc_table[2][(chunk >> 40) & 0xff] ^
          crc_table[1][(chunk >> 48) & 0xff] ^
          crc_table[0][(chunk >> 56) & 0xff];
    data += 8;
    len -= 8;
  }
  while (len--) crc = (crc >> 8) ^ crc_table[0][(crc ^ *data++) & 0xff];
  return ~crc;
}
#endif  // __SSE4_2__

// ---------------------------------------------------------------------------
// Scatter-gather copy: assemble N source buffers into one destination.
// Returns total bytes copied.  Called with the GIL released.
// ---------------------------------------------------------------------------

uint64_t rf_gather_copy(uint8_t* dst, const uint8_t** srcs,
                        const uint64_t* lens, uint64_t n) {
  uint64_t off = 0;
  for (uint64_t i = 0; i < n; i++) {
    std::memcpy(dst + off, srcs[i], lens[i]);
    off += lens[i];
  }
  return off;
}

// Gather + checksum in one pass over the sources (saves a full re-read of
// the assembled buffer when both are needed).
uint64_t rf_gather_copy_crc(uint8_t* dst, const uint8_t** srcs,
                            const uint64_t* lens, uint64_t n,
                            uint32_t* crc_out) {
  uint64_t off = 0;
  uint32_t crc = 0;
  for (uint64_t i = 0; i < n; i++) {
    std::memcpy(dst + off, srcs[i], lens[i]);
    crc = rf_crc32c(crc, srcs[i], lens[i]);
    off += lens[i];
  }
  *crc_out = crc;
  return off;
}

// ---------------------------------------------------------------------------
// Vectored socket write: drain N buffers to a (possibly non-blocking) fd
// with writev, handling partial writes, EINTR, and EAGAIN (poll for
// writability).  Called with the GIL released, so the asyncio loop and
// codec threads keep running while the kernel drains multi-MB payloads.
// Returns total bytes written, or -errno on failure (-ETIMEDOUT when the
// fd stays unwritable for timeout_ms).
// ---------------------------------------------------------------------------

#include <sys/uio.h>
#include <poll.h>
#include <errno.h>

int64_t rf_writev_full(int fd, const uint8_t** bufs, const uint64_t* lens,
                       uint64_t n, int timeout_ms) {
  uint64_t i = 0;   // current buffer
  uint64_t off = 0; // offset into current buffer
  int64_t total = 0;
  while (i < n) {
    struct iovec iov[64];
    int cnt = 0;
    uint64_t j = i, o = off;
    while (j < n && cnt < 64) {
      if (lens[j] - o == 0) { j++; o = 0; continue; }
      iov[cnt].iov_base = const_cast<uint8_t*>(bufs[j]) + o;
      iov[cnt].iov_len = lens[j] - o;
      cnt++; j++; o = 0;
    }
    if (cnt == 0) break;  // only empty buffers remain
    ssize_t w = writev(fd, iov, cnt);
    if (w < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        struct pollfd p;
        p.fd = fd; p.events = POLLOUT; p.revents = 0;
        int pr = poll(&p, 1, timeout_ms);
        if (pr == 0) return -ETIMEDOUT;
        if (pr < 0 && errno != EINTR) return -static_cast<int64_t>(errno);
        continue;
      }
      return -static_cast<int64_t>(errno);
    }
    total += w;
    uint64_t adv = static_cast<uint64_t>(w);
    while (adv > 0) {
      uint64_t rem = lens[i] - off;
      if (adv >= rem) { adv -= rem; i++; off = 0; }
      else { off += adv; adv = 0; }
    }
    while (i < n && lens[i] == 0) i++;
  }
  return total;
}

}  // extern "C"
