// Native data plane for the rayfed_tpu wire transport.
//
// The reference gets its native transport from third-party wheels (gRPC
// C-core + Ray's C++ core, SURVEY §2.9); this framework's equivalent is
// first-party: the byte-level hot path of the DCN push transport lives
// here — checksums, frame assembly, and large scatter-gather copies —
// callable from Python via ctypes with the GIL released, so the asyncio
// loop and codec threads never serialize on big memcpys.
//
// Build: g++ -O3 -march=native -shared -fPIC wirecodec.cc -o libwirecodec.so
// (see build.py; pure-Python fallbacks exist for every entry point).

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32-C (Castagnoli), slicing-by-8.  Table generated at first use.
// ---------------------------------------------------------------------------

static uint32_t crc_table[8][256];
static bool crc_init_done = false;

static void crc_init() {
  const uint32_t poly = 0x82f63b78u;  // reflected CRC32-C polynomial
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    crc_table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = crc_table[0][i];
    for (int s = 1; s < 8; s++) {
      crc = (crc >> 8) ^ crc_table[0][crc & 0xff];
      crc_table[s][i] = crc;
    }
  }
  crc_init_done = true;
}

#if defined(__SSE4_2__)
#include <nmmintrin.h>
uint32_t rf_crc32c(uint32_t seed, const uint8_t* data, uint64_t len) {
  // Hardware CRC32-C (SSE4.2 crc32 instruction): ~1 byte/cycle/lane.
  uint32_t crc = ~seed;
  while (len && (reinterpret_cast<uintptr_t>(data) & 7)) {
    crc = _mm_crc32_u8(crc, *data++);
    len--;
  }
  uint64_t crc64 = crc;
  while (len >= 8) {
    uint64_t chunk;
    std::memcpy(&chunk, data, 8);
    crc64 = _mm_crc32_u64(crc64, chunk);
    data += 8;
    len -= 8;
  }
  crc = static_cast<uint32_t>(crc64);
  while (len--) crc = _mm_crc32_u8(crc, *data++);
  return ~crc;
}
#else
uint32_t rf_crc32c(uint32_t seed, const uint8_t* data, uint64_t len) {
  if (!crc_init_done) crc_init();
  uint32_t crc = ~seed;
  // Align to 8 bytes.
  while (len && (reinterpret_cast<uintptr_t>(data) & 7)) {
    crc = (crc >> 8) ^ crc_table[0][(crc ^ *data++) & 0xff];
    len--;
  }
  while (len >= 8) {
    uint64_t chunk;
    std::memcpy(&chunk, data, 8);
    chunk ^= crc;  // little-endian assumption (x86-64 / aarch64)
    crc = crc_table[7][chunk & 0xff] ^ crc_table[6][(chunk >> 8) & 0xff] ^
          crc_table[5][(chunk >> 16) & 0xff] ^
          crc_table[4][(chunk >> 24) & 0xff] ^
          crc_table[3][(chunk >> 32) & 0xff] ^
          crc_table[2][(chunk >> 40) & 0xff] ^
          crc_table[1][(chunk >> 48) & 0xff] ^
          crc_table[0][(chunk >> 56) & 0xff];
    data += 8;
    len -= 8;
  }
  while (len--) crc = (crc >> 8) ^ crc_table[0][(crc ^ *data++) & 0xff];
  return ~crc;
}
#endif  // __SSE4_2__

// ---------------------------------------------------------------------------
// Scatter-gather copy: assemble N source buffers into one destination.
// Returns total bytes copied.  Called with the GIL released.
// ---------------------------------------------------------------------------

uint64_t rf_gather_copy(uint8_t* dst, const uint8_t** srcs,
                        const uint64_t* lens, uint64_t n) {
  uint64_t off = 0;
  for (uint64_t i = 0; i < n; i++) {
    std::memcpy(dst + off, srcs[i], lens[i]);
    off += lens[i];
  }
  return off;
}

// Gather + checksum in one pass over the sources (saves a full re-read of
// the assembled buffer when both are needed).
uint64_t rf_gather_copy_crc(uint8_t* dst, const uint8_t** srcs,
                            const uint64_t* lens, uint64_t n,
                            uint32_t* crc_out) {
  uint64_t off = 0;
  uint32_t crc = 0;
  for (uint64_t i = 0; i < n; i++) {
    std::memcpy(dst + off, srcs[i], lens[i]);
    crc = rf_crc32c(crc, srcs[i], lens[i]);
    off += lens[i];
  }
  *crc_out = crc;
  return off;
}

// ---------------------------------------------------------------------------
// Frame prefix pack/unpack (mirrors wire.py _HEADER_STRUCT ">4sBBIQ").
// ---------------------------------------------------------------------------

static inline void put_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
static inline void put_be64(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; i++) p[i] = v >> (56 - 8 * i);
}
static inline uint32_t get_be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}
static inline uint64_t get_be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

void rf_pack_prefix(uint8_t* dst, uint8_t msg_type, uint8_t flags,
                    uint32_t hlen, uint64_t plen) {
  dst[0] = 'R'; dst[1] = 'F'; dst[2] = 'W'; dst[3] = '1';
  dst[4] = msg_type;
  dst[5] = flags;
  put_be32(dst + 6, hlen);
  put_be64(dst + 10, plen);
}

// Returns 0 on success, -1 on bad magic.
int rf_unpack_prefix(const uint8_t* src, uint8_t* msg_type, uint8_t* flags,
                     uint32_t* hlen, uint64_t* plen) {
  if (src[0] != 'R' || src[1] != 'F' || src[2] != 'W' || src[3] != '1')
    return -1;
  *msg_type = src[4];
  *flags = src[5];
  *hlen = get_be32(src + 6);
  *plen = get_be64(src + 10);
  return 0;
}

}  // extern "C"
