"""ctypes bindings for the native (C++) wire data plane.

Builds ``libwirecodec.so`` from :file:`wirecodec.cc` on first use if
missing (g++, ~1s) and exposes:

- :func:`crc32c` — CRC32-C checksum (slicing-by-8 in C++, GIL released)
- :func:`gather_copy` — assemble many buffers into one ``bytearray``,
  optionally computing the checksum in the same pass
- :func:`writev_full` — vectored socket write (writev + EAGAIN poll)
  with the GIL released: the send path drains multi-MB payloads to the
  kernel without copying into asyncio's transport buffer or blocking
  the event loop
- :func:`is_available` — False when no toolchain; every consumer keeps a
  pure-Python fallback (the transport works without native code, just
  slower on multi-MB payloads).

The reference's native layer is third-party (gRPC C-core, Ray core —
SURVEY §2.9); ours is first-party and scoped to the byte hot path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "wirecodec.cc")
_LIB = os.path.join(_HERE, "libwirecodec.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_build_lock = threading.Lock()


def _build() -> bool:
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB + ".tmp"]
    # Prefer the host ISA (hardware CRC32-C on x86); fall back to generic.
    for extra in (["-march=native"], []):
        cmd = base[:2] + extra + base[2:]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(_LIB + ".tmp", _LIB)
            return True
        except (OSError, subprocess.SubprocessError) as e:
            logger.debug("native build %s failed: %s", extra, e)
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    with _build_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        try:
            stale = not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            )
            if stale and not _build():
                return None
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            logger.debug("native wirecodec unavailable: %s", e)
            return None
        lib.rf_crc32c.restype = ctypes.c_uint32
        lib.rf_crc32c.argtypes = [ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint64]
        lib.rf_gather_copy.restype = ctypes.c_uint64
        lib.rf_gather_copy.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
        ]
        lib.rf_gather_copy_crc.restype = ctypes.c_uint64
        lib.rf_gather_copy_crc.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.rf_writev_full.restype = ctypes.c_int64
        lib.rf_writev_full.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
            ctypes.c_int,
        ]
        _lib = lib
        return lib


def is_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# Buffer address extraction (zero-copy where the buffer allows it)
# ---------------------------------------------------------------------------


def _byte_view(buf) -> memoryview:
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    if not mv.c_contiguous:  # pragma: no cover — callers pass contiguous bufs
        mv = memoryview(bytes(mv))
    return mv


def _addr_of(mv: memoryview, keepalive: List) -> int:
    """Address of a memoryview's first byte, zero-copy.

    Writable views go through ``ctypes.from_buffer``; readonly views
    (numpy views of jax arrays, ``bytes``) are wrapped by
    ``np.frombuffer`` — numpy accepts readonly buffers zero-copy and
    exposes the base address.  (An earlier version fell back to
    ``bytes(mv)`` here, which silently memcpy'd every readonly payload —
    at wire rates that one line halved push throughput.)
    """
    if not mv.readonly:
        c = (ctypes.c_char * mv.nbytes).from_buffer(mv)
        keepalive.append(c)
        return ctypes.addressof(c)
    import numpy as np

    arr = np.frombuffer(mv, dtype=np.uint8)
    keepalive.append(arr)
    return arr.ctypes.data


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def crc32c_multi(buffers: Sequence) -> int:
    """Chained CRC32-C over a sequence of buffers == crc of their concat."""
    crc = 0
    for buf in buffers:
        crc = crc32c(buf, seed=crc)
    return crc


def crc32c(data, seed: int = 0) -> int:
    """CRC32-C (Castagnoli) of a bytes-like object."""
    lib = _load()
    mv = _byte_view(data)
    if lib is not None:
        keepalive: List = []
        addr = _addr_of(mv, keepalive)
        return int(lib.rf_crc32c(seed, addr, mv.nbytes))
    return _crc32c_py(mv, seed)


_CRC32C_TABLE: Optional[List[int]] = None


def _crc32c_py(data, seed: int = 0) -> int:
    """Bitwise-compatible pure-Python fallback (slow; small inputs only)."""
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            table.append(crc)
        _CRC32C_TABLE = table
    crc = ~seed & 0xFFFFFFFF
    for b in bytes(data):
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
    return (~crc) & 0xFFFFFFFF


def writev_full(fd: int, buffers: Sequence, timeout_ms: int = 60_000) -> int:
    """Drain ``buffers`` to ``fd`` via C++ writev (GIL released).

    Handles partial writes and non-blocking sockets (EAGAIN → poll for
    writability, up to ``timeout_ms`` per stall).  Raises ``OSError`` on
    failure.  Callers must serialize writes per fd themselves (the
    transport client holds its per-connection write lock).
    """
    lib = _load()
    views = [_byte_view(b) for b in buffers]
    views = [mv for mv in views if mv.nbytes]
    if not views:
        return 0
    if lib is None:
        # Fallback: sequential write loop; mirrors the native path's
        # non-blocking handling (EAGAIN → poll for writability).
        import select

        total = 0
        for mv in views:
            off = 0
            while off < mv.nbytes:
                try:
                    off += os.write(fd, mv[off:])
                except (BlockingIOError, InterruptedError):
                    _, writable, _ = select.select([], [fd], [], timeout_ms / 1000)
                    if not writable:
                        raise OSError(110, "write stalled (poll timeout)")
            total += mv.nbytes
        return total
    n = len(views)
    src_arr = (ctypes.c_void_p * n)()
    len_arr = (ctypes.c_uint64 * n)()
    keepalive: List = []
    for i, mv in enumerate(views):
        src_arr[i] = _addr_of(mv, keepalive)
        len_arr[i] = mv.nbytes
    res = int(lib.rf_writev_full(fd, src_arr, len_arr, n, timeout_ms))
    if res < 0:
        raise OSError(-res, os.strerror(-res))
    return res


def gather_copy(buffers: Sequence, with_crc: bool = False):
    """Assemble ``buffers`` into one ``bytearray`` via native memcpy loop.

    With ``with_crc=True`` returns ``(bytearray, crc32c)`` computed in the
    same pass over the sources.  Pure-Python fallback joins + (slow) crc.
    """
    views = [_byte_view(b) for b in buffers]
    total = sum(mv.nbytes for mv in views)
    lib = _load()
    if lib is None:
        out = bytearray(total)
        off = 0
        for mv in views:
            out[off : off + mv.nbytes] = mv
            off += mv.nbytes
        return (out, _crc32c_py(out)) if with_crc else out

    out = bytearray(total)
    n = len(views)
    src_arr = (ctypes.c_void_p * n)()
    len_arr = (ctypes.c_uint64 * n)()
    keepalive: List = []
    for i, mv in enumerate(views):
        src_arr[i] = _addr_of(mv, keepalive)
        len_arr[i] = mv.nbytes
    dst = (ctypes.c_char * total).from_buffer(out)
    if with_crc:
        crc = ctypes.c_uint32(0)
        lib.rf_gather_copy_crc(
            ctypes.addressof(dst), src_arr, len_arr, n, ctypes.byref(crc)
        )
        return out, int(crc.value)
    lib.rf_gather_copy(ctypes.addressof(dst), src_arr, len_arr, n)
    return out
