"""Per-party Runtime — the single-controller replacement for Ray.

The reference spreads per-party state across a Ray cluster: config in the
GCS internal KV, proxies as named actors, a module-global seq counter.
Here everything a party owns lives on one :class:`Runtime` object:

- the deterministic sequence counter (:class:`~rayfed_tpu.context.GlobalContext`),
- the local :class:`~rayfed_tpu.executor.TaskExecutor`,
- the cross-party send/recv proxies (asyncio transport),
- the cleanup/send-watchdog,
- the party-local JAX device mesh for sharded compute.

Runtime resolution is thread-local with a process-wide default.  This is
what enables *multi-party-in-one-process simulation*: each simulated party
gets its own Runtime bound to its own threads, so all parties can share
the one local TPU chip while still exercising the real wire transport.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

from rayfed_tpu.config import ClusterConfig, JobConfig
from rayfed_tpu.context import GlobalContext
from rayfed_tpu.executor import ActorInstance, TaskExecutor

logger = logging.getLogger(__name__)

_tls = threading.local()
_process_default_runtime: Optional["Runtime"] = None
_default_lock = threading.Lock()


class Runtime:
    def __init__(
        self,
        cluster_config: ClusterConfig,
        job_config: JobConfig,
        max_workers: int = 16,
        mesh: Optional[Any] = None,
    ) -> None:
        self.cluster_config = cluster_config
        self.job_config = job_config
        self.global_context = GlobalContext()
        self.mesh = mesh  # party-local jax.sharding.Mesh (or None)
        self.executor = TaskExecutor(
            max_workers=max_workers,
            thread_name_prefix=f"rayfed-{cluster_config.current_party}",
            bind_runtime_fn=self._bind_to_current_thread,
        )
        self._actors: list[ActorInstance] = []
        self._actors_lock = threading.Lock()
        # Late-bound by api.init(): transport proxies + cleanup manager.
        self.send_proxy = None
        self.recv_proxy = None
        self.transport = None
        self.cleanup_manager = None
        self.sequence_tracer = None

    @property
    def party(self) -> str:
        return self.cluster_config.current_party

    def _bind_to_current_thread(self) -> None:
        _tls.runtime = self

    def register_actor(self, actor: ActorInstance) -> None:
        with self._actors_lock:
            self._actors.append(actor)

    def next_seq_id(self) -> int:
        return self.global_context.next_seq_id()

    def shutdown_actors(self) -> None:
        with self._actors_lock:
            actors, self._actors = self._actors, []
        for actor in actors:
            actor.kill()


def set_current_runtime(runtime: Optional[Runtime], process_default: bool = True):
    """Bind ``runtime`` for the current thread (and optionally the process)."""
    global _process_default_runtime
    _tls.runtime = runtime
    if process_default:
        with _default_lock:
            _process_default_runtime = runtime


def get_runtime() -> Runtime:
    runtime = getattr(_tls, "runtime", None)
    if runtime is None:
        runtime = _process_default_runtime
    if runtime is None:
        raise RuntimeError(
            "rayfed_tpu is not initialized in this thread; call fed.init() first"
        )
    return runtime


def get_runtime_or_none() -> Optional[Runtime]:
    runtime = getattr(_tls, "runtime", None)
    return runtime if runtime is not None else _process_default_runtime
